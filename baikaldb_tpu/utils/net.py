"""Cluster RPC plane: length-prefixed JSON messages over TCP.

The reference's inter-process contract is protobuf over brpc (SURVEY §5.8:
meta control / store data / MPP shuffle planes).  Here the MPP shuffle plane
is XLA collectives in-program, so the host side only needs a control/data
RPC for raft messages, heartbeats, and region ops — small, latency-tolerant
payloads.  JSON with tagged base64 for byte fields keeps the protocol
language-neutral and safe (no pickle: a store must not execute payloads).

Framing: 4-byte little-endian length + UTF-8 JSON body.

Reliability policy (the brpc retry discipline, chaos-hardened — see
docs/CHAOS.md):

- every ``RpcClient.call`` runs under ONE per-call deadline budget
  (``timeout``), propagated to the server as a ``deadline_ms`` header so
  handlers with internal waits (``rpc_propose``) never work past the
  caller's deadline; exhaustion raises the typed :class:`RpcTimeout`,
- transport failures AFTER an established connection retry with
  exponential backoff + full jitter inside the budget; connection-refused
  fails fast (peer rotation belongs to the caller's routing loop),
- non-idempotent methods carry an idempotency ``token``: the server keeps
  a bounded token -> response cache and replays the recorded response for
  a resend, so a retried write whose first copy executed with the
  response lost applies exactly once (metrics.rpc_dedup_hits),
- malformed frames are counted (``swallowed.rpc.bad_frame``) and drop the
  connection instead of silently killing the serving thread.

Failpoints (chaos/failpoint.py): ``rpc.send``, ``rpc.recv`` client-side,
``store.handler`` around server dispatch — ``panic`` there crashes the
daemon through ``RpcServer.on_panic``.
"""

from __future__ import annotations

import base64
import itertools
import json
import select
import socket
import struct
import threading
import time
import uuid
from collections import OrderedDict
from random import Random
from typing import Callable, Optional

from ..chaos import failpoint
from ..obs import progress, trace
from . import metrics
from .flags import FLAGS, define

define("rpc_retry_max", 3,
       "transport-failure resends per RPC call (established-connection "
       "failures only; all attempts share the call's deadline budget)")
define("rpc_backoff_ms", 20.0,
       "base of the exponential backoff between RPC retries; the actual "
       "sleep is full-jitter uniform(0, backoff), backoff doubling per "
       "attempt (capped at 1s)")

_BYTES_TAG = "__b64__"

# process-wide wire accounting (diagnostics + the pushdown transfer tests:
# a pushed fragment must move a small fraction of what a raw region pull
# moves).  Plain int adds under the GIL — close enough for accounting.
WIRE_STATS = {"sent_bytes": 0, "recv_bytes": 0}


def _enc(obj):
    if isinstance(obj, bytes):
        return {_BYTES_TAG: base64.b64encode(obj).decode()}
    if isinstance(obj, dict):
        return {k: _enc(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_enc(v) for v in obj]
    return obj


def _dec(obj):
    if isinstance(obj, dict):
        if set(obj) == {_BYTES_TAG}:
            return base64.b64decode(obj[_BYTES_TAG])
        return {k: _dec(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_dec(v) for v in obj]
    return obj


def send_msg(sock: socket.socket, obj) -> None:
    body = json.dumps(_enc(obj)).encode()
    WIRE_STATS["sent_bytes"] += 4 + len(body)
    sock.sendall(struct.pack("<I", len(body)) + body)


# frame-length sanity cap: a garbage 4-byte prefix (the most common
# malformed frame) must be rejected, not buffered — 0xFFFFFFFF would
# otherwise accumulate 4 GiB of attacker-controlled bytes before the
# JSON parse could ever fail.  64 MiB clears every real payload (full
# region scans included) by a wide margin.
MAX_FRAME_BYTES = 64 << 20


def recv_msg(sock: socket.socket):
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (n,) = struct.unpack("<I", header)
    if n > MAX_FRAME_BYTES:
        raise ValueError(f"frame length {n} exceeds cap {MAX_FRAME_BYTES}")
    body = _recv_exact(sock, n)
    if body is None:
        return None
    WIRE_STATS["recv_bytes"] += 4 + n
    return _dec(json.loads(body.decode()))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class RpcError(RuntimeError):
    pass


class RpcTimeout(RpcError):
    """The per-call deadline budget expired (connect, send, or receive).
    Typed so callers can tell 'the peer is slow/dead' from a handler-side
    failure; counted in metrics.rpc_timeouts."""


# the caller's propagated deadline, visible to the handler serving it
_BUDGET = threading.local()


def handler_deadline_s() -> Optional[float]:
    """Remaining seconds of the calling client's deadline budget (from the
    ``deadline_ms`` request header), or None when the caller sent none.
    Handlers with internal waits (rpc_propose) clamp to it so a daemon
    never keeps working past the caller's deadline."""
    until = getattr(_BUDGET, "until", None)
    if until is None:
        return None
    return max(0.0, until - time.monotonic())


class RpcServer:
    """Thread-per-connection RPC dispatch (the brpc service analog at test
    scale; the data plane lives on the TPU, not in this loop)."""

    # bounded idempotency-token -> response cache (exactly-once replay for
    # retried writes); 1024 entries comfortably covers every in-flight
    # retry window at test/bench scale
    DEDUPE_MAX = 1024

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._handlers: dict[str, Callable] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        # node label stamped on spans recorded while serving a traced RPC,
        # so a stitched frontend tree shows WHICH daemon did the work
        self.trace_node = f"{self.host}:{self.port}"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conns: set[socket.socket] = set()
        self._conns_mu = threading.Lock()
        self._dedupe: "OrderedDict[str, dict]" = OrderedDict()
        self._dedupe_mu = threading.Lock()
        # crash hook for the ``store.handler`` panic action: the owning
        # daemon installs its kill-9 analog (StoreServer.crash); default
        # is stop() — the server goes dark
        self.on_panic: Optional[Callable[[], None]] = None
        # telemetry-plane instrumentation (attach_metrics): per-method
        # handler latency histogram + in-flight gauge, recorded into the
        # OWNING daemon's registry; None = uninstrumented, zero cost
        self._m_handler = None
        self._m_inflight = None

    def register(self, name: str, fn: Callable) -> None:
        self._handlers[name] = fn

    def attach_metrics(self, registry) -> None:
        """Record per-method handler telemetry into ``registry`` (a
        daemon-scoped metrics.Registry): ``rpc_handler_ms`` histogram —
        the mergeable instrument, so the frontend's fleet aggregator can
        sum latency distributions across daemons — and ``rpc_inflight``
        gauge (requests currently executing, the brpc concurrency bvar)."""
        self._m_handler = registry.histogram_family(
            "rpc_handler_ms", ("method",))
        self._m_inflight = registry.gauge_family(
            "rpc_inflight", ("method",))

    def start(self) -> None:
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def stop(self, hard: bool = False) -> None:
        """Stop accepting.  ``hard`` additionally severs every LIVE
        connection, so in-flight handlers cannot ack after the stop — the
        kill-9 analog the chaos harness's daemon crash needs (a soft stop
        lets in-flight replies drain)."""
        self._stop.set()
        try:
            # shutdown BEFORE close: the accept thread blocks inside
            # accept() holding the socket's fd reference, so a bare
            # close() defers the actual fd teardown until one more
            # connection arrives — the port stays LISTENING and a
            # restarted daemon on the same address gets EADDRINUSE
            # forever.  shutdown() pops the blocked accept immediately.
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if hard:
            with self._conns_mu:
                conns = list(self._conns)
            for c in conns:
                try:
                    c.close()
                except OSError:
                    pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _panic(self) -> None:
        """Injected daemon crash (failpoint ``panic``): run the owner's
        crash hook, default to going dark."""
        cb = self.on_panic
        if cb is None:
            self.stop()
            return
        try:
            cb()
        except Exception:           # the crash hook itself must not throw
            metrics.count_swallowed("rpc.on_panic")

    def _serve_conn(self, conn: socket.socket) -> None:
        with self._conns_mu:
            self._conns.add(conn)
        try:
            self._serve_conn_loop(conn)
        finally:
            with self._conns_mu:
                self._conns.discard(conn)

    def _serve_conn_loop(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    req = recv_msg(conn)
                except OSError:
                    return
                except (ValueError, struct.error) as e:
                    # malformed frame: the stream is garbage from here —
                    # count it (operators must see a flood) and drop the
                    # connection instead of killing the thread silently
                    metrics.count_swallowed("rpc.bad_frame")
                    print(f"rpc {self.host}:{self.port}: malformed frame: "
                          f"{type(e).__name__}: {e}", flush=True)
                    return
                if req is None:
                    return
                method = req.get("method", "")
                try:
                    if failpoint.ENABLED and \
                            failpoint.hit("store.handler", method=method):
                        return          # drop: no reply, connection dies
                except failpoint.FailpointPanic:
                    self._panic()
                    return
                except failpoint.FailpointError as e:
                    try:
                        send_msg(conn, {"ok": False,
                                        "error": f"{type(e).__name__}: {e}"})
                        continue
                    except OSError:
                        return
                token = req.get("token")
                entry = None
                replay = False
                if token is not None:
                    with self._dedupe_mu:
                        entry = self._dedupe.get(token)
                        if entry is None:
                            # first copy: claim the token BEFORE executing
                            # so a retry arriving mid-execution waits for
                            # this outcome instead of re-executing (the
                            # double-execute race a completed-only cache
                            # still has)
                            entry = {"done": threading.Event(),
                                     "resp": None}
                            self._dedupe[token] = entry
                            if len(self._dedupe) > self.DEDUPE_MAX:
                                # evict COMPLETED entries only: dropping a
                                # claimed-but-executing token would let its
                                # retry re-execute — the exact race the
                                # cache exists to close
                                for tok in list(self._dedupe):
                                    if len(self._dedupe) <= self.DEDUPE_MAX:
                                        break
                                    if self._dedupe[tok]["done"].is_set():
                                        del self._dedupe[tok]
                        else:
                            replay = True
                    if replay:
                        metrics.rpc_dedup_hits.add(1)
                        budget = req.get("deadline_ms")
                        wait_s = min(30.0, float(budget) / 1e3
                                     if budget is not None else 10.0)
                        entry["done"].wait(wait_s)
                        resp = entry["resp"]
                        if resp is None:
                            resp = {"ok": False,
                                    "error": "RetryInProgress: first "
                                             "attempt still executing"}
                        try:
                            send_msg(conn, resp)
                        except OSError:
                            return
                        continue
                deadline_ms = req.get("deadline_ms")
                _BUDGET.until = None if deadline_ms is None else \
                    time.monotonic() + float(deadline_ms) / 1e3
                fn = self._handlers.get(method)
                wire = req.get("trace")
                buf = None

                def run():
                    if fn is None:
                        raise RpcError(f"unknown method {method!r}")
                    rem = handler_deadline_s()
                    if rem is not None and rem <= 0:
                        # the caller's budget is already gone (a delay
                        # failpoint or a slow queue ate it): don't do work
                        # nobody is waiting for
                        raise RpcError("DeadlineExceeded: caller budget "
                                       "exhausted before dispatch")
                    return {"ok": True,
                            "result": fn(**req.get("args", {}))}
                # only KNOWN methods mint metric children: the label value
                # is client-supplied, and an unknown-method probe must not
                # grow the registry one Gauge+Histogram row per spelling
                instrumented = self._m_inflight is not None \
                    and fn is not None
                if instrumented:
                    self._m_inflight.labels(method=method).add(1)
                t_h = time.perf_counter()
                try:
                    try:
                        if isinstance(wire, dict):
                            # caller's sampling decision propagates: record
                            # handler spans under ITS trace and ship them
                            # back for the frontend tree (obs/trace.py)
                            with trace.adopt(wire, f"serve.{method}",
                                             node=self.trace_node) as buf:
                                resp = run()
                        else:
                            resp = run()
                    except failpoint.FailpointPanic:
                        # a panic failpoint fired INSIDE the handler (e.g.
                        # binlog.append): the daemon crashes, no reply
                        self._panic()
                        return
                    except Exception as e:  # noqa: BLE001 — fault isolation per call
                        resp = {"ok": False,
                                "error": f"{type(e).__name__}: {e}"}
                finally:
                    if instrumented:
                        self._m_inflight.labels(method=method).add(-1)
                        self._m_handler.labels(method=method).observe(
                            (time.perf_counter() - t_h) * 1e3)
                if buf:
                    resp["trace_spans"] = list(buf)
                if entry is not None:
                    # publish the outcome: retries waiting on this token
                    # (and any later resend) replay it instead of
                    # re-executing
                    entry["resp"] = resp
                    entry["done"].set()
                try:
                    send_msg(conn, resp)
                except OSError:
                    return


# wall-clock retry jitter: deliberately NOT the chaos RNG — fault schedules
# are deterministic per failpoint (chaos/failpoint.py); backoff spacing is
# an anti-thundering-herd measure, not part of the replayed schedule
_JITTER = Random()
_TOKEN_TAG = uuid.uuid4().hex[:12]
_TOKENS = itertools.count(1)


def _new_token() -> str:
    """Process-unique idempotency token (uuid tag + counter: two frontends
    can never mint the same token, and tokens are cheap)."""
    return f"{_TOKEN_TAG}.{next(_TOKENS)}"


def _fp_rpc(point: str, **ctx) -> bool:
    """Client-seam failpoint evaluation honoring RpcClient's error
    contract: an injected ``return(msg)`` surfaces as RpcError — the type
    the routing/retry loops already absorb — never as a bare RuntimeError
    that would blow through them."""
    try:
        return failpoint.ENABLED and failpoint.hit(point, **ctx)
    except failpoint.FailpointError as e:
        raise RpcError(str(e)) from None


class RpcClient:
    """One persistent connection to a peer; reconnects on failure, retries
    transport failures with backoff + jitter inside one per-call deadline
    budget (``timeout``), and stamps non-idempotent calls with an
    idempotency token so resends dedupe at the server."""

    def __init__(self, address: str, timeout: float = 5.0):
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._mu = threading.Lock()

    def _connect(self, timeout: Optional[float] = None) -> socket.socket:
        s = socket.create_connection(
            (self.host, self.port),
            timeout=self.timeout if timeout is None else timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    # Methods idempotent by protocol: reads, health, and ops where a
    # duplicate is a no-op (raft messages dedupe by term/index; drops are
    # no-ops the second time).  These resend WITHOUT a token.  Everything
    # else (split_region_key, create_regions, propose, ...) carries an
    # idempotency token so the server's dedupe cache makes resends safe —
    # the first copy may have executed with the response lost, and a
    # duplicated split would mint a second child region with an identical
    # start key, bricking the table layout (ADVICE r03 low #3).
    _IDEMPOTENT = frozenset({
        "ping", "scan_raw", "txn_status", "region_size", "region_status",
        "instances", "table_regions", "heartbeat", "tso", "raft_msg",
        "drop_region", "drop_regions", "register_store", "cold_manifest",
        "exec_fragment", "fragment_execute", "metrics", "prometheus",
        "health",
        # AOT artifact tier: reads, plus puts/publishes that are
        # idempotent by construction (same key -> same bytes; the meta
        # manifest is last-writer-wins on identical content)
        "aot_fetch", "aot_fetch_xla", "aot_list", "aot_lookup",
        "aot_manifest", "aot_put", "aot_put_xla", "aot_publish",
        # fragment artifact tier: same discipline (same key -> same bytes)
        "frag_put", "frag_fetch",
    })

    # Fire-and-forget at the transport: raft IS its own retry protocol
    # (retransmit on tick, dedupe by term/index is only half the story —
    # a transport-level resend re-delivers STALE acks out of order, which
    # churns the leader's nextIndex into ever-longer suffix retransmits:
    # under a 25% injected response-drop the raft_msg traffic went
    # superlinear until writes starved).  A lost raft message is the case
    # the protocol is built for; the transport must not "help".
    _FIRE_AND_FORGET = frozenset({"raft_msg"})

    def call(self, method: str, **args):
        with self._mu, trace.span(f"rpc.{method}",
                                  peer=f"{self.host}:{self.port}"):
            # wire context captured INSIDE the rpc span: the daemon's
            # serve.* span nests under it, not beside it
            wire = trace.wire_context()
            req = {"method": method, "args": args}
            if wire is not None:
                req["trace"] = wire
            if method not in self._IDEMPOTENT:
                req["token"] = _new_token()
            resp = self._call_retrying(method, req)
            remote = resp.get("trace_spans")
            if remote:
                # the daemon's spans already carry this trace's ids:
                # stitch them under the rpc span that crossed the wire
                trace.absorb(remote)
            if not resp.get("ok"):
                raise RpcError(resp.get("error", "rpc failed"))
            return resp.get("result")

    def _call_retrying(self, method: str, req: dict) -> dict:
        """One logical call under the retry policy.  All attempts share one
        deadline budget (``self.timeout``) that also rides the request as
        the ``deadline_ms`` header; between attempts: exponential backoff
        with full jitter.  Connection-refused raises immediately (the
        caller's routing loop owns peer rotation — burning the budget on a
        dead peer would starve the live ones); a failure after an
        established connection retries, which the idempotency token makes
        safe for mutating methods."""
        deadline = time.monotonic() + self.timeout
        backoff = max(1.0, float(FLAGS.rpc_backoff_ms)) / 1e3
        retries = 0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                metrics.rpc_timeouts.add(1)
                raise RpcTimeout(
                    f"rpc {method} to {self.host}:{self.port}: deadline "
                    f"budget ({self.timeout}s) exhausted after "
                    f"{retries} retries")
            # KILL integration (obs/progress.py): a killed query must not
            # even send an idempotent call.  Non-idempotent (tokened)
            # methods are exempt end to end — interrupting a write whose
            # outcome is unknown would break exactly-once; they run to
            # their own deadline and the kill lands at the next statement
            # boundary.
            tok = progress.cancel_token()
            if tok is not None and tok.killed() \
                    and method in self._IDEMPOTENT:
                raise progress.QueryKilled()
            try:
                if self._sock is None:
                    self._sock = self._connect(remaining)
                self._sock.settimeout(remaining)
                if _fp_rpc("rpc.send", method=method,
                           peer=f"{self.host}:{self.port}"):
                    raise OSError("rpc.send dropped (failpoint)")
                req["deadline_ms"] = int(remaining * 1e3)
                send_msg(self._sock, req)
                if _fp_rpc("rpc.recv", method=method,
                           peer=f"{self.host}:{self.port}"):
                    # the server got (and executes) the request; its
                    # response is lost with the connection
                    raise OSError("rpc.recv dropped (failpoint)")
                resp = self._recv_cancellable(method, deadline)
                if resp is None:
                    raise OSError("connection closed")
                return resp
            except (socket.timeout, TimeoutError):
                self.close_locked()
                metrics.rpc_timeouts.add(1)
                raise RpcTimeout(
                    f"rpc {method} to {self.host}:{self.port} timed out "
                    f"({self.timeout}s budget, {retries} retries)") from None
            except OSError:
                conn_failed = self._sock is None    # _connect itself failed
                self.close_locked()
                if conn_failed or method in self._FIRE_AND_FORGET or \
                        retries >= int(FLAGS.rpc_retry_max):
                    raise
                retries += 1
                metrics.rpc_retries.add(1)
                trace.event("rpc.retry", method=method, attempt=retries,
                            peer=f"{self.host}:{self.port}")
                delay = _JITTER.uniform(0.0, backoff)
                if time.monotonic() + delay >= deadline:
                    metrics.rpc_timeouts.add(1)
                    raise RpcTimeout(
                        f"rpc {method} to {self.host}:{self.port}: deadline "
                        f"budget ({self.timeout}s) exhausted after "
                        f"{retries} retries") from None
                time.sleep(delay)
                backoff = min(backoff * 2.0, 1.0)

    def _recv_cancellable(self, method: str, deadline: float):
        """The response wait, interruptible by KILL for IDEMPOTENT methods
        only: poll the live query's cancel token between short select()
        slices, then do the normal blocking receive once bytes are
        pending.  select-before-recv (never a sliced recv) so a timeout
        can never strand a partial frame and desync the stream.  On kill
        the connection is severed — the response may still arrive later,
        and the next call must start on a clean stream."""
        tok = progress.cancel_token()
        if tok is None or method not in self._IDEMPOTENT:
            return recv_msg(self._sock)
        while True:
            if tok.killed():
                self.close_locked()
                raise progress.QueryKilled()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("deadline while polling for response")
            r, _, _ = select.select([self._sock], [], [],
                                    min(0.05, remaining))
            if r:
                return recv_msg(self._sock)

    def try_call(self, method: str, **args):
        """call() that returns None instead of raising on transport/handler
        failure (fan-out paths where a dead peer is expected).  Injected
        FailpointErrors count as failures too — chaos must not crash the
        tick/heartbeat loops that use this."""
        try:
            return self.call(method, **args)
        except (OSError, RpcError, failpoint.FailpointError):
            return None

    def close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._mu:
            self.close_locked()
