"""Version guards over the moving jax API surface.

The engine tracks two jax API migrations that landed between 0.4.x and
0.6.x; every call site goes through this module so the tree runs on both
sides of the break:

- ``shard_map`` moved from ``jax.experimental.shard_map`` to ``jax.shard_map``
  and renamed its replication-check kwarg ``check_rep`` -> ``check_vma``.
  The wrapper here accepts the new-world spelling (``check_vma``) and maps it
  onto whichever kwarg the installed jax understands.
- ``jnp.maximum`` grew numpy's ufunc methods (``.accumulate``) only in newer
  jax; ``lax.cummax``/``lax.cummin``/``lax.cumsum`` are the spellings that
  exist on both sides, so ``cummax`` routes through the ufunc when present
  and falls back to the lax primitive otherwise (identical lowering).
"""

from __future__ import annotations

import inspect

import jax
import jax.numpy as jnp
from jax import lax

try:  # jax>=0.6 promotes shard_map out of experimental
    _shard_map = jax.shard_map  # type: ignore[attr-defined]
except AttributeError:  # pragma: no cover — depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

# check_rep (jax<0.6) vs check_vma (jax>=0.6): same knob, renamed
_SM_KWARGS = frozenset(inspect.signature(_shard_map).parameters)
if "check_vma" in _SM_KWARGS:
    _CHECK_KW = "check_vma"
elif "check_rep" in _SM_KWARGS:
    _CHECK_KW = "check_rep"
else:  # pragma: no cover — future jax dropped the knob entirely
    _CHECK_KW = None


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` with the replication-check kwarg version-adapted."""
    kwargs = {} if _CHECK_KW is None else {_CHECK_KW: check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)


# which branch runs depends on the installed jax; both lower identically
if hasattr(jnp.maximum, "accumulate"):  # jnp ufunc methods (newer jax)
    def cummax(x, axis: int = 0):
        """Running maximum along ``axis`` (``jnp.maximum.accumulate``)."""
        return jnp.maximum.accumulate(x, axis=axis)
else:
    def cummax(x, axis: int = 0):
        """Running maximum along ``axis`` (``lax.cummax`` fallback)."""
        return lax.cummax(x, axis=axis)
