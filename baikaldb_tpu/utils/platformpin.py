"""Honor a CPU-backend request against the axon site hook.

The axon site hook (``PYTHONPATH=/root/.axon_site`` sitecustomize, active
when ``PALLAS_AXON_POOL_IPS`` is set) pins the platform with
``jax.config.update("jax_platforms", "axon,cpu")`` at interpreter start,
which OVERRIDES the ``JAX_PLATFORMS`` env var.  A process that was launched
with ``JAX_PLATFORMS=cpu`` therefore still initializes the accelerator
tunnel on first backend touch — and a wedged tunnel HANGS instead of
failing (VERDICT r03: three rounds of multichip rc=124 timeouts).

``honor_cpu_env()`` re-pins through the same config channel, and is the ONE
place this workaround lives (callers: tests/conftest.py, __graft_entry__).
It must run before any backend init in the process; ``jax.config.update``
after a backend has initialized succeeds silently with no effect.
"""

import json
import os
import subprocess
import sys
import time

# process-level probe verdict memo: one bench/watcher process must never
# pay the subprocess probe (or its retry window) twice
_PROBE_MEMO: dict = {}


def load_probe_verdict(cache_path: str,
                       max_age_s: float) -> dict | None:
    """The cross-process probe verdict ({"platform": str|None, "ts": ...})
    if one was saved within ``max_age_s``, else None.  A cached FAILURE is
    the valuable case: it lets the next bench process skip the multi-
    minute retry window a wedged tunnel costs (BENCH_r05: 4 x 75 s failed
    attempts before the CPU fallback)."""
    try:
        with open(cache_path) as f:
            v = json.load(f)
        if time.time() - float(v["ts"]) <= max_age_s:
            return v
    except (OSError, ValueError, KeyError, TypeError):
        pass
    return None


def save_probe_verdict(cache_path: str, platform: str | None) -> None:
    try:
        os.makedirs(os.path.dirname(cache_path), exist_ok=True)
        tmp = f"{cache_path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"platform": platform, "ts": time.time()}, f)
        os.replace(tmp, cache_path)
    except OSError:
        pass                                    # verdict cache is advisory


def probe_backend_once(timeout_s: float) -> str | None:
    """Initialise the JAX backend in a THROWAWAY subprocess; return the
    platform name, or None if init fails or hangs (wedged tunnel).  The
    subprocess is essential: a wedged tunnel hangs the initializing process,
    and that process must not be the caller.  Shared by bench.py and
    tools/tpu_watch.py so tunnel-health logic cannot diverge."""
    memo = _PROBE_MEMO.get("verdict")
    if memo is not None:
        return memo["platform"]
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None
    if r.returncode != 0:
        return None
    out = r.stdout.strip().splitlines()
    platform = out[-1] if out else None
    if platform is not None:
        # memoize success only: a failure may be transient within this
        # process's lifetime (the caller owns the retry policy)
        _PROBE_MEMO["verdict"] = {"platform": platform}
    return platform


def honor_cpu_env() -> bool:
    """If the environment requests a CPU JAX backend, re-pin jax's config to
    cpu (defeating the axon site hook's override).  Returns True iff pinned.
    No-op — and no jax import — when the env doesn't request cpu, so a
    real-TPU run is never affected."""
    if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu":
        return False
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True
