"""Honor a CPU-backend request against the axon site hook.

The axon site hook (``PYTHONPATH=/root/.axon_site`` sitecustomize, active
when ``PALLAS_AXON_POOL_IPS`` is set) pins the platform with
``jax.config.update("jax_platforms", "axon,cpu")`` at interpreter start,
which OVERRIDES the ``JAX_PLATFORMS`` env var.  A process that was launched
with ``JAX_PLATFORMS=cpu`` therefore still initializes the accelerator
tunnel on first backend touch — and a wedged tunnel HANGS instead of
failing (VERDICT r03: three rounds of multichip rc=124 timeouts).

``honor_cpu_env()`` re-pins through the same config channel, and is the ONE
place this workaround lives (callers: tests/conftest.py, __graft_entry__).
It must run before any backend init in the process; ``jax.config.update``
after a backend has initialized succeeds silently with no effect.
"""

import os
import subprocess
import sys


def probe_backend_once(timeout_s: float) -> str | None:
    """Initialise the JAX backend in a THROWAWAY subprocess; return the
    platform name, or None if init fails or hangs (wedged tunnel).  The
    subprocess is essential: a wedged tunnel hangs the initializing process,
    and that process must not be the caller.  Shared by bench.py and
    tools/tpu_watch.py so tunnel-health logic cannot diverge."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None
    if r.returncode != 0:
        return None
    out = r.stdout.strip().splitlines()
    return out[-1] if out else None


def honor_cpu_env() -> bool:
    """If the environment requests a CPU JAX backend, re-pin jax's config to
    cpu (defeating the axon site hook's override).  Returns True iff pinned.
    No-op — and no jax import — when the env doesn't request cpu, so a
    real-TPU run is never affected."""
    if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu":
        return False
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True
