"""Bulk importer (reference: src/tools/importer*.cpp ~4k LoC).

The reference's importer is job-driven: a JSON description names the target
table, source files, and format; jobs trigger when a DONE marker file
appears next to the data (importer.cpp:139-141 done-file polling), and a
"fast importer" bypasses the SQL write path by building SSTs directly.

TPU-build mapping:

- ``hot`` mode: rows go through the session ingest path — PK-checked,
  WAL/raft-durable, global indexes maintained (the plain importer).
- ``fast`` mode: rows land straight in the COLD tier — immutable Parquet
  segments on the external FS with the manifest raft-committed (the
  SST-building fast importer: no per-row consensus writes), then the
  column cache refreshes.  Requires a fleet-replicated table and a
  configured cold FS.
- ``watch_dir`` polls for ``<job>.done`` markers and runs the matching
  ``<job>.json`` job exactly once (renamed ``.imported`` after success).

CLI:  python -m baikaldb_tpu.tools.importer --job j.json [--watch DIR]
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field

import pyarrow as pa
import pyarrow.parquet as pq


@dataclass
class ImportJob:
    """One import job (the reference's JSON job description analog)."""
    table: str                       # "db.table" or bare name
    files: list[str] = field(default_factory=list)
    format: str = "csv"              # csv | parquet
    delimiter: str = ","
    mode: str = "hot"                # hot | fast
    columns: list[str] = field(default_factory=list)   # csv header override

    @classmethod
    def from_json(cls, path: str) -> "ImportJob":
        with open(path) as f:
            d = json.load(f)
        job = cls(table=d["table"], files=list(d.get("files", [])),
                  format=d.get("format", "csv"),
                  delimiter=d.get("delimiter", ","),
                  mode=d.get("mode", "hot"),
                  columns=list(d.get("columns", [])))
        base = os.path.dirname(os.path.abspath(path))
        job.files = [f if os.path.isabs(f) else os.path.join(base, f)
                     for f in job.files]
        return job


def _read_file(job: ImportJob, path: str, schema) -> pa.Table:
    from ..storage.column_store import schema_to_arrow

    arrow = schema_to_arrow(schema)
    if job.format == "parquet":
        t = pq.read_table(path)
        return t.select([c for c in arrow.names if c in t.column_names])
    from pyarrow import csv as pacsv

    names = job.columns or list(arrow.names)
    ropt = pacsv.ReadOptions(column_names=names)
    popt = pacsv.ParseOptions(delimiter=job.delimiter)
    copt = pacsv.ConvertOptions(
        column_types={f.name: arrow.field(f.name).type
                      for f in arrow if f.name in names},
        null_values=["", "\\N", "NULL"], strings_can_be_null=True)
    return pacsv.read_csv(path, read_options=ropt, parse_options=popt,
                          convert_options=copt)


def run_job(session, job: ImportJob) -> int:
    """Execute one job; returns rows imported."""
    db, _, name = job.table.rpartition(".")
    db = db or session.current_db
    info = session.db.catalog.get_table(db, name)
    store = session.db.stores.get(f"{db}.{name}")
    if store is None:
        store = session.db.stores[f"{db}.{name}"] = \
            session.db.make_store(info)
    total = 0
    if job.mode == "fast":
        return _run_fast(session, job, info, store)
    for path in job.files:
        t = _read_file(job, path, info.schema)
        session._ingest_arrow(store, t, check_dups=True)
        total += t.num_rows
    session.db.binlog.append(
        "insert", db, name,
        statement=f"IMPORT {len(job.files)} files", affected=total)
    if session.db.data_dir:
        # bulk rows are cold appends (durable at checkpoint, not per-row
        # WAL'd); job completion IS the durability point — exactly the
        # reference's importer contract (files fully ingested or not at all)
        session.db.checkpoint()
    return total


def _run_fast(session, job: ImportJob, info, store) -> int:
    """Fast import: build immutable cold segments directly (the reference's
    SST-building fast_importer, bypassing per-row consensus writes).  The
    rows get cluster-allocated rowids, land on the external FS as ONE
    segment per file, and the manifest entries raft-commit; the column
    cache then refreshes from cold+hot."""
    from ..raft.cluster import CMD_COLD
    from ..storage.coldfs import segment_bytes
    from ..storage.column_store import ROWID, schema_to_arrow
    from ..storage.replicated import ReplicatedRowTier

    tier = store.replicated
    if not isinstance(tier, ReplicatedRowTier):
        raise ValueError("fast import requires a fleet-replicated table")
    fs = session.db.cold_fs(required=True)
    if any(ix.kind in ("global", "global_unique")
           for ix in info.indexes):
        raise ValueError("fast import cannot maintain global indexes; "
                         "use mode=hot")
    row_arrow = schema_to_arrow(store._row_schema())
    total = 0
    with tier._mu:
        g = tier.groups[0]
        m = tier.metas[0]
        for path in job.files:
            t = _read_file(job, path, info.schema)
            if not t.num_rows:
                continue
            start = tier.alloc_rowids(t.num_rows)
            rows = t.to_pylist()
            for i, r in enumerate(rows):
                r[ROWID] = start + i
            seq = tier.alloc_rowids(1)
            seg = f"{tier.table_key}.r{m.region_id}.s{seq}.parquet"
            fs.put(seg, segment_bytes(rows, row_arrow))
            payload = json.dumps({"op": "add", "seq": int(seq),
                                  "file": seg,
                                  "watermark": -1}).encode()
            # watermark -1: a pure-cold segment evicts nothing hot
            if not g.propose_cmd(CMD_COLD, 0, payload):
                raise RuntimeError("fast import: manifest propose failed")
            total += t.num_rows
    # refresh the column cache: rebuild the store, which re-attaches the
    # tier and replays cold (incl. the new segments) + hot
    session.db.stores[f"{info.database}.{info.name}"] = \
        session.db.make_store(info)
    session.db.binlog.append(
        "insert", info.database, info.name,
        statement=f"FAST IMPORT {len(job.files)} files", affected=total)
    return total


def watch_dir(session, directory: str, poll_s: float = 1.0,
              max_rounds: int | None = None) -> int:
    """Done-file driver: a job runs when BOTH <name>.json and <name>.done
    exist (the data writer drops .done last — the reference's protocol for
    'the files are complete').  Successful jobs rename .done -> .imported.
    Returns jobs executed (runs until max_rounds when given, else forever).
    """
    done = 0
    rounds = 0
    while True:
        for f in sorted(os.listdir(directory)):
            if not f.endswith(".done"):
                continue
            stem = f[:-len(".done")]
            jpath = os.path.join(directory, stem + ".json")
            if not os.path.exists(jpath):
                continue
            job = ImportJob.from_json(jpath)
            run_job(session, job)
            os.replace(os.path.join(directory, f),
                       os.path.join(directory, stem + ".imported"))
            done += 1
        rounds += 1
        if max_rounds is not None and rounds >= max_rounds:
            return done
        time.sleep(poll_s)


def main() -> int:
    import argparse

    from ..exec.session import Database, Session

    ap = argparse.ArgumentParser()
    ap.add_argument("--job", help="job JSON path")
    ap.add_argument("--watch", help="directory to poll for .done markers")
    ap.add_argument("--data-dir", default="", help="durable Database dir")
    ap.add_argument("--meta", default="", help="cluster meta address")
    args = ap.parse_args()
    db = Database(data_dir=args.data_dir or None,
                  cluster=args.meta or None)
    s = Session(db)
    if args.job:
        n = run_job(s, ImportJob.from_json(args.job))
        print(json.dumps({"imported": n}))
        return 0
    if args.watch:
        watch_dir(s, args.watch)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
