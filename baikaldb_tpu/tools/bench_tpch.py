"""TPC-H wall-clock harness: all 22 queries end-to-end through Session.

Usage:  python -m baikaldb_tpu.tools.bench_tpch [--scale 0.05] [--mesh N]
Prints per-query first-run (compile incl.) and warm times plus a JSON
summary line (BASELINE config #5's measurement shape)."""

from __future__ import annotations

import argparse
import json
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.05)
    ap.add_argument("--mesh", type=int, default=0,
                    help="run distributed over an N-device mesh")
    ap.add_argument("--repeat", type=int, default=3)
    args = ap.parse_args()

    import jax

    from ..exec.session import Session
    from ..models import tpch

    mesh = None
    if args.mesh:
        from ..parallel.mesh import make_mesh

        mesh = make_mesh(args.mesh)
    s = Session(mesh=mesh)
    t0 = time.perf_counter()
    tpch.load_into(s, scale=args.scale, seed=42)
    load_s = time.perf_counter() - t0
    platform = jax.devices()[0].platform
    n_li = s.db.stores["default.lineitem"].num_rows
    print(f"# scale={args.scale} lineitem={n_li} platform={platform} "
          f"mesh={args.mesh or 1} load={load_s:.1f}s")

    results = {}
    total_warm = 0.0
    for name in sorted(tpch.QUERIES, key=lambda q: int(q[1:])):
        sql = tpch.QUERIES[name]
        t0 = time.perf_counter()
        s.query(sql)
        first = time.perf_counter() - t0
        warm = []
        for _ in range(args.repeat):
            t0 = time.perf_counter()
            s.query(sql)
            warm.append(time.perf_counter() - t0)
        w = min(warm)
        total_warm += w
        results[name] = round(w * 1e3, 2)
        print(f"{name:>4}: first {first * 1e3:8.1f} ms   warm {w * 1e3:8.1f} ms")
    print(json.dumps({"metric": f"tpch-22 warm total (SF{args.scale}, "
                                f"{platform}, mesh={args.mesh or 1})",
                      "value": round(total_warm * 1e3, 1), "unit": "ms",
                      "per_query_ms": results}))


if __name__ == "__main__":
    main()
