"""TPC-H wall-clock harness: all 22 queries end-to-end through Session.

Usage:  python -m baikaldb_tpu.tools.bench_tpch [--scale 0.05] [--mesh N]
                                                [--json]
Prints per-query first-run (compile incl.) and warm times plus a JSON
summary line (BASELINE config #5's measurement shape).  With ``--json``
every query emits ONE machine-readable line instead of the human row:
wall-clock (first + best warm), shuffle rounds per execution, and compiles
paid — the counters the MPP exchange v2 work moves.
"""

from __future__ import annotations

import argparse
import json
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.05)
    ap.add_argument("--mesh", type=int, default=0,
                    help="run distributed over an N-device mesh")
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument("--json", action="store_true",
                    help="one machine-readable JSON line per query "
                         "(wall-clock, shuffle rounds, compiles)")
    ap.add_argument("--force-shuffle", action="store_true",
                    help="repartition every sharded join input (the "
                         "pure-MPP regime: the per-edge baseline pays one "
                         "shuffle round per binary join — the config the "
                         "keyed exchange scheduler moves)")
    ap.add_argument("--mpp", action="store_true",
                    help="natural MPP regime: big joins shuffle "
                         "(broadcast size threshold 0, dense fast path "
                         "off), small dims broadcast by the mesh-ratio "
                         "rule and fuse as rider levels")
    ap.add_argument("--no-multiway", action="store_true",
                    help="disable the keyed exchange scheduler (the "
                         "per-edge chained-binary baseline, for diffing "
                         "with tools/bench_regress.py)")
    ap.add_argument("--queries", default="",
                    help="comma-separated subset (e.g. q5,q7,q8,q9); "
                         "empty = all 22")
    args = ap.parse_args()

    import jax

    from ..exec.session import Session
    from ..models import tpch
    from ..plan import distribute as _dist  # noqa: F401 — registers flags
    from ..plan import planner as _planner  # noqa: F401 — registers flags
    from ..utils import metrics
    from ..utils.flags import set_flag

    if args.force_shuffle:
        set_flag("mpp_force_shuffle", True)
        set_flag("dense_join_span_max", 0)
    if args.mpp:
        set_flag("mpp_broadcast_rows", 0)
        set_flag("dense_join_span_max", 0)
    if args.no_multiway:
        set_flag("multiway_join", False)

    mesh = None
    if args.mesh:
        from ..parallel.mesh import make_mesh

        mesh = make_mesh(args.mesh)
    s = Session(mesh=mesh)
    t0 = time.perf_counter()
    tpch.load_into(s, scale=args.scale, seed=42)
    load_s = time.perf_counter() - t0
    platform = jax.devices()[0].platform
    n_li = s.db.stores["default.lineitem"].num_rows
    header = (f"# scale={args.scale} lineitem={n_li} platform={platform} "
              f"mesh={args.mesh or 1} load={load_s:.1f}s")
    if args.json:
        print(json.dumps({"header": {"scale": args.scale, "lineitem": n_li,
                                     "platform": platform,
                                     "mesh": args.mesh or 1,
                                     "force_shuffle":
                                         bool(args.force_shuffle),
                                     "mpp": bool(args.mpp),
                                     "multiway": not args.no_multiway,
                                     "load_s": round(load_s, 1)}}))
    else:
        print(header)

    only = {q.strip() for q in args.queries.split(",") if q.strip()}
    results = {}
    total_warm = 0.0
    for name in sorted(tpch.QUERIES, key=lambda q: int(q[1:])):
        if only and name not in only:
            continue
        sql = tpch.QUERIES[name]
        c0 = metrics.xla_retraces.value
        t0 = time.perf_counter()
        s.query(sql)
        first = time.perf_counter() - t0
        first_compiles = metrics.xla_retraces.value - c0
        warm = []
        warm_rounds = 0
        warm_saved = 0
        warm_compiles = 0
        for _ in range(args.repeat):
            r0 = metrics.shuffle_rounds.value
            s0 = metrics.shuffle_rounds_saved.value
            c0 = metrics.xla_retraces.value
            t0 = time.perf_counter()
            s.query(sql)
            warm.append(time.perf_counter() - t0)
            warm_rounds = metrics.shuffle_rounds.value - r0
            warm_saved = metrics.shuffle_rounds_saved.value - s0
            warm_compiles += metrics.xla_retraces.value - c0
        w = min(warm)
        total_warm += w
        results[name] = round(w * 1e3, 2)
        if args.json:
            print(json.dumps({
                "query": name,
                "first_ms": round(first * 1e3, 2),
                "warm_ms": round(w * 1e3, 2),
                "shuffle_rounds": warm_rounds,
                "rounds_saved": warm_saved,
                "first_compiles": first_compiles,
                "warm_compiles": warm_compiles,
            }))
        else:
            print(f"{name:>4}: first {first * 1e3:8.1f} ms   "
                  f"warm {w * 1e3:8.1f} ms")
    print(json.dumps({"metric": f"tpch-22 warm total (SF{args.scale}, "
                                f"{platform}, mesh={args.mesh or 1})",
                      "value": round(total_warm * 1e3, 1), "unit": "ms",
                      "per_query_ms": results,
                      "multiway_joins_fused":
                          metrics.multiway_joins_fused.value,
                      "shuffle_rounds_saved":
                          metrics.shuffle_rounds_saved.value,
                      "shuffle_overflow_retries":
                          metrics.shuffle_overflow_retries.value}))


if __name__ == "__main__":
    main()
