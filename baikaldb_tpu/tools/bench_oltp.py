"""sysbench-analog OLTP harness (VERDICT r02 missing #6 / next #8).

The reference publishes one OLTP number: 92,287 QPS point-select (avg 2.77 ms,
p95 6.21 ms) from the patched sysbench lua suite over a 1-meta + 3-store +
N-frontend deploy (/root/reference/sysbench/sysbench.md:29-56,
sysbench/lua/oltp_common_baikaldb.lua).  This harness drives the same
workload shapes against this engine so the two can sit side by side:

- ``point_select`` — ``SELECT c FROM sbtest1 WHERE id = ?`` with uniformly
  random ids (the OLTP fast path: host-tier point lookup, no device program)
- ``insert``       — single-row autocommit INSERTs with fresh ids
- ``update``       — ``UPDATE sbtest1 SET k = k + 1 WHERE id = ?`` (the
  write path through the columnar merge + row tier)

Modes:
- ``--wire``  (default): a real MySQLServer on a loopback socket, N client
  threads speaking the binary protocol with prepared statements — the
  apples-to-apples sysbench topology, protocol cost included.
- ``--inproc``: N threads calling Session.execute directly — engine cost
  only (what the wire tax subtracts from).

Prints ONE JSON line: qps, latency avg/p95/p99 (ms), thread count, mode.
"""

from __future__ import annotations

import argparse
import json
import random
import string
import threading
import time

import pyarrow as pa

TABLE = "sbtest1"


def _pad(rng: random.Random, n: int) -> str:
    return "".join(rng.choices(string.ascii_lowercase, k=n))


def load(session, rows: int, seed: int = 7) -> None:
    """sysbench prepare: id PK, secondary-ish k, payload c/pad columns."""
    rng = random.Random(seed)
    session.execute(
        f"CREATE TABLE {TABLE} (id BIGINT, k BIGINT, c VARCHAR(120), "
        f"pad VARCHAR(60), PRIMARY KEY (id))")
    session.load_arrow(TABLE, pa.table({
        "id": list(range(1, rows + 1)),
        "k": [rng.randrange(1, rows + 1) for _ in range(rows)],
        "c": [_pad(rng, 32) for _ in range(rows)],
        "pad": [_pad(rng, 16) for _ in range(rows)],
    }))


def _percentile(sorted_ms: list[float], p: float) -> float:
    if not sorted_ms:
        return 0.0
    i = min(len(sorted_ms) - 1, int(p * len(sorted_ms)))
    return sorted_ms[i]


class _Worker(threading.Thread):
    def __init__(self, op, deadline: float):
        super().__init__(daemon=True)
        self.op = op
        self.deadline = deadline
        self.lat_ms: list[float] = []
        self.errors = 0

    def run(self):
        while time.perf_counter() < self.deadline:
            t0 = time.perf_counter()
            try:
                self.op()
            except Exception:
                self.errors += 1
                continue
            self.lat_ms.append((time.perf_counter() - t0) * 1e3)


def _run_threads(make_op, threads: int, seconds: float):
    deadline = time.perf_counter() + seconds
    ws = [_Worker(make_op(i), deadline) for i in range(threads)]
    t0 = time.perf_counter()
    for w in ws:
        w.start()
    for w in ws:
        w.join()
    wall = time.perf_counter() - t0
    lats = sorted(x for w in ws for x in w.lat_ms)
    n = len(lats)
    return {
        "queries": n,
        "errors": sum(w.errors for w in ws),
        "qps": round(n / wall, 1),
        "avg_ms": round(sum(lats) / n, 3) if n else 0.0,
        "p95_ms": round(_percentile(lats, 0.95), 3),
        "p99_ms": round(_percentile(lats, 0.99), 3),
        "max_ms": round(lats[-1], 3) if n else 0.0,
    }


def bench(mode: str = "point_select", threads: int = 8, seconds: float = 5.0,
          rows: int = 100_000, wire: bool = True) -> dict:
    from ..exec.session import Database, Session

    db = Database()
    setup = Session(db)
    load(setup, rows)
    # ids already taken; insert workload allocates above them, sharded by
    # worker so two threads never collide on a key
    next_id = [rows + 1 + i * 10_000_000 for i in range(threads)]

    if wire:
        from ..client.mysql_client import Connection
        from ..server.mysql_server import MySQLServer

        srv = MySQLServer(db, port=0)
        srv.start()

        def make_op(i: int):
            rng = random.Random(100 + i)
            conn = Connection("127.0.0.1", srv.port)
            if mode == "point_select":
                sid = conn.prepare(f"SELECT c FROM {TABLE} WHERE id = ?")
                return lambda: conn.execute(sid,
                                            (rng.randrange(1, rows + 1),))
            if mode == "insert":
                sid = conn.prepare(
                    f"INSERT INTO {TABLE} VALUES (?, ?, ?, ?)")

                def op():
                    next_id[i] += 1
                    conn.execute(sid, (next_id[i], rng.randrange(1, rows),
                                       "cccc", "pppp"))
                return op
            if mode == "update":
                sid = conn.prepare(
                    f"UPDATE {TABLE} SET k = k + 1 WHERE id = ?")
                return lambda: conn.execute(sid,
                                            (rng.randrange(1, rows + 1),))
            raise ValueError(f"unknown mode {mode!r}")

        try:
            out = _run_threads(make_op, threads, seconds)
        finally:
            srv.stop()
    else:
        def make_op(i: int):
            rng = random.Random(100 + i)
            s = Session(db)
            if mode == "point_select":
                return lambda: s.execute(
                    f"SELECT c FROM {TABLE} WHERE id = "
                    f"{rng.randrange(1, rows + 1)}")
            if mode == "insert":
                def op():
                    next_id[i] += 1
                    s.execute(f"INSERT INTO {TABLE} VALUES ({next_id[i]}, "
                              f"{rng.randrange(1, rows)}, 'cccc', 'pppp')")
                return op
            if mode == "update":
                return lambda: s.execute(
                    f"UPDATE {TABLE} SET k = k + 1 WHERE id = "
                    f"{rng.randrange(1, rows + 1)}")
            raise ValueError(f"unknown mode {mode!r}")

        out = _run_threads(make_op, threads, seconds)

    out.update({"mode": mode, "threads": threads, "rows": rows,
                "transport": "wire" if wire else "inproc",
                "ref_qps_point_select": 92287.54})
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", default="point_select",
                    choices=["point_select", "insert", "update"])
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--inproc", action="store_true",
                    help="skip the wire protocol; measure the engine only")
    args = ap.parse_args(argv)
    out = bench(args.mode, args.threads, args.seconds, args.rows,
                wire=not args.inproc)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
