"""sysbench-analog OLTP harness (VERDICT r02 missing #6 / next #8).

The reference publishes one OLTP number: 92,287 QPS point-select (avg 2.77 ms,
p95 6.21 ms) from the patched sysbench lua suite over a 1-meta + 3-store +
N-frontend deploy (/root/reference/sysbench/sysbench.md:29-56,
sysbench/lua/oltp_common_baikaldb.lua).  This harness drives the same
workload shapes against this engine so the two can sit side by side:

- ``point_select`` — ``SELECT c FROM sbtest1 WHERE id = ?`` with uniformly
  random ids (the OLTP fast path: host-tier point lookup, no device program)
- ``insert``       — single-row autocommit INSERTs with fresh ids
- ``update``       — ``UPDATE sbtest1 SET k = k + 1 WHERE id = ?`` (the
  write path through the columnar merge + row tier)

Modes:
- ``--wire``  (default): a real MySQLServer on a loopback socket, N client
  threads speaking the binary protocol with prepared statements — the
  apples-to-apples sysbench topology, protocol cost included.
- ``--inproc``: N threads calling Session.execute directly — engine cost
  only (what the wire tax subtracts from).

Prints ONE JSON line: qps, latency avg/p95/p99 (ms), thread count, mode.
"""

from __future__ import annotations

import argparse
import json
import random
import string
import threading
import time

import pyarrow as pa

TABLE = "sbtest1"


def _pad(rng: random.Random, n: int) -> str:
    return "".join(rng.choices(string.ascii_lowercase, k=n))


def load(session, rows: int, seed: int = 7, create: bool = True) -> None:
    """sysbench prepare: id PK, secondary-ish k, payload c/pad columns."""
    rng = random.Random(seed)
    if create:
        session.execute(
            f"CREATE TABLE {TABLE} (id BIGINT, k BIGINT, c VARCHAR(120), "
            f"pad VARCHAR(60), PRIMARY KEY (id))")
    session.load_arrow(TABLE, pa.table({
        "id": list(range(1, rows + 1)),
        "k": [rng.randrange(1, rows + 1) for _ in range(rows)],
        "c": [_pad(rng, 32) for _ in range(rows)],
        "pad": [_pad(rng, 16) for _ in range(rows)],
    }))


def _percentile(sorted_ms: list[float], p: float) -> float:
    if not sorted_ms:
        return 0.0
    i = min(len(sorted_ms) - 1, int(p * len(sorted_ms)))
    return sorted_ms[i]


class _Worker(threading.Thread):
    def __init__(self, op, deadline: float):
        super().__init__(daemon=True)
        self.op = op
        self.deadline = deadline
        self.lat_ms: list[float] = []
        self.errors = 0

    def run(self):
        while time.perf_counter() < self.deadline:
            t0 = time.perf_counter()
            try:
                self.op()
            except Exception:
                self.errors += 1
                continue
            self.lat_ms.append((time.perf_counter() - t0) * 1e3)


def _run_threads(make_op, threads: int, seconds: float):
    # build every op FIRST (connections, prepares, table attach): setup
    # cost must not eat the measured window
    ops = [make_op(i) for i in range(threads)]
    deadline = time.perf_counter() + seconds
    ws = [_Worker(op, deadline) for op in ops]
    t0 = time.perf_counter()
    for w in ws:
        w.start()
    for w in ws:
        w.join()
    wall = time.perf_counter() - t0
    lats = sorted(x for w in ws for x in w.lat_ms)
    n = len(lats)
    return {
        "queries": n,
        "errors": sum(w.errors for w in ws),
        "qps": round(n / wall, 1),
        "avg_ms": round(sum(lats) / n, 3) if n else 0.0,
        "p95_ms": round(_percentile(lats, 0.95), 3),
        "p99_ms": round(_percentile(lats, 0.99), 3),
        "max_ms": round(lats[-1], 3) if n else 0.0,
    }


def bench(mode: str = "point_select", threads: int = 8, seconds: float = 5.0,
          rows: int = 100_000, wire: bool = True) -> dict:
    from ..exec.session import Database, Session

    db = Database()
    setup = Session(db)
    load(setup, rows)
    # ids already taken; insert workload allocates above them, sharded by
    # worker so two threads never collide on a key
    next_id = [rows + 1 + i * 10_000_000 for i in range(threads)]

    if wire:
        from ..client.mysql_client import Connection
        from ..server.mysql_server import MySQLServer

        srv = MySQLServer(db, port=0)
        srv.start()

        def make_op(i: int):
            rng = random.Random(100 + i)
            conn = Connection("127.0.0.1", srv.port)
            if mode == "point_select":
                sid = conn.prepare(f"SELECT c FROM {TABLE} WHERE id = ?")
                return lambda: conn.execute(sid,
                                            (rng.randrange(1, rows + 1),))
            if mode == "insert":
                sid = conn.prepare(
                    f"INSERT INTO {TABLE} VALUES (?, ?, ?, ?)")

                def op():
                    next_id[i] += 1
                    conn.execute(sid, (next_id[i], rng.randrange(1, rows),
                                       "cccc", "pppp"))
                return op
            if mode == "update":
                sid = conn.prepare(
                    f"UPDATE {TABLE} SET k = k + 1 WHERE id = ?")
                return lambda: conn.execute(sid,
                                            (rng.randrange(1, rows + 1),))
            raise ValueError(f"unknown mode {mode!r}")

        try:
            out = _run_threads(make_op, threads, seconds)
        finally:
            srv.stop()
    else:
        def make_op(i: int):
            rng = random.Random(100 + i)
            s = Session(db)
            if mode == "point_select":
                return lambda: s.execute(
                    f"SELECT c FROM {TABLE} WHERE id = "
                    f"{rng.randrange(1, rows + 1)}")
            if mode == "insert":
                def op():
                    next_id[i] += 1
                    s.execute(f"INSERT INTO {TABLE} VALUES ({next_id[i]}, "
                              f"{rng.randrange(1, rows)}, 'cccc', 'pppp')")
                return op
            if mode == "update":
                return lambda: s.execute(
                    f"UPDATE {TABLE} SET k = k + 1 WHERE id = "
                    f"{rng.randrange(1, rows + 1)}")
            raise ValueError(f"unknown mode {mode!r}")

        out = _run_threads(make_op, threads, seconds)

    out.update({"mode": mode, "threads": threads, "rows": rows,
                "transport": "wire" if wire else "inproc",
                "ref_qps_point_select": 92287.54})
    return out


_DDL = (f"CREATE TABLE IF NOT EXISTS {TABLE} (id BIGINT, k BIGINT, "
        f"c VARCHAR(120), pad VARCHAR(60), PRIMARY KEY (id))")


def _client_proc_main(port: int, threads: int, seconds: float, rows: int):
    """One CLIENT PROCESS hammering one frontend (sysbench is a native
    multi-process client; a single CPython client would bottleneck on its
    own GIL before the servers did)."""
    from ..client.mysql_client import Connection

    def make_op(i: int):
        # port*1000 spacing keeps seeds collision-free across processes
        # for any per-process thread count below 1000
        rng = random.Random(1000 + port * 1000 + i)
        conn = Connection("127.0.0.1", port)
        conn.query(_DDL)       # attach the table in that frontend process
        sid = conn.prepare(f"SELECT c FROM {TABLE} WHERE id = ?")
        return lambda: conn.execute(sid, (rng.randrange(1, rows + 1),))

    out = _run_threads(make_op, threads, seconds)
    print(json.dumps(out), flush=True)


def bench_cluster(threads: int, seconds: float, rows: int,
                  meta_addr: str, ports: list[int]) -> dict:
    """point_select over N REAL frontend processes (the reference's
    N-baikaldb deploy), one client PROCESS per frontend.  Reads only —
    the remote tier is single-writer (rowid allocation; see
    RemoteRowTier)."""
    import subprocess
    import sys as _sys

    from ..exec.session import Database, Session
    from .deploy_cluster import _ENV, _repo_root

    loader = Session(Database(cluster=meta_addr))
    loader.execute(_DDL)
    tier = loader.db.cluster.tiers[f"default.{TABLE}"]
    existing = sum(1 for r in tier.scan_rows() if not r.get("__del"))
    if existing == 0:
        load(loader, rows, create=False)
    elif existing != rows:
        raise ValueError(
            f"cluster already holds {existing} sbtest rows (wanted {rows}):"
            f" restart the cluster or pass --rows {existing}")
    # pre-attach the table on every frontend OUTSIDE the timed window
    # (the first CREATE rebuilds that process's columnar cache)
    from ..client.mysql_client import Connection
    for p in ports:
        c = Connection("127.0.0.1", p)
        c.query(_DDL)
        c.close()
    per = max(1, threads // len(ports))
    procs = [subprocess.Popen(
        [_sys.executable, "-c",
         "from baikaldb_tpu.tools.bench_oltp import _client_proc_main; "
         f"_client_proc_main({p}, {per}, {seconds}, {rows})"],
        stdout=subprocess.PIPE, text=True, env=_ENV,
        cwd=_repo_root()) for p in ports]
    parts = [json.loads(pr.communicate(timeout=seconds + 120)[0])
             for pr in procs]
    lat_w = sum(p["queries"] for p in parts) or 1
    out = {
        "queries": sum(p["queries"] for p in parts),
        "errors": sum(p["errors"] for p in parts),
        "qps": round(sum(p["qps"] for p in parts), 1),
        "avg_ms": round(sum(p["avg_ms"] * p["queries"]
                            for p in parts) / lat_w, 3),
        # tail latencies are the max over client processes: UPPER BOUNDS
        # on the combined percentiles, not exact merges
        "p95_ms": max(p["p95_ms"] for p in parts),
        "p99_ms": max(p["p99_ms"] for p in parts),
        "max_ms": max(p["max_ms"] for p in parts),
        "mode": "point_select", "threads": per * len(ports), "rows": rows,
        "transport": f"wire x{len(ports)} frontends x{len(ports)} clients",
        "ref_qps_point_select": 92287.54,
    }
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", default="point_select",
                    choices=["point_select", "insert", "update"])
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--inproc", action="store_true",
                    help="skip the wire protocol; measure the engine only")
    ap.add_argument("--meta", default="",
                    help="cluster mode: meta daemon address")
    ap.add_argument("--ports", default="",
                    help="cluster mode: comma-separated frontend ports")
    args = ap.parse_args(argv)
    if args.meta and args.ports:
        if args.mode != "point_select" or args.inproc:
            ap.error("cluster mode (--meta/--ports) supports point_select "
                     "over the wire only (the remote tier is single-writer)")
        out = bench_cluster(args.threads, args.seconds, args.rows,
                            args.meta,
                            [int(p) for p in args.ports.split(",")])
    else:
        out = bench(args.mode, args.threads, args.seconds, args.rows,
                    wire=not args.inproc)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
