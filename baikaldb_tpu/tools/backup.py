"""Backup / restore tooling (reference: src/store/backup.cpp region SST
export/import + backup_tool/backup_import CLIs).

Single-node analog: every table dumps its regions as Parquet files plus a
catalog manifest (schema, indexes, options, versions); restore rebuilds a
Database from the manifest.  The per-region file layout is exactly what the
distributed tier will ship between stores.

CLI: python -m baikaldb_tpu.tools.backup dump|restore --dir PATH
(driven programmatically by tests and the importer).
"""

from __future__ import annotations

import json
import os

from ..exec.session import Database
from ..meta.catalog import IndexInfo
from ..storage.column_store import TableStore
from ..types import Field, LType, Schema


def dump(db: Database, directory: str) -> dict:
    os.makedirs(directory, exist_ok=True)
    manifest: dict = {"databases": {}, "tables": []}
    for dbname in db.catalog.databases():
        if dbname == "information_schema":
            continue
        manifest["databases"][dbname] = db.catalog.tables(dbname)
        for tname in db.catalog.tables(dbname):
            info = db.catalog.get_table(dbname, tname)
            entry = {
                "database": dbname,
                "name": tname,
                "version": info.version,
                "options": info.options,
                "fields": [[f.name, f.ltype.value, f.nullable]
                           for f in info.schema.fields],
                "indexes": [[ix.name, ix.kind, ix.columns]
                            for ix in info.indexes],
            }
            store = db.stores.get(f"{dbname}.{tname}")
            tdir = os.path.join(directory, dbname, tname)
            if store is not None:
                store.save_parquet(tdir)
                entry["data_dir"] = os.path.relpath(tdir, directory)
            manifest["tables"].append(entry)
    with open(os.path.join(directory, "MANIFEST.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    return manifest


def restore(directory: str) -> Database:
    with open(os.path.join(directory, "MANIFEST.json")) as f:
        manifest = json.load(f)
    db = Database()
    for dbname in manifest["databases"]:
        if dbname != "default":
            db.catalog.create_database(dbname, if_not_exists=True)
    for entry in manifest["tables"]:
        schema = Schema(tuple(Field(n, LType(t), nullable)
                              for n, t, nullable in entry["fields"]))
        indexes = [IndexInfo(n, k, cols) for n, k, cols in entry["indexes"]]
        info = db.catalog.create_table(entry["database"], entry["name"], schema,
                                       indexes, options=entry.get("options", {}))
        info.version = entry["version"]
        store = TableStore(info)
        db.stores[f"{entry['database']}.{entry['name']}"] = store
        if "data_dir" in entry:
            store.load_parquet(os.path.join(directory, entry["data_dir"]))
    return db


def main():  # pragma: no cover - thin CLI
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("action", choices=["dump", "restore"])
    ap.add_argument("--dir", required=True)
    args = ap.parse_args()
    if args.action == "restore":
        db = restore(args.dir)
        total = sum(s.num_rows for s in db.stores.values())
        print(f"restored {len(db.stores)} tables, {total} rows")
    else:
        raise SystemExit("dump requires an in-process Database; use the API")


if __name__ == "__main__":  # pragma: no cover
    main()
