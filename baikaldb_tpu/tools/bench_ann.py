"""ANN speedup benchmark: IVF candidates + exact re-rank vs brute force.

VERDICT r05 item #4 acceptance: recall@10 >= 0.95 vs exact on 1M x 128-d
with >5x speedup on CPU.  Prints ONE JSON line.

Run: python -m baikaldb_tpu.tools.bench_ann [--rows 1000000] [--dim 128]
     [--queries 32] [--k 10]
CPU: PYTHONPATH= JAX_PLATFORMS=cpu python -m baikaldb_tpu.tools.bench_ann
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--queries", type=int, default=32)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--nprobe", type=int, default=8)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from ..ops.vector import (brute_force_topk, ivf_search_host, kmeans,
                              pack_ivf)

    rng = np.random.RandomState(42)
    nc = max(64, int(np.sqrt(args.rows)))
    centers = rng.randn(nc, args.dim).astype(np.float32) * 4
    base = (centers[rng.randint(0, nc, args.rows)]
            + rng.randn(args.rows, args.dim).astype(np.float32) * 0.4)
    queries = (base[rng.randint(0, args.rows, args.queries)]
               + rng.randn(args.queries, args.dim).astype(np.float32) * 0.1)

    t0 = time.time()
    cent, assign = kmeans(base, nc, iters=6)
    train_s = time.time() - t0
    order, starts, counts, max_count = pack_ivf(base, assign,
                                                n_clusters=len(cent))

    qd = jnp.asarray(queries)
    bd = jnp.asarray(base)
    base_sorted = base[order]

    def timed(fn, reps=3):
        jax.block_until_ready(fn())            # compile / warm caches
        t0 = time.time()
        for _ in range(reps):
            out = fn()
            jax.block_until_ready(out)         # accepts any pytree
        return (time.time() - t0) / reps, out

    # per-QUERY timing on both sides: the SQL plane serves one SELECT at a
    # time, so batch-amortized exact numbers would overstate brute force
    def run_exact():
        return [brute_force_topk(qd[i:i + 1], bd, None, args.k, "l2",
                                 "f32") for i in range(args.queries)]

    def run_ivf():
        return [ivf_search_host(queries[i], base_sorted, None, cent,
                                starts, counts, args.k, args.nprobe, "l2",
                                norms_sorted=norms)
                for i in range(args.queries)]

    norms = (base_sorted * base_sorted).sum(1)
    exact_s, exact_out = timed(run_exact)
    ivf_s, ivf_out = timed(run_ivf)
    ei = np.stack([np.asarray(i)[0] for _s, i in exact_out])
    vi = [order[p] for _s, p in ivf_out]
    recall = float(np.mean([
        len(set(ei[i]) & set(vi[i])) / min(args.k, len(vi[i]))
        for i in range(args.queries)]))
    print(json.dumps({
        "metric": f"ANN IVF speedup ({args.rows}x{args.dim}, k={args.k}, "
                  f"nprobe={args.nprobe})",
        "value": round(exact_s / ivf_s, 2), "unit": "x vs exact",
        "recall_at_k": round(recall, 4),
        "exact_ms": round(exact_s * 1e3, 1),
        "ivf_ms": round(ivf_s * 1e3, 1),
        "train_s": round(train_s, 1),
        "platform": jax.devices()[0].platform,
    }))


if __name__ == "__main__":
    main()
