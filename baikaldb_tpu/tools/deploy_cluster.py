"""Mini-cluster deploy: 1 meta + N store daemons + 1 MySQL frontend, all
real processes on one host (the reference deployment shape,
/root/reference/sysbench/baikaldb_deploy_scripts/init.sh: baikalMeta +
3 baikalStore + baikaldb).

Usage:
    python -m baikaldb_tpu.tools.deploy_cluster [--stores 3] \
        [--base-port 9100] [--mysql-port 28000]

Prints one line per process and stays in the foreground; Ctrl-C tears the
cluster down.  ``spawn_cluster`` is the library entry the e2e test uses.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

from ..utils.net import RpcClient

_ENV = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _spawn(args: list[str]) -> subprocess.Popen:
    log_dir = os.environ.get("BK_CLUSTER_LOGS")
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        name = args[0].rsplit(".", 1)[-1] + "_" + \
            "_".join(a.replace(":", "_").replace("/", "_")
                     for a in args[1:] if not a.startswith("--"))
        out = open(os.path.join(log_dir, name + ".log"), "ab")
    else:
        out = subprocess.DEVNULL
    return subprocess.Popen([sys.executable, "-m"] + args, env=_ENV,
                            cwd=_repo_root(), stdout=out, stderr=out)


def _wait_ping(address: str, timeout: float = 15.0) -> None:
    deadline = time.monotonic() + timeout
    client = RpcClient(address, timeout=1.0)
    while time.monotonic() < deadline:
        if client.try_call("ping") is not None:
            client.close()
            return
        time.sleep(0.2)
    raise TimeoutError(f"no ping from {address}")


def spawn_cluster(n_stores: int = 3, base_port: int = 9100,
                  mysql_port: int = 0, n_mysql: int = 1,
                  aot_dir: str = "", cold_dir: str = ""):
    """-> (meta_address, {"meta", "stores", "mysql", "mysqls"}).
    mysql_port=0 skips frontends (tests drive Session directly);
    ``n_mysql`` > 1 spawns frontends on consecutive ports — the
    reference's N-baikaldb deploy (throughput scales per frontend
    process; see RemoteRowTier's single-WRITER note).  ``aot_dir`` /
    ``cold_dir`` plumb the daemons' fragment-artifact blob tier and
    cold-segment filesystem (per-store subdirectories, so daemons warm
    fragment programs from disk and fold their own cold tier in place)."""
    meta_addr = f"127.0.0.1:{base_port}"
    procs = {"meta": _spawn(["baikaldb_tpu.server.meta_server",
                             "--address", meta_addr,
                             "--peer-count", str(n_stores)]),
             "stores": [], "mysql": None, "mysqls": []}
    _wait_ping(meta_addr)
    for i in range(1, n_stores + 1):
        addr = f"127.0.0.1:{base_port + i}"
        cmd = ["baikaldb_tpu.server.store_server", "--store-id", str(i),
               "--address", addr, "--meta", meta_addr]
        if aot_dir:
            cmd += ["--aot-dir", os.path.join(aot_dir, f"store{i}")]
        if cold_dir:
            cmd += ["--cold-dir", os.path.join(cold_dir, f"store{i}")]
        procs["stores"].append(_spawn(cmd))
        _wait_ping(addr)
    if mysql_port and n_mysql > 0:
        for j in range(n_mysql):
            procs["mysqls"].append(_spawn(["baikaldb_tpu.server",
                                           "--port", str(mysql_port + j),
                                           "--meta", meta_addr]))
        procs["mysql"] = procs["mysqls"][0]
    return meta_addr, procs


def teardown(procs: dict) -> None:
    victims = [procs.get("meta")] + procs.get("mysqls", []) + \
        procs.get("stores", [])
    if procs.get("mysql") is not None and \
            procs["mysql"] not in procs.get("mysqls", []):
        victims.append(procs["mysql"])
    for p in victims:
        if p is not None and p.poll() is None:
            p.terminate()
    for p in victims:
        if p is not None:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--stores", type=int, default=3)
    ap.add_argument("--base-port", type=int, default=9100)
    ap.add_argument("--mysql-port", type=int, default=28000)
    ap.add_argument("--frontends", type=int, default=1,
                    help="MySQL frontends on consecutive ports")
    ap.add_argument("--aot-dir", default="",
                    help="fragment/AOT blob root (per-store subdirs)")
    ap.add_argument("--cold-dir", default="",
                    help="cold-segment FS root (per-store subdirs)")
    args = ap.parse_args()
    meta_addr, procs = spawn_cluster(args.stores, args.base_port,
                                     args.mysql_port,
                                     n_mysql=args.frontends,
                                     aot_dir=args.aot_dir,
                                     cold_dir=args.cold_dir)
    print(f"meta     @ {meta_addr} (pid {procs['meta'].pid})")
    for i, p in enumerate(procs["stores"], 1):
        print(f"store {i}  @ 127.0.0.1:{args.base_port + i} (pid {p.pid})")
    for j, p in enumerate(procs["mysqls"][1:], 1):
        print(f"mysql+{j}  @ 127.0.0.1:{args.mysql_port + j} (pid {p.pid})")
    if procs["mysql"] is not None:
        print(f"mysql    @ 127.0.0.1:{args.mysql_port} "
              f"(pid {procs['mysql'].pid})")
    print("cluster up — Ctrl-C to tear down", flush=True)

    def _stop(signum, frame):
        teardown(procs)
        sys.exit(0)

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    main()
