"""Background TPU-tunnel watcher: capture an on-chip bench whenever possible.

The axon tunnel to the single real TPU chip wedges for hours at a time
(VERDICT r03 weak #2: one probe window at round end lost the round's on-chip
number).  This watcher runs for the whole round: it probes the tunnel with a
cheap subprocess (a wedged tunnel HANGS, so the probe gets a hard timeout),
and whenever the tunnel is healthy it runs ``bench.py`` — whose successful
on-chip result is cached to ``.bench_cache/tpu_result.json`` and emitted by
``bench.py`` at round end even if the tunnel has wedged again by then.

Usage:  python -m baikaldb_tpu.tools.tpu_watch [--once]
Knobs:  TPU_WATCH_PROBE_S (default 600; wait between probes while unhealthy)
        TPU_WATCH_REFRESH_S (default 3600; wait after a successful capture)
        TPU_WATCH_PROBE_TIMEOUT (default 75)
        TPU_WATCH_BENCH_TIMEOUT (default 1800)
"""

import os
import subprocess
import sys
import time

from baikaldb_tpu.utils.platformpin import probe_backend_once as probe

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
LOG_DIR = os.path.join(REPO, ".bench_cache")


def _log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)


def capture_bench(timeout_s: float) -> bool:
    """Run bench.py against the live accelerator; its TPU result self-caches.
    Returns True iff an on-chip (non-cpu) result was produced."""
    env = dict(os.environ)
    # no CPU fallback from the watcher: if the accelerator dies mid-run we
    # want a clean failure, not a multi-minute CPU benchmark whose result
    # capture_bench would discard anyway
    env["BENCH_PROBE_WINDOW"] = "60"
    env["BENCH_NO_CPU_FALLBACK"] = "1"
    try:
        r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                           capture_output=True, text=True, cwd=REPO,
                           timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        _log("bench run timed out")
        return False
    tail = r.stdout.strip().splitlines()
    _log(f"bench rc={r.returncode}: {tail[-1] if tail else '<no output>'}")
    if r.returncode != 0 or not tail:
        return False
    import json

    try:
        result = json.loads(tail[-1])
    except ValueError:
        return False
    return result.get("platform") not in (None, "cpu") \
        and not result.get("cached")


def main() -> int:
    once = "--once" in sys.argv
    probe_s = float(os.environ.get("TPU_WATCH_PROBE_S", 600))
    refresh_s = float(os.environ.get("TPU_WATCH_REFRESH_S", 3600))
    probe_timeout = float(os.environ.get("TPU_WATCH_PROBE_TIMEOUT", 75))
    bench_timeout = float(os.environ.get("TPU_WATCH_BENCH_TIMEOUT", 1800))
    os.makedirs(LOG_DIR, exist_ok=True)
    while True:
        platform = probe(probe_timeout)
        if platform and platform != "cpu":
            _log(f"tunnel healthy ({platform}); capturing bench")
            ok = capture_bench(bench_timeout)
            _log(f"capture {'succeeded' if ok else 'failed'}")
            if once:
                return 0 if ok else 1
            time.sleep(refresh_s if ok else probe_s)
        else:
            _log(f"tunnel unhealthy (probe -> {platform!r})")
            if once:
                return 1
            time.sleep(probe_s)


if __name__ == "__main__":
    sys.exit(main())
