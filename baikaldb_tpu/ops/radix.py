"""Radix hash-partition machinery for the sort join (reference: the hash
join is the reference's default join, src/exec/join_node.cpp; this is its
TPU-shaped analog — VERDICT r03 next #4).

The sort join's cost on large non-dense builds is ONE global bitonic sort:
O(n log^2 n) compare-exchange stages.  Radix partitioning replaces it with

1. bucket = multiplicative-hash(key) >> (64 - log2 nb)   (one op per row),
2. a STABLE counting scatter into bucket-major order — a lax.scan over
   fixed-size row blocks carrying per-bucket counters, so the working set
   stays [block, nb] instead of [n, nb],
3. per-bucket sorts of ~n/nb rows as ONE batched sort over a [nb, width]
   matrix — log^2(width) stages instead of log^2(n),
4. probes hash to their bucket and binary-search only its width.

Static shapes throughout: buckets pad to a common ``width``; skew past it
reports the true max occupancy through the same retry-flag protocol as
join caps.  Everything is plain XLA (portable CPU/TPU); the per-bucket
sort is where a Pallas kernel slots in next.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

_MULT = 0x9E3779B97F4A7C15


def bucket_of(keys: jnp.ndarray, n_buckets: int) -> jnp.ndarray:
    """Multiplicative hash -> bucket id in [0, n_buckets); n_buckets must
    be a power of two (high bits: multiplicative hashing concentrates its
    quality there)."""
    if n_buckets < 2 or n_buckets & (n_buckets - 1):
        raise ValueError("n_buckets must be a power of two >= 2 (a shift "
                         "by 64 is implementation-defined)")
    shift = 64 - int(math.log2(n_buckets))
    h = (keys.astype(jnp.uint64) * jnp.uint64(_MULT)) >> jnp.uint64(shift)
    return h.astype(jnp.int32)


def stable_bucket_order(bucket: jnp.ndarray, n_buckets: int,
                        block: int = 4096) -> tuple:
    """-> (perm, offsets, counts): ``perm`` lists row indices bucket-major
    (stable within a bucket); offsets[b] = bucket b's start position.

    A scan over row blocks carries per-bucket counters; each step ranks its
    block's rows within their buckets via a [block, nb+1] one-hot cumsum —
    bounded memory, n/block scan steps."""
    n = bucket.shape[0]
    nb = n_buckets
    pad = (-n) % block
    b_pad = jnp.concatenate([bucket.astype(jnp.int32),
                             jnp.full((pad,), nb, jnp.int32)]) \
        if pad else bucket.astype(jnp.int32)
    blocks = b_pad.reshape(-1, block)

    def step(carry, blk):
        onehot = jax.nn.one_hot(blk, nb + 1, dtype=jnp.int32)
        before = jnp.cumsum(onehot, axis=0) - onehot   # earlier same-bucket
        rank_in_block = jnp.sum(before * onehot, axis=1).astype(jnp.int32)
        base = carry[blk]
        return ((carry + jnp.sum(onehot, axis=0)).astype(jnp.int32),
                (base + rank_in_block).astype(jnp.int32))

    counts, rank_blocks = jax.lax.scan(step,
                                       jnp.zeros(nb + 1, jnp.int32), blocks)
    rank = rank_blocks.reshape(-1)[:n]
    counts = counts[:nb]
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    dest = offsets[jnp.clip(bucket, 0, nb - 1)] + rank
    dest = jnp.where(bucket < nb, dest, n)             # pad bucket: drop
    perm = jnp.zeros(n + 1, jnp.int32).at[
        jnp.clip(dest, 0, n)].set(jnp.arange(n, dtype=jnp.int32))[:n]
    return perm, offsets, counts


def radix_build(keys: jnp.ndarray, dead: jnp.ndarray, n_buckets: int,
                width: int):
    """Partition + per-bucket sort of the BUILD side.

    -> (sort_src [nb, width], sort_keys [nb, width], needed): per-bucket
    key-sorted layout with the padding sentinel (dtype max) at each row's
    tail and sort_src = source row indices (len(keys) for padding).  Dead
    rows (NULL keys / sel-dead) route to an overflow bucket and never
    enter the matrix.  ``needed`` = true max LIVE bucket occupancy (the
    caller re-traces with width >= needed on skew overflow)."""
    nb = n_buckets
    n = keys.shape[0]
    bucket = jnp.where(dead, nb, bucket_of(keys, nb))
    perm, offsets, counts = stable_bucket_order(bucket, nb + 1)
    needed = jnp.max(counts[:nb])
    src = perm
    row_bucket = bucket[src]
    slot = jnp.arange(n, dtype=jnp.int32) - offsets[row_bucket]
    ok = (row_bucket < nb) & (slot < width)
    sentinel = jnp.iinfo(keys.dtype).max
    tgt = jnp.where(ok, row_bucket * width + slot, nb * width)  # scratch
    flat = jnp.full((nb * width + 1,), sentinel, keys.dtype).at[tgt].set(
        jnp.where(ok, keys[src], sentinel))
    srcflat = jnp.full((nb * width + 1,), n, jnp.int32).at[tgt].set(
        jnp.where(ok, src, n))
    mat = flat[:-1].reshape(nb, width)
    srcmat = srcflat[:-1].reshape(nb, width)
    sort_keys, sort_src = jax.lax.sort([mat, srcmat], num_keys=1)
    return sort_src, sort_keys, needed


def radix_probe(pk: jnp.ndarray, pdead: jnp.ndarray, sort_keys: jnp.ndarray,
                n_buckets: int):
    """-> (bucket, lo, hi): each probe key's match range within ITS
    bucket's sorted row.  Branchless binary search over the FLAT matrix
    with per-probe base offsets — O(log width) single-element gathers per
    probe, never a [n_probe, width] row materialization (that gather is
    what made the naive vmapped searchsorted blow up)."""
    width = sort_keys.shape[1]
    flat = sort_keys.reshape(-1)
    b = bucket_of(pk, n_buckets)
    base = b.astype(jnp.int64) * width

    def bsearch(right: bool):
        lo = jnp.zeros(pk.shape, jnp.int32)
        hi = jnp.full(pk.shape, width, jnp.int32)
        steps = int(width).bit_length()

        def body(_, lohi):
            lo, hi = lohi
            mid = (lo + hi) // 2
            v = flat[base + mid]
            go_right = (v <= pk) if right else (v < pk)
            return (jnp.where((lo < hi) & go_right, mid + 1, lo),
                    jnp.where((lo < hi) & ~go_right, mid, hi))

        lo, hi = jax.lax.fori_loop(0, steps + 1, body, (lo, hi))
        return lo

    lo = bsearch(False)
    hi = bsearch(True)
    lo = jnp.where(pdead, 0, lo).astype(jnp.int32)
    hi = jnp.where(pdead, 0, hi).astype(jnp.int32)
    return b, lo, hi
