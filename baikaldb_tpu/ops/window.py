"""Window function kernels (reference: src/expr/window_fn_call.cpp — rank /
row_number / ntile / lead / lag / aggregates; src/exec/window_node.cpp runs
them over sorted partitions).

TPU re-design: one stable multi-key sort puts rows in (partition, order)
order; every window function is then O(n) vectorized prefix math —
``cumsum`` + segment-start gathers — and results scatter back to the original
row order through the inverse permutation.  No per-partition loops: a million
tiny partitions cost the same as one big one.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp

from ..column.batch import Column, ColumnBatch
from ..types import LType
from ..utils.jax_compat import cummax
from .segments import seg_max, seg_min, seg_sum
from .sort import SortKey


@dataclass(frozen=True)
class WinSpec:
    op: str                      # row_number | rank | dense_rank | ntile |
    #                              lead | lag | first_value | last_value |
    #                              sum | count | avg | min | max (partition or
    #                              running)
    input: Optional[str] = None
    out_name: str = ""
    offset: int = 1              # lead/lag
    default: Optional[float] = None
    n: int = 1                   # ntile buckets
    running: bool = False        # ROWS UNBOUNDED PRECEDING .. CURRENT ROW
    # explicit frame (reference: window frame specs of window_fn_call.cpp):
    # ("rows"|"range", lo_bound, hi_bound); bounds as in expr/ast.WindowCall.
    # Executed as O(n log n) prefix/sparse-table math — no per-row loops.
    frame: Optional[tuple] = None


def window_compute(batch: ColumnBatch, partition_names: list[str],
                   order_keys: list[SortKey], specs: list[WinSpec]) -> ColumnBatch:
    """Append window-function columns (aligned to the batch's row order)."""
    n = len(batch)
    sel = batch.sel_mask()

    # ---- sort rows: partition keys (primary) then order keys; dead rows
    # last — one stable multi-key sort, shared with ORDER BY (ops/sort.py)
    from .sort import sort_permutation

    perm = sort_permutation(batch, [SortKey(p, True) for p in partition_names]
                            + list(order_keys))
    pkey_data = []
    for pn in partition_names:
        c = batch.column(pn)
        d = c.data
        if d.dtype == jnp.bool_:
            d = d.astype(jnp.int32)
        if c.validity is not None:
            d = jnp.where(c.validity, d, jnp.zeros((), d.dtype))
        pkey_data.append((c, d))

    inv = jnp.zeros(n, perm.dtype).at[perm].set(jnp.arange(n))
    sel_s = sel[perm]
    idx = jnp.arange(n)

    # partition boundaries (NULL keys canonicalized above)
    flags = idx == 0
    for c, d in pkey_data:
        ds = d[perm]
        flags = flags | (ds != jnp.roll(ds, 1))
        if c.validity is not None:
            v = c.validity[perm]
            flags = flags | (v != jnp.roll(v, 1))
    flags = flags | (sel_s != jnp.roll(sel_s, 1))

    # order-key tie boundaries (for rank/dense_rank)
    tie = flags
    for k in order_keys:
        c = batch.column(k.name)
        ds = c.data[perm]
        tie = tie | (ds != jnp.roll(ds, 1))
        if c.validity is not None:
            v = c.validity[perm]
            tie = tie | (v != jnp.roll(v, 1))

    start_idx = cummax(jnp.where(flags, idx, 0))
    row_number = idx - start_idx + 1
    sid = jnp.cumsum(flags.astype(jnp.int32)) - 1
    nseg = n + 1
    import jax

    seg_size = seg_sum(sel_s.astype(jnp.int64),
                       jnp.where(sel_s, sid, n),
                       num_segments=nseg)[:n]
    size_here = jnp.take(seg_size, jnp.clip(sid, 0, n - 1))
    end_idx = start_idx + jnp.maximum(size_here, 1) - 1

    names = list(batch.names)
    cols = list(batch.columns)
    fctx = None
    if any(s.frame for s in specs):
        # tie (peer) group bounds, shared by RANGE CURRENT ROW bounds
        tstart = cummax(jnp.where(tie, idx, 0))
        tid = jnp.cumsum(tie.astype(jnp.int32)) - 1
        tsize = seg_sum(sel_s.astype(jnp.int64),
                        jnp.where(sel_s, tid, n), num_segments=nseg)[:n]
        tsize_here = jnp.take(tsize, jnp.clip(tid, 0, n - 1))
        tend = tstart + jnp.maximum(tsize_here, 1) - 1
        fctx = {"tstart": tstart, "tend": tend, "sid": sid,
                "start": start_idx, "end": end_idx, "idx": idx,
                "sel_s": sel_s, "nseg": nseg, "order_keys": order_keys,
                "perm": perm}
    for s in specs:
        if s.frame is not None:
            res = _one_framed(s, batch, fctx)
        else:
            res = _one(s, batch, perm, idx, sel_s, flags, tie, sid,
                       start_idx, end_idx, row_number, size_here, nseg)
        if len(res) == 4:
            out_sorted, validity_sorted, lt, dct = res
        else:
            out_sorted, validity_sorted, lt = res
            dct = None
            if s.input is not None and lt is LType.STRING:
                dct = batch.column(s.input).dictionary
        data = jnp.take(out_sorted, inv)
        validity = None if validity_sorted is None else jnp.take(validity_sorted, inv)
        names.append(s.out_name)
        cols.append(Column(data, validity, lt, dct))
    return ColumnBatch(tuple(names), cols, batch.sel, batch.num_rows)


def _one(s: WinSpec, batch, perm, idx, sel_s, flags, tie, sid, start_idx,
         end_idx, row_number, size_here, nseg):
    import jax

    n = idx.shape[0]
    if s.op == "row_number":
        return row_number.astype(jnp.int64), None, LType.INT64
    if s.op == "rank":
        tstart = cummax(jnp.where(tie, idx, 0))
        return (tstart - start_idx + 1).astype(jnp.int64), None, LType.INT64
    if s.op == "dense_rank":
        c = jnp.cumsum(tie.astype(jnp.int64))
        c_start = jnp.take(c, start_idx)
        return c - c_start + 1, None, LType.INT64
    if s.op == "ntile":
        t = ((row_number - 1) * s.n) // jnp.maximum(size_here, 1) + 1
        return t.astype(jnp.int64), None, LType.INT64
    if s.op == "count" and s.input is None:
        # COUNT(*) OVER: all live rows count
        if s.running:
            return row_number.astype(jnp.int64), None, LType.INT64
        return size_here.astype(jnp.int64), None, LType.INT64

    c = batch.column(s.input)
    x = c.data[perm]
    xv = (c.valid_mask()[perm]) & sel_s

    if s.op in ("lead", "lag"):
        off = s.offset if s.op == "lead" else -s.offset
        src = idx + off
        in_range = (src >= 0) & (src < n)
        src_c = jnp.clip(src, 0, n - 1)
        same = jnp.take(sid, src_c) == sid
        ok = in_range & same & sel_s
        data = jnp.take(x, src_c)
        validity = jnp.take(xv, src_c) & ok
        if s.default is not None:
            if c.ltype is LType.STRING:
                if not isinstance(s.default, str):
                    raise ValueError("lead/lag default on a string column "
                                     "must be a string")
                # default becomes a code in an extended dictionary
                import numpy as np
                from ..column.dictionary import Dictionary
                values = np.union1d(c.dictionary.values,
                                    np.asarray([s.default], dtype=str))
                remap = jnp.asarray(np.searchsorted(values, c.dictionary.values)
                                    .astype(np.int32))
                data = jnp.where(data >= 0,
                                 jnp.take(remap, jnp.clip(data, 0, None),
                                          mode="clip"), data)
                dcode = int(np.searchsorted(values, s.default))
                data = jnp.where(ok, data, jnp.int32(dcode))
                validity = jnp.where(ok, validity, True)
                return data, validity, c.ltype, Dictionary(values)
            if isinstance(s.default, str):
                raise ValueError("string default on a non-string column")
            if isinstance(s.default, float) and not float(s.default).is_integer() \
                    and x.dtype.kind in "iu":
                # float default on int column: widen output to f64
                data = data.astype(jnp.float64)
                data = jnp.where(ok, data, jnp.float64(s.default))
                validity = jnp.where(ok, validity, True)
                return data, validity, LType.FLOAT64, None
            data = jnp.where(ok, data, jnp.asarray(s.default, x.dtype))
            validity = jnp.where(ok, validity, True)
        return data, validity, c.ltype
    if s.op == "first_value":
        return jnp.take(x, start_idx), jnp.take(xv, start_idx), c.ltype
    if s.op == "last_value":
        if s.running:
            # default ordered frame (UNBOUNDED PRECEDING..CURRENT ROW):
            # LAST_VALUE is the current row's value
            return x, xv, c.ltype
        return jnp.take(x, end_idx), jnp.take(xv, end_idx), c.ltype

    # aggregates (partition-wide or running)
    dt = jnp.int64 if c.ltype.is_integer else jnp.float64
    xa = jnp.where(xv, x.astype(dt), 0)
    ones = xv.astype(jnp.int64)
    if s.running:
        cs = jnp.cumsum(xa)
        cs0 = cs - xa
        run_sum = cs - jnp.take(cs0, start_idx)
        cn = jnp.cumsum(ones)
        run_cnt = cn - jnp.take(cn - ones, start_idx)
        if s.op == "sum":
            return run_sum, run_cnt > 0, LType.INT64 if dt == jnp.int64 else LType.FLOAT64
        if s.op == "count":
            return run_cnt, None, LType.INT64
        if s.op == "avg":
            return (run_sum.astype(jnp.float64) /
                    jnp.maximum(run_cnt, 1)), run_cnt > 0, LType.FLOAT64
        if s.op in ("min", "max"):
            # segmented running min/max: associative scan that resets at
            # partition boundaries (carries (segment id, running extreme))
            big = (jnp.iinfo if x.dtype.kind in "iu" else jnp.finfo)(x.dtype)
            ident = big.max if s.op == "min" else big.min
            xm = jnp.where(xv, x, ident)
            import jax.lax as lax

            def combine(a, b):
                asid, aval = a
                bsid, bval = b
                take_b = bsid != asid
                val = jnp.where(take_b, bval,
                                jnp.minimum(aval, bval) if s.op == "min"
                                else jnp.maximum(aval, bval))
                return (bsid, val)

            _, vals = lax.associative_scan(combine, (sid, xm))
            return vals, run_cnt > 0, c.ltype
        raise ValueError(f"unsupported running window aggregate {s.op}")
    # partition-wide
    gid = jnp.where(sel_s, sid, n)
    if s.op == "count":
        t = seg_sum(ones, gid, num_segments=nseg)[:n]
        return jnp.take(t, jnp.clip(sid, 0, n - 1)), None, LType.INT64
    if s.op == "sum":
        t = seg_sum(xa, gid, num_segments=nseg)[:n]
        tc = seg_sum(ones, gid, num_segments=nseg)[:n]
        sd = jnp.take(t, jnp.clip(sid, 0, n - 1))
        vc = jnp.take(tc, jnp.clip(sid, 0, n - 1)) > 0
        return sd, vc, LType.INT64 if dt == jnp.int64 else LType.FLOAT64
    if s.op == "avg":
        t = seg_sum(xa.astype(jnp.float64), gid, num_segments=nseg)[:n]
        tc = seg_sum(ones, gid, num_segments=nseg)[:n]
        sd = jnp.take(t, jnp.clip(sid, 0, n - 1))
        cd = jnp.take(tc, jnp.clip(sid, 0, n - 1))
        return sd / jnp.maximum(cd, 1), cd > 0, LType.FLOAT64
    if s.op in ("min", "max"):
        big = (jnp.iinfo if x.dtype.kind in "iu" else jnp.finfo)(x.dtype)
        ident = big.max if s.op == "min" else big.min
        xm = jnp.where(xv, x, ident)
        f = seg_min if s.op == "min" else seg_max
        t = f(xm, gid, num_segments=nseg)[:n]
        tc = seg_sum(ones, gid, num_segments=nseg)[:n]
        sd = jnp.take(t, jnp.clip(sid, 0, n - 1))
        vc = jnp.take(tc, jnp.clip(sid, 0, n - 1)) > 0
        return sd, vc, c.ltype
    raise ValueError(f"unsupported window op {s.op}")


def _first_true(a, b, pred_at, n: int):
    """Vectorized monotone binary search: per row, the smallest j in
    [a, b+1) with pred_at(j) True (b+1 when none).  pred must be monotone
    (False..False True..True) over each row's range — the frame-bound
    invariant over (partition, order)-sorted values."""
    lo, hi = a, b + 1
    for _ in range(max(n, 2).bit_length() + 1):
        cont = lo < hi
        mid = (lo + hi) >> 1
        p = pred_at(jnp.clip(mid, 0, n - 1))
        hi = jnp.where(cont & p, mid, hi)
        lo = jnp.where(cont & ~p, mid + 1, lo)
    return lo


def _sparse_table(xm, combine, n: int):
    """Doubling (sparse) table for O(1) range min/max queries: level k
    holds combine over [i, i+2^k) (clamped).  n log n memory, built with
    static shapes at trace time."""
    levels = [xm]
    shift = 1
    while shift < n:
        prev = levels[-1]
        nxt = jnp.concatenate([combine(prev[:n - shift], prev[shift:]),
                               prev[n - shift:]])
        levels.append(nxt)
        shift *= 2
    return jnp.stack(levels)              # (K+1, n)


def _range_query(table, combine_take, lo, hi, n: int):
    """combine over [lo, hi] via two overlapping power-of-two blocks."""
    length = jnp.maximum(hi - lo + 1, 1)
    k = jnp.log2(length.astype(jnp.float64)).astype(jnp.int32)
    k = jnp.clip(k, 0, table.shape[0] - 1)
    flat = table.reshape(-1)
    left = jnp.take(flat, k * n + jnp.clip(lo, 0, n - 1))
    right_pos = hi - (1 << k.astype(jnp.int64)) + 1
    right = jnp.take(flat, k * n + jnp.clip(right_pos, 0, n - 1))
    return combine_take(left, right)


def _one_framed(s: WinSpec, batch, fctx):
    """Aggregates / first_value / last_value over an explicit ROWS or
    RANGE frame (reference: src/exec/window_node.cpp frame execution).
    Per-row frame bounds [lo, hi] come from clamped index arithmetic
    (ROWS) or vectorized binary search over the single order key (RANGE
    n PRECEDING/FOLLOWING); aggregation is prefix-sum differences, with a
    sparse table for min/max — no per-partition loops."""
    idx = fctx["idx"]
    n = idx.shape[0]
    start_idx, end_idx = fctx["start"], fctx["end"]
    tstart, tend = fctx["tstart"], fctx["tend"]
    sid, sel_s, nseg = fctx["sid"], fctx["sel_s"], fctx["nseg"]
    perm = fctx["perm"]
    unit, lo_b, hi_b = s.frame

    def rows_bound(b, is_lo):
        if b == ("up",):
            return start_idx
        if b == ("uf",):
            return end_idx
        if b == ("c",):
            return idx
        off = int(b[1])
        return idx - off if b[0] == "p" else idx + off

    def range_bound(b, is_lo):
        if b == ("up",):
            return start_idx
        if b == ("uf",):
            return end_idx
        if b == ("c",):
            # RANGE CURRENT ROW means the current row's PEER group
            return tstart if is_lo else tend
        # n PRECEDING / n FOLLOWING over the single numeric order key
        ks = fctx["order_keys"]
        if len(ks) != 1:
            raise ValueError("RANGE n PRECEDING/FOLLOWING needs exactly "
                             "one ORDER BY key")
        oc = batch.column(ks[0].name)
        if oc.ltype is LType.STRING:
            raise ValueError("RANGE frames need a numeric or temporal "
                             "ORDER BY key")
        asc = ks[0].asc
        ov = oc.data[perm]
        ovalid = oc.valid_mask()[perm] & sel_s
        delta = b[1]
        dt = jnp.float64 if (ov.dtype.kind == "f"
                             or isinstance(delta, float)) else jnp.int64
        sv = ov.astype(dt)
        sv = sv if asc else -sv               # ascending in sort order
        # the order key's non-NULL run inside each partition (NULL rows
        # are peers of each other only; their frame is their peer group)
        first_valid = jnp.take(
            seg_min(jnp.where(ovalid, idx, n),
                    jnp.where(sel_s, sid, n), num_segments=nseg)[:n],
            jnp.clip(sid, 0, n - 1))
        last_valid = jnp.take(
            seg_max(jnp.where(ovalid, idx, -1),
                    jnp.where(sel_s, sid, n), num_segments=nseg)[:n],
            jnp.clip(sid, 0, n - 1))
        # target in ascending sv space: PRECEDING = -delta, FOLLOWING = +d;
        # the search DIRECTION comes from which end of the frame this
        # bound is — lo wants the first index >= target, hi the last
        # index <= target (they differ for p-as-hi / f-as-lo frames)
        d = jnp.asarray(delta, dt)
        target = sv - d if b[0] == "p" else sv + d
        if is_lo:
            pos = _first_true(first_valid, last_valid,
                              lambda j: jnp.take(sv, j) >= target, n)
        else:
            pos = _first_true(first_valid, last_valid,
                              lambda j: jnp.take(sv, j) > target, n) - 1
        # NULL-ordered rows: peer-group frame
        return jnp.where(ovalid, pos, tstart if is_lo else tend)

    bound = rows_bound if unit == "rows" else range_bound
    lo = jnp.maximum(bound(lo_b, True), start_idx)
    hi = jnp.minimum(bound(hi_b, False), end_idx)
    nonempty = (hi >= lo) & sel_s
    lo_c = jnp.clip(lo, 0, n - 1)
    hi_c = jnp.clip(hi, 0, n - 1)

    if s.op == "count" and s.input is None:
        return (jnp.where(nonempty, hi - lo + 1, 0).astype(jnp.int64),
                None, LType.INT64)

    c = batch.column(s.input)
    x = c.data[perm]
    xv = (c.valid_mask()[perm]) & sel_s

    if s.op == "first_value":
        return (jnp.take(x, lo_c), jnp.take(xv, lo_c) & nonempty, c.ltype)
    if s.op == "last_value":
        return (jnp.take(x, hi_c), jnp.take(xv, hi_c) & nonempty, c.ltype)

    dt = jnp.int64 if c.ltype.is_integer else jnp.float64
    xa = jnp.where(xv, x.astype(dt), 0)
    ones = xv.astype(jnp.int64)
    cs = jnp.cumsum(xa)
    cn = jnp.cumsum(ones)

    def span(prefix):
        head = jnp.take(prefix, hi_c)
        tail = jnp.where(lo > 0, jnp.take(prefix, jnp.clip(lo - 1, 0, n - 1)),
                         jnp.zeros((), prefix.dtype))
        return jnp.where(nonempty, head - tail, 0)

    cnt = span(cn)
    if s.op == "count":
        return cnt, None, LType.INT64
    if s.op == "sum":
        return (span(cs), cnt > 0,
                LType.INT64 if dt == jnp.int64 else LType.FLOAT64)
    if s.op == "avg":
        return (span(cs).astype(jnp.float64) / jnp.maximum(cnt, 1),
                cnt > 0, LType.FLOAT64)
    if s.op in ("min", "max"):
        big = (jnp.iinfo if x.dtype.kind in "iu" else jnp.finfo)(x.dtype)
        ident = big.max if s.op == "min" else big.min
        xm = jnp.where(xv, x, ident)
        comb = jnp.minimum if s.op == "min" else jnp.maximum
        # frames anchored at a partition edge (the default frame shape)
        # use an O(n) segmented scan + gather; the n-log-n sparse table is
        # only built when BOTH bounds slide
        if lo_b == ("up",):
            vals = jnp.take(_seg_running(xm, sid, comb), hi_c)
        elif hi_b == ("uf",):
            vals = jnp.take(
                _seg_running(xm[::-1], sid[::-1], comb)[::-1], lo_c)
        else:
            table = _sparse_table(xm, comb, n)
            vals = _range_query(table, comb, lo_c, hi_c, n)
        return vals, (cnt > 0) & nonempty, c.ltype
    raise ValueError(f"unsupported framed window op {s.op}")


def _seg_running(xm, sid, comb):
    """Running min/max from each segment's start: associative scan that
    resets at segment boundaries (same shape as the running path in
    _one)."""
    import jax.lax as lax

    def combine(a, b):
        asid, aval = a
        bsid, bval = b
        return (bsid, jnp.where(bsid != asid, bval, comb(aval, bval)))

    _, vals = lax.associative_scan(combine, (sid, xm))
    return vals
