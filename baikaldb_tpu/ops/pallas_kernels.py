"""Hand-written Pallas TPU kernels for mid-cardinality dense group-by.

Three lowerings cover the dense group-by (measured on v5e, 100M rows):

- ``num_groups <= 512``: XLA fused select+reduce (ops/segments.py) — one
  bandwidth-bound pass, ~1.5ms per segment.
- ``512 < num_groups <= PALLAS_MAX_GROUPS``: THESE kernels — the one-hot
  lives in VMEM as an MXU operand, so cost grows ~4x slower with group count
  than the select+reduce (~200ms at 512 groups where select+reduce takes
  ~850ms).
- beyond: scatter / sort strategies.

Mosaic constraints discovered on real hardware (every one of these failed
the remote compile until restructured):
- no 1-D intermediates: a ``(R,128)`` tile cannot reshape/broadcast through
  a flat ``(R*128,)`` vector; the one-hot is built per sublane-row from a
  ``(128, R)`` transpose instead, and each row's partials go to a distinct
  out_ref sublane.
- no 64-bit types anywhere in the traced kernel — the enclosing program
  runs in jax x64 mode, so the launcher traces under ``enable_x64(False)``.
- ``precision=HIGHEST`` is IGNORED by the Mosaic dot: f32 operands truncate
  to bf16 (relative error ~2^-8 per product).  Values are split into three
  bf16-exact components (8+8+8 significand bits) and contracted separately
  — products against a 0/1 one-hot are then exact; a Kahan accumulator row
  in VMEM scratch compensates the cross-step f32 adds.

The public entry points pad rows to full blocks with out-of-range codes
(their one-hot rows are all zero) and fall back to the XLA lowering off-TPU
or when Pallas is unavailable; ``interpret=True`` runs the same kernels on
CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # Pallas is part of jax; guard for stripped builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    PALLAS_AVAILABLE = False

try:
    from jax._src.config import enable_x64 as _x64_scope  # context manager
except Exception:  # pragma: no cover
    import contextlib

    def _x64_scope(_):
        return contextlib.nullcontext()

LANE = 128
R_BLOCK = 8                  # sublane rows per grid step = out_ref sublanes
PALLAS_MAX_GROUPS = 4096

_BIG = 3.4e38                # python float (a jnp constant would be captured
#                              by the kernel closure, which pallas_call rejects)


def _bf16_split3(v):
    """Split f32 lanes into three bf16-exact f32 components (v = a+b+c).

    The Mosaic dot truncates f32 operands to bf16; contracting each
    component separately keeps every product against a 0/1 one-hot exact."""
    a = v.astype(jnp.bfloat16).astype(jnp.float32)
    r = v - a
    b = r.astype(jnp.bfloat16).astype(jnp.float32)
    c = r - b
    return a, b, c


def _kahan_add(o_ref, comp_ref, row, crow, delta):
    """out[row] += delta, compensation tracked in scratch row ``crow``."""
    y = delta - comp_ref[crow:crow + 1, :]
    t = o_ref[row:row + 1, :] + y
    comp_ref[crow:crow + 1, :] = (t - o_ref[row:row + 1, :]) - y
    o_ref[row:row + 1, :] = t


def _sum_kernel(g_ref, v_ref, o_ref, comp_ref, *, ng: int):
    """counts -> o[0:8], sums -> o[8:16] (one sublane per block row)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        o_ref[:, :] = jnp.zeros_like(o_ref)
        comp_ref[:, :] = jnp.zeros_like(comp_ref)

    it = jax.lax.broadcasted_iota(jnp.int32, (LANE, ng), 1)
    gt = jnp.transpose(g_ref[:, :])                    # (LANE, R)
    ones = jnp.ones((1, LANE), jnp.float32)
    for r in range(R_BLOCK):
        oh = (gt[:, r:r + 1] == it).astype(jnp.float32)   # (LANE, ng)
        o_ref[r:r + 1, :] += jnp.dot(ones, oh,
                                     preferred_element_type=jnp.float32)
        va, vb, vc = _bf16_split3(v_ref[r:r + 1, :])
        sm = (jnp.dot(va, oh, preferred_element_type=jnp.float32)
              + jnp.dot(vb, oh, preferred_element_type=jnp.float32)
              + jnp.dot(vc, oh, preferred_element_type=jnp.float32))
        _kahan_add(o_ref, comp_ref, 8 + r, r, sm)


def _agg_kernel(g_ref, v_ref, o_ref, comp_ref, *, ng: int):
    """counts/sums as _sum_kernel, plus mins -> o[16:24], maxs -> o[24:32]."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        o_ref[0:16, :] = jnp.zeros_like(o_ref[0:16, :])
        o_ref[16:24, :] = jnp.full_like(o_ref[16:24, :], _BIG)
        o_ref[24:32, :] = jnp.full_like(o_ref[24:32, :], -_BIG)
        comp_ref[:, :] = jnp.zeros_like(comp_ref)

    it = jax.lax.broadcasted_iota(jnp.int32, (LANE, ng), 1)
    gt = jnp.transpose(g_ref[:, :])
    vt = jnp.transpose(v_ref[:, :])
    ones = jnp.ones((1, LANE), jnp.float32)
    for r in range(R_BLOCK):
        hit = gt[:, r:r + 1] == it                        # (LANE, ng)
        oh = hit.astype(jnp.float32)
        o_ref[r:r + 1, :] += jnp.dot(ones, oh,
                                     preferred_element_type=jnp.float32)
        va, vb, vc = _bf16_split3(v_ref[r:r + 1, :])
        sm = (jnp.dot(va, oh, preferred_element_type=jnp.float32)
              + jnp.dot(vb, oh, preferred_element_type=jnp.float32)
              + jnp.dot(vc, oh, preferred_element_type=jnp.float32))
        _kahan_add(o_ref, comp_ref, 8 + r, r, sm)
        vcol = vt[:, r:r + 1]                             # (LANE, 1)
        # typed f32 sentinel: the weak python float would promote the select
        # to f64 under the enclosing x64 program (Mosaic verifier rejects it)
        big = jnp.asarray(_BIG, jnp.float32)
        mins = jnp.min(jnp.where(hit, vcol, big), axis=0, keepdims=True)
        maxs = jnp.max(jnp.where(hit, vcol, -big), axis=0, keepdims=True)
        o_ref[16 + r:17 + r, :] = jnp.minimum(o_ref[16 + r:17 + r, :], mins)
        o_ref[24 + r:25 + r, :] = jnp.maximum(o_ref[24 + r:25 + r, :], maxs)


def _hist_kernel(g_ref, o_ref, *, ng: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        o_ref[:, :] = jnp.zeros_like(o_ref)

    it = jax.lax.broadcasted_iota(jnp.int32, (LANE, ng), 1)
    gt = jnp.transpose(g_ref[:, :])
    ones = jnp.ones((1, LANE), jnp.float32)
    for r in range(R_BLOCK):
        oh = (gt[:, r:r + 1] == it).astype(jnp.float32)
        o_ref[r:r + 1, :] += jnp.dot(ones, oh,
                                     preferred_element_type=jnp.float32)


def _prep(codes, mask, num_groups, values=None):
    """Mask/pad to (steps*R_BLOCK, LANE) blocks; dead rows get code ng_pad
    (matches no one-hot lane, incl. the padding lanes we slice off)."""
    ng_pad = -(-num_groups // LANE) * LANE
    flat = R_BLOCK * LANE
    n = codes.shape[0]
    target = max(flat, -(-n // flat) * flat)
    g = codes.astype(jnp.int32)
    live = mask & (g >= 0) & (g < num_groups)
    # ng_pad must be a typed i32 constant: a weak python int promotes to i64
    # under the enclosing x64 program, and Mosaic's verifier rejects the
    # mixed-width select
    g = jnp.where(live, g, jnp.asarray(ng_pad, jnp.int32))
    if target != n:
        g = jnp.concatenate([g, jnp.full((target - n,), ng_pad, jnp.int32)])
    rows = target // LANE
    out = [g.reshape(rows, LANE)]
    if values is not None:
        v = jnp.where(live, values.astype(jnp.float32),
                      jnp.zeros((), jnp.float32))
        if target != n:
            v = jnp.concatenate([v, jnp.zeros((target - n,), jnp.float32)])
        out.append(v.reshape(rows, LANE))
    return out, rows // R_BLOCK, ng_pad


@functools.partial(jax.jit, static_argnames=("num_groups", "interpret"))
def filtered_group_sum(codes, values, mask, num_groups: int,
                       interpret: bool = False):
    """Fused filter + dense group-by COUNT/SUM.

    codes: int [N]; values: [N] (contracted as f32); mask: bool [N].
    -> (counts [num_groups] f32, sums [num_groups] f32).  Rows failing the
    mask or with out-of-range codes drop."""
    if not PALLAS_AVAILABLE:
        return _xla_fallback(codes, values, mask, num_groups)
    with _x64_scope(False):
        (g2, v2), steps, ng_pad = _prep(codes, mask, num_groups, values)
        out = pl.pallas_call(
            functools.partial(_sum_kernel, ng=ng_pad),
            grid=(steps,),
            in_specs=[pl.BlockSpec((R_BLOCK, LANE), lambda i: (i, 0))] * 2,
            out_specs=pl.BlockSpec((16, ng_pad), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((16, ng_pad), jnp.float32),
            scratch_shapes=[pltpu.VMEM((8, ng_pad), jnp.float32)],
            interpret=interpret,
        )(g2, v2)
    counts = out[0:8].astype(jnp.float64).sum(axis=0).astype(jnp.float32)
    sums = out[8:16].astype(jnp.float64).sum(axis=0).astype(jnp.float32)
    return counts[:num_groups], sums[:num_groups]


@functools.partial(jax.jit, static_argnames=("num_groups", "interpret"))
def fused_group_aggregate(codes, values, mask, num_groups: int,
                          interpret: bool = False):
    """Fused filter + dense group-by COUNT/SUM/MIN/MAX in ONE VMEM pass.

    -> (counts, sums, mins, maxs) [num_groups] f32; min/max lanes of empty
    groups hold +/-3.4e38 (count==0 marks them)."""
    if not PALLAS_AVAILABLE:
        return _xla_agg_fallback(codes, values, mask, num_groups)
    with _x64_scope(False):
        (g2, v2), steps, ng_pad = _prep(codes, mask, num_groups, values)
        out = pl.pallas_call(
            functools.partial(_agg_kernel, ng=ng_pad),
            grid=(steps,),
            in_specs=[pl.BlockSpec((R_BLOCK, LANE), lambda i: (i, 0))] * 2,
            out_specs=pl.BlockSpec((32, ng_pad), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((32, ng_pad), jnp.float32),
            scratch_shapes=[pltpu.VMEM((8, ng_pad), jnp.float32)],
            interpret=interpret,
        )(g2, v2)
    counts = out[0:8].astype(jnp.float64).sum(axis=0).astype(jnp.float32)
    sums = out[8:16].astype(jnp.float64).sum(axis=0).astype(jnp.float32)
    mins = jnp.minimum(out[16:24].min(axis=0), _BIG)
    maxs = jnp.maximum(out[24:32].max(axis=0), -_BIG)
    return (counts[:num_groups], sums[:num_groups],
            mins[:num_groups], maxs[:num_groups])


@functools.partial(jax.jit, static_argnames=("num_partitions", "interpret"))
def partition_histogram(dest, mask, num_partitions: int,
                        interpret: bool = False):
    """Per-destination row counts for a hash shuffle, as one MXU pass (sizes
    exchange capacities exactly so the repartition compiles with the right
    cap on the FIRST attempt)."""
    if not PALLAS_AVAILABLE:
        gid = jnp.where(mask & (dest >= 0) & (dest < num_partitions),
                        dest, num_partitions)
        return jax.ops.segment_sum(
            jnp.ones(dest.shape[0], jnp.float32), gid,
            num_segments=num_partitions + 1)[:num_partitions]
    with _x64_scope(False):
        (g2,), steps, ng_pad = _prep(dest, mask, num_partitions)
        out = pl.pallas_call(
            functools.partial(_hist_kernel, ng=ng_pad),
            grid=(steps,),
            in_specs=[pl.BlockSpec((R_BLOCK, LANE), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, ng_pad), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((8, ng_pad), jnp.float32),
            interpret=interpret,
        )(g2)
    return out.astype(jnp.float64).sum(axis=0).astype(jnp.float32)[:num_partitions]


def _xla_fallback(codes, values, mask, num_groups: int):
    gid = jnp.where(mask & (codes >= 0) & (codes < num_groups),
                    codes, num_groups)
    counts = jax.ops.segment_sum(jnp.ones_like(values, jnp.float32), gid,
                                 num_segments=num_groups + 1)[:num_groups]
    sums = jax.ops.segment_sum(values.astype(jnp.float32), gid,
                               num_segments=num_groups + 1)[:num_groups]
    return counts, sums


def _xla_agg_fallback(codes, values, mask, num_groups: int):
    live = mask & (codes >= 0) & (codes < num_groups)
    gid = jnp.where(live, codes, num_groups)
    v = values.astype(jnp.float32)
    counts = jax.ops.segment_sum(jnp.ones_like(v), gid,
                                 num_segments=num_groups + 1)[:num_groups]
    sums = jax.ops.segment_sum(v, gid,
                               num_segments=num_groups + 1)[:num_groups]
    mins = jnp.minimum(jax.ops.segment_min(
        jnp.where(live, v, _BIG), gid,
        num_segments=num_groups + 1)[:num_groups], _BIG)
    maxs = jnp.maximum(jax.ops.segment_max(
        jnp.where(live, v, -_BIG), gid,
        num_segments=num_groups + 1)[:num_groups], -_BIG)
    return counts, sums, mins, maxs
