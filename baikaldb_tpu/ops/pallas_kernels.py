"""Hand-written Pallas TPU kernels for the hottest query path.

XLA's generic lowering handles most relational kernels well (fused
elementwise + segment_sum), but the single hottest OLAP loop — scan ->
filter -> dense group-by partial aggregation (BASELINE configs #1/#2) — can
be expressed as one VMEM-resident pass that turns the per-row scatter of
``segment_sum`` into an MXU matmul against a one-hot group matrix:

    per row-tile:  onehot[B, G] = (codes == iota(G)) & pred
                   counts[G]  += ones[B]  @ onehot      (MXU)
                   sums[G]    += values[B] @ onehot     (MXU)

The grid walks row tiles; the accumulator block stays pinned in VMEM across
grid steps (same output block for every i, initialized at i == 0) — the
standard Pallas reduction pattern.  For small group counts this keeps the
whole reduction on-chip: one HBM read of the data, zero scatter traffic.

Falls back to the XLA segment_sum path when Pallas is unavailable; tests run
in interpret mode on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

LANE = 128


def _pad_to(x, multiple, fill):
    n = x.shape[0]
    target = max(multiple, -(-n // multiple) * multiple)
    if target == n:
        return x
    return jnp.concatenate([x, jnp.full((target - n,), fill, x.dtype)])


def _kernel(g_ref, v_ref, m_ref, out_ref, *, ng_pad: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    g = g_ref[:, :].reshape(-1)                      # [B]
    v = v_ref[:, :].reshape(-1)
    m = m_ref[:, :].reshape(-1)
    b = g.shape[0]
    groups = jax.lax.broadcasted_iota(jnp.int32, (b, ng_pad), 1)
    onehot = ((g[:, None] == groups) & m[:, None]).astype(jnp.float32)
    counts = jnp.dot(jnp.ones((1, b), jnp.float32), onehot,
                     preferred_element_type=jnp.float32)       # [1, G]
    sums = jnp.dot(v.reshape(1, b), onehot,
                   preferred_element_type=jnp.float32)         # [1, G]
    out_ref[0:1, :] += counts
    out_ref[1:2, :] += sums


try:  # Pallas is part of jax; guard for stripped builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    PALLAS_AVAILABLE = False


def _launch_reduction(kernel, codes, mask, num_out: int, block_rows: int,
                      interpret: bool, values=None):
    """Shared launch scaffolding for the tiled one-hot reductions: pad rows
    to full tiles, range-mask out-of-domain codes, reshape to (rows, LANE)
    blocks, and run with a pinned (8, padded) f32 accumulator block."""
    n_pad = -(-num_out // LANE) * LANE
    rows = block_rows
    flat = rows * LANE
    g = _pad_to(codes.astype(jnp.int32), flat, jnp.int32(-1))
    m = _pad_to(mask, flat, False) & (g >= 0) & (g < num_out)
    steps = g.shape[0] // flat
    args = [g.reshape(steps * rows, LANE)]
    if values is not None:
        v = _pad_to(values.astype(jnp.float32), flat, jnp.float32(0))
        args.append(v.reshape(steps * rows, LANE))
    args.append(m.reshape(steps * rows, LANE))
    out = pl.pallas_call(
        functools.partial(kernel, ng_pad=n_pad),
        grid=(steps,),
        in_specs=[pl.BlockSpec((rows, LANE), lambda i: (i, 0))
                  for _ in args],
        out_specs=pl.BlockSpec((8, n_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, n_pad), jnp.float32),
        interpret=interpret,
    )(*args)
    return out


@functools.partial(jax.jit, static_argnames=("num_groups", "block_rows",
                                             "interpret"))
def filtered_group_sum(codes, values, mask, num_groups: int,
                       block_rows: int = 512, interpret: bool = False):
    """Fused filter + dense group-by COUNT/SUM.

    codes: int32 [N] in [0, num_groups); values: [N] (cast to f32);
    mask: bool [N] live-row predicate.  -> (counts [num_groups] f32,
    sums [num_groups] f32).  Rows with out-of-range codes are dropped.
    """
    if not PALLAS_AVAILABLE:
        return _xla_fallback(codes, values, mask, num_groups)
    out = _launch_reduction(_kernel, codes, mask, num_groups, block_rows,
                            interpret, values=values)
    return out[0, :num_groups], out[1, :num_groups]


def _xla_fallback(codes, values, mask, num_groups: int):
    gid = jnp.where(mask & (codes >= 0) & (codes < num_groups),
                    codes, num_groups)
    counts = jax.ops.segment_sum(jnp.ones_like(values, jnp.float32), gid,
                                 num_segments=num_groups + 1)[:num_groups]
    sums = jax.ops.segment_sum(values.astype(jnp.float32), gid,
                               num_segments=num_groups + 1)[:num_groups]
    return counts, sums


# ---------------------------------------------------------------------------
# full fused aggregate: COUNT / SUM / MIN / MAX in one VMEM pass

_BIG = 3.4e38      # python float: a jnp constant would be captured by the
#                    kernel closure, which pallas_call rejects


def _agg_kernel(g_ref, v_ref, m_ref, out_ref, *, ng_pad: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[0:2, :] = jnp.zeros_like(out_ref[0:2, :])
        out_ref[2:3, :] = jnp.full_like(out_ref[2:3, :], _BIG)
        out_ref[3:4, :] = jnp.full_like(out_ref[3:4, :], -_BIG)

    g = g_ref[:, :].reshape(-1)
    v = v_ref[:, :].reshape(-1)
    m = m_ref[:, :].reshape(-1)
    b = g.shape[0]
    groups = jax.lax.broadcasted_iota(jnp.int32, (b, ng_pad), 1)
    hit = (g[:, None] == groups) & m[:, None]
    onehot = hit.astype(jnp.float32)
    counts = jnp.dot(jnp.ones((1, b), jnp.float32), onehot,
                     preferred_element_type=jnp.float32)
    sums = jnp.dot(v.reshape(1, b), onehot,
                   preferred_element_type=jnp.float32)
    # min/max: masked broadcast + reduce along the row axis (VPU); the
    # accumulator row stays pinned in VMEM like the sums
    vb = v[:, None]
    mins = jnp.min(jnp.where(hit, vb, _BIG), axis=0, keepdims=True)
    maxs = jnp.max(jnp.where(hit, vb, -_BIG), axis=0, keepdims=True)
    out_ref[0:1, :] += counts
    out_ref[1:2, :] += sums
    out_ref[2:3, :] = jnp.minimum(out_ref[2:3, :], mins)
    out_ref[3:4, :] = jnp.maximum(out_ref[3:4, :], maxs)


@functools.partial(jax.jit, static_argnames=("num_groups", "block_rows",
                                             "interpret"))
def fused_group_aggregate(codes, values, mask, num_groups: int,
                          block_rows: int = 512, interpret: bool = False):
    """Fused filter + dense group-by COUNT/SUM/MIN/MAX in ONE HBM pass
    (SURVEY §7 hard part #4: the MIN/MAX-capable sibling of
    filtered_group_sum).  -> (counts, sums, mins, maxs) [num_groups] f32;
    min/max lanes of empty groups hold +/-3.4e38 (count==0 marks them)."""
    if not PALLAS_AVAILABLE:
        return _xla_agg_fallback(codes, values, mask, num_groups)
    out = _launch_reduction(_agg_kernel, codes, mask, num_groups, block_rows,
                            interpret, values=values)
    return (out[0, :num_groups], out[1, :num_groups],
            out[2, :num_groups], out[3, :num_groups])


def _xla_agg_fallback(codes, values, mask, num_groups: int):
    live = mask & (codes >= 0) & (codes < num_groups)
    gid = jnp.where(live, codes, num_groups)
    v = values.astype(jnp.float32)
    counts = jax.ops.segment_sum(jnp.ones_like(v), gid,
                                 num_segments=num_groups + 1)[:num_groups]
    sums = jax.ops.segment_sum(v, gid,
                               num_segments=num_groups + 1)[:num_groups]
    # clamp the +/-inf identities of empty segments to the documented
    # sentinel so both paths agree (and results stay JSON-serializable)
    mins = jnp.minimum(jax.ops.segment_min(
        jnp.where(live, v, _BIG), gid,
        num_segments=num_groups + 1)[:num_groups], _BIG)
    maxs = jnp.maximum(jax.ops.segment_max(
        jnp.where(live, v, -_BIG), gid,
        num_segments=num_groups + 1)[:num_groups], -_BIG)
    return counts, sums, mins, maxs


# ---------------------------------------------------------------------------
# radix-partition histogram (the shuffle-sizing building block)


def _hist_kernel(d_ref, m_ref, out_ref, *, ng_pad: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    d = d_ref[:, :].reshape(-1)
    m = m_ref[:, :].reshape(-1)
    b = d.shape[0]
    parts = jax.lax.broadcasted_iota(jnp.int32, (b, ng_pad), 1)
    onehot = ((d[:, None] == parts) & m[:, None]).astype(jnp.float32)
    out_ref[0:1, :] += jnp.dot(jnp.ones((1, b), jnp.float32), onehot,
                               preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("num_partitions", "block_rows",
                                             "interpret"))
def partition_histogram(dest, mask, num_partitions: int,
                        block_rows: int = 512, interpret: bool = False):
    """Per-destination row counts for a hash shuffle, as one MXU pass
    (SURVEY §7 hard part #2: the counting phase of radix partition — XLA's
    sort does the reorder, this sizes exchange capacities exactly so the
    repartition compiles with the right cap on the FIRST attempt)."""
    if not PALLAS_AVAILABLE:
        gid = jnp.where(mask & (dest >= 0) & (dest < num_partitions),
                        dest, num_partitions)
        return jax.ops.segment_sum(
            jnp.ones(dest.shape[0], jnp.float32), gid,
            num_segments=num_partitions + 1)[:num_partitions]
    out = _launch_reduction(_hist_kernel, dest, mask, num_partitions,
                            block_rows, interpret)
    return out[0, :num_partitions]
