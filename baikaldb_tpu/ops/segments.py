"""Backend-adaptive segment reductions: the group-by primitive.

``jax.ops.segment_sum`` lowers to a row-serialized ``scatter-add`` on TPU —
measured ~880ms for 100M rows x 16 segments on v5e, ~1000x off the HBM
roofline — while on CPU the scatter loop is the *right* lowering.  The
reference hits the same fork: row-wise hash-table aggregation on the OLTP
path vs Arrow's vectorized hash-agg on the Acero path (src/exec/agg_node.cpp
vs the arrow declaration in the same file).  Here the fork is by backend,
decided at trace time:

- **TPU, num_segments <= ONEHOT_MAX_SEGMENTS**: a fused select+reduce — each
  segment's lane reduces ``where(gid == k, x, identity)`` over the row axis.
  XLA fuses the compare into the reduction (nothing materializes in HBM; an
  einsum against a one-hot does NOT fuse — XLA allocates the full
  ``[n, k]`` one-hot, 54GB at 100M x 17 x f64), so the pass is one
  bandwidth-bound read of the data plus ~1.5ms of VPU work per segment per
  100M rows.  Accumulation is exact-width (int sums in the integer dtype,
  wrapping exactly like the scatter path; float sums in f64), so results are
  in the same rounding class as ``jax.ops.segment_*``.
- **CPU or large num_segments**: ``jax.ops.segment_*`` scatter, unchanged.
  The ~512-segment crossover is where per-segment VPU work meets the
  scatter's fixed ~8.8ns/row cost (both measured on v5e).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

ONEHOT_MAX_SEGMENTS = 512


def _onehot_backend() -> bool:
    return jax.default_backend() not in ("cpu",)


def _max_segments() -> int:
    """Flag-tunable crossover (utils/flags.py: onehot_max_segments)."""
    try:
        from ..utils.flags import FLAGS
        return int(FLAGS.onehot_max_segments)
    except Exception:
        return ONEHOT_MAX_SEGMENTS


def _use_onehot(num_segments: int) -> bool:
    return _onehot_backend() and num_segments <= _max_segments()


def seg_sum(x, gid, num_segments: int):
    """Drop-in ``jax.ops.segment_sum(x, gid, num_segments=...)``.

    Out-of-range ids drop, matching scatter-mode="drop" semantics.  The
    select+reduce path handles 1-D data; multi-dim ``x`` (e.g. kmeans
    centroid sums over [n, d] vectors) stays on the scatter path."""
    if x.ndim != 1 or not _use_onehot(num_segments):
        return jax.ops.segment_sum(x, gid, num_segments=num_segments)
    dt = x.dtype
    acc = jnp.float64 if dt.kind == "f" else dt
    if dt == jnp.bool_:
        acc = jnp.int64
    k = jax.lax.broadcasted_iota(jnp.int32, (1, num_segments), 1)
    hit = gid[:, None] == k
    out = jnp.sum(jnp.where(hit, x[:, None].astype(acc),
                            jnp.zeros((), acc)), axis=0)
    return out.astype(dt) if dt != jnp.bool_ else out.astype(jnp.int32)


def _seg_extremum(x, gid, num_segments: int, is_min: bool):
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.int32)
    if jnp.issubdtype(x.dtype, jnp.integer):
        info = jnp.iinfo(x.dtype)
        ident = info.max if is_min else info.min
    else:
        ident = jnp.inf if is_min else -jnp.inf
    if not _use_onehot(num_segments):
        f = jax.ops.segment_min if is_min else jax.ops.segment_max
        return f(x, gid, num_segments=num_segments)
    ident = jnp.asarray(ident, x.dtype)
    k = jax.lax.broadcasted_iota(jnp.int32, (1, num_segments), 1)
    masked = jnp.where(gid[:, None] == k, x[:, None], ident)
    return (jnp.min if is_min else jnp.max)(masked, axis=0)


def seg_min(x, gid, num_segments: int):
    """Drop-in ``jax.ops.segment_min`` (empty segments get dtype max/+inf)."""
    return _seg_extremum(x, gid, num_segments, True)


def seg_max(x, gid, num_segments: int):
    """Drop-in ``jax.ops.segment_max`` (empty segments get dtype min/-inf)."""
    return _seg_extremum(x, gid, num_segments, False)
