"""Aggregation kernels: scalar, sort-grouped, and dense-domain group-by.

Reference: src/exec/agg_node.cpp (hash aggregation with partial mode on stores
and MERGE_AGG on the coordinator) + src/expr/agg_fn_call.cpp (the per-function
update/merge protocol).  On TPU a pointer-chasing hash table would serialize
the VPU, so grouping is re-expressed as data-parallel primitives:

- **dense path**: when every group key has a known dense domain (dictionary
  codes are dense by construction; small-range ints are detected by the
  planner), the combined group id is a mixed-radix fold and aggregation is one
  ``segment_sum`` per aggregate — zero sorts, the TPU-optimal plan for
  GROUP BY over categorical keys (the BASELINE.json north-star config #2).
- **sort path**: general fallback — multi-key stable sort, boundary detection,
  ``cumsum`` group ids, then segment reductions into a static ``max_groups``
  table.

Both paths emit *mergeable partials* (SUM/COUNT pairs for AVG etc.), so the
distributed layer can ``psum``/re-reduce them across mesh shards exactly like
the reference merges per-region partial aggregates.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..column.batch import Column, ColumnBatch
from .segments import seg_max, seg_min, seg_sum
from ..types import LType


def agg_result_type(op: str, input_type: LType) -> LType:
    if op in ("count", "count_star", "approx_count_distinct"):
        return LType.INT64
    if op == "sum":
        return LType.INT64 if input_type.is_integer else LType.FLOAT64
    if op in ("avg", "sumsq", "stddev", "stddev_samp", "variance", "var_samp",
              "percentile"):
        return LType.FLOAT64
    if op in ("min", "max"):
        return input_type
    raise ValueError(f"unknown aggregate {op}")


# aggregates whose state cannot merge as a single psum/pmin/pmax lane; the
# distribute pass co-locates each group's rows (repartition/gather) instead
ROW_AGGS = {"approx_count_distinct", "percentile"}

# HyperLogLog register count for APPROX_COUNT_DISTINCT (the reference keeps
# 16384-register HLLs in src/common/hll_common.cpp; 512 keeps the dense
# group table small at <2% typical error)
HLL_REGISTERS = 512


@dataclass(frozen=True)
class AggSpec:
    op: str                 # count | count_star | sum | avg | min | max |
    #                         stddev/variance family | approx_count_distinct |
    #                         percentile
    input: Optional[str]    # column name; None for count_star
    out_name: str
    distinct: bool = False
    param: Optional[float] = None   # percentile fraction


def _sum_dtype(c: Column):
    return jnp.int64 if c.ltype.is_integer else jnp.float64


def _minmax_identity(c: Column, is_min: bool):
    info = (jnp.iinfo if c.data.dtype.kind in "iu" else jnp.finfo)(c.data.dtype)
    return info.max if is_min else info.min


# ----------------------------------------------------------------------
# scalar aggregation (no GROUP BY)


def scalar_aggregate(batch: ColumnBatch, specs: list[AggSpec]) -> ColumnBatch:
    sel = batch.sel_mask()
    names, cols = [], []
    for s in specs:
        names.append(s.out_name)
        cols.append(_scalar_one(batch, s, sel))
    return ColumnBatch(tuple(names), cols)


def _scalar_one(batch: ColumnBatch, s: AggSpec, sel) -> Column:
    if s.op == "count_star":
        return Column(jnp.sum(sel).astype(jnp.int64)[None], None, LType.INT64)
    c = batch.column(s.input)
    live = sel & c.valid_mask()
    if s.distinct and s.op in ("count", "sum", "avg"):
        return _scalar_distinct(c, live, s)
    if s.op == "count":
        return Column(jnp.sum(live).astype(jnp.int64)[None], None, LType.INT64)
    if s.op == "sum":
        dt = _sum_dtype(c)
        v = jnp.sum(jnp.where(live, c.data.astype(dt), 0))[None]
        any_ = jnp.any(live)[None]
        return Column(v, any_, agg_result_type("sum", c.ltype))
    if s.op == "avg":
        dt = jnp.float64
        sm = jnp.sum(jnp.where(live, c.data.astype(dt), 0))
        ct = jnp.sum(live)
        any_ = ct > 0
        return Column((sm / jnp.maximum(ct, 1))[None], any_[None], LType.FLOAT64)
    if s.op in ("min", "max"):
        ident = _minmax_identity(c, s.op == "min")
        v = jnp.where(live, c.data, ident)
        r = (jnp.min(v) if s.op == "min" else jnp.max(v))[None]
        return Column(r, jnp.any(live)[None], c.ltype, c.dictionary)
    if s.op == "sumsq":
        x = c.data.astype(jnp.float64)
        v = jnp.sum(jnp.where(live, x * x, 0.0))[None]
        return Column(v, jnp.any(live)[None], LType.FLOAT64)
    if s.op in ("stddev", "stddev_samp", "variance", "var_samp"):
        x = jnp.where(live, c.data.astype(jnp.float64), 0.0)
        n = jnp.sum(live).astype(jnp.float64)
        n1 = jnp.maximum(n, 1.0)
        mean = jnp.sum(x) / n1
        var = jnp.sum(jnp.where(live, (c.data.astype(jnp.float64) - mean) ** 2, 0.0))
        denom = n1 if s.op in ("stddev", "variance") else jnp.maximum(n - 1.0, 1.0)
        v = var / denom
        if s.op.startswith("stddev"):
            v = jnp.sqrt(v)
        return Column(v[None], (n > 0)[None], LType.FLOAT64)
    if s.op == "approx_count_distinct":
        regs = _hll_registers(c, live, jnp.zeros_like(c.data, jnp.int32), 1)
        return Column(_hll_estimate(regs)[:1], None, LType.INT64)
    if s.op == "percentile":
        gid = jnp.where(live, 0, 1)
        v, ok = _segment_percentile(c, gid, 1, s.param)
        return Column(v, ok, LType.FLOAT64)
    raise ValueError(f"unknown aggregate {s.op}")


# -- sketch aggregates --------------------------------------------------


def _hll_registers(c: Column, live, gid, ng: int):
    """Per-group HyperLogLog register table [ng, m] via ONE segment_max —
    the reference's HLL sketches (src/common/hll_common.cpp) re-expressed as
    a segment reduction (TPU-native: no per-row register RMW)."""
    from ..utils.hashing import hash_columns, mix32

    m = HLL_REGISTERS
    h1 = hash_columns([c.data])
    h2 = mix32(h1 ^ jnp.uint32(0x9E3779B9))     # independent second stream
    reg = (h1 % jnp.uint32(m)).astype(jnp.int32)
    # rho = 1 + leading zeros of the second stream (32-bit)
    nz = 32 - jnp.ceil(jnp.log2(h2.astype(jnp.float64) + 1.0)).astype(jnp.int32)
    rho = jnp.clip(nz + 1, 1, 33)
    slot = jnp.where(live, gid * m + reg, ng * m)
    regs = seg_max(jnp.where(live, rho, 0), slot,
                               num_segments=ng * m + 1)[:ng * m]
    return jnp.maximum(regs, 0).reshape(ng, m)


def _hll_estimate(regs):
    """[ng, m] registers -> cardinality estimate with small-range correction."""
    m = float(HLL_REGISTERS)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    z = jnp.sum(2.0 ** (-regs.astype(jnp.float64)), axis=1)
    e = alpha * m * m / z
    zeros = jnp.sum(regs == 0, axis=1).astype(jnp.float64)
    small = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    est = jnp.where((e <= 2.5 * m) & (zeros > 0), small, e)
    return jnp.round(est).astype(jnp.int64)


def _segment_percentile(c: Column, gid_v, ng: int, p: float):
    """Exact percentile per group: sort by (group, value), index into each
    group's run with linear interpolation (PERCENTILE_CONT semantics).  The
    reference approximates with t-digest (src/common/tdigest.cpp) because
    CPU sorts are expensive; on TPU the sort IS the cheap primitive."""
    x = c.data.astype(jnp.float64)
    order = jnp.argsort(x, stable=True)
    order = order[jnp.argsort(gid_v[order], stable=True)]
    g = gid_v[order]
    v = x[order]
    counts = seg_sum(jnp.ones_like(gid_v, jnp.int32), gid_v,
                                 num_segments=ng + 1)[:ng]
    starts = jnp.cumsum(counts) - counts
    tpos = starts.astype(jnp.float64) + p * jnp.maximum(counts - 1, 0)
    lo = jnp.floor(tpos).astype(jnp.int32)
    hi = jnp.ceil(tpos).astype(jnp.int32)
    n = v.shape[0]
    vlo = jnp.take(v, jnp.clip(lo, 0, max(n - 1, 0)), mode="clip")
    vhi = jnp.take(v, jnp.clip(hi, 0, max(n - 1, 0)), mode="clip")
    frac = tpos - lo
    return vlo + (vhi - vlo) * frac, counts > 0


def _scalar_distinct(c: Column, live, s: AggSpec) -> Column:
    """COUNT/SUM/AVG(DISTINCT x): sort + boundary count.  Dead/NULL lanes get
    the +max sentinel so they sort past the live prefix."""
    d = jnp.where(live, c.data, _minmax_identity(c, is_min=True))
    srt = jnp.sort(d)
    live_n = jnp.sum(live)
    idx = jnp.arange(d.shape[0])
    new = (idx == 0) | (srt != jnp.roll(srt, 1))
    uniq = new & (idx < live_n)
    if s.op == "count":
        return Column(jnp.sum(uniq).astype(jnp.int64)[None], None, LType.INT64)
    dt = _sum_dtype(c)
    sm = jnp.sum(jnp.where(uniq, srt.astype(dt), 0))
    if s.op == "sum":
        return Column(sm[None], jnp.any(uniq)[None], agg_result_type("sum", c.ltype))
    ct = jnp.maximum(jnp.sum(uniq), 1)
    return Column((sm.astype(jnp.float64) / ct)[None], jnp.any(uniq)[None], LType.FLOAT64)


# ----------------------------------------------------------------------
# dense-domain group-by (segment_sum fast path)


def combined_dense_id(key_cols: list[Column], domains: list[int]):
    """Mixed-radix fold of dense key codes -> single group id, plus validity.

    NULL keys get their own slot: each radix is domain+1 with NULL -> domain."""
    cid = None
    for c, dom in zip(key_cols, domains):
        code = c.data.astype(jnp.int32)
        if c.validity is not None:
            code = jnp.where(c.validity, code, dom)
        code = jnp.clip(code, 0, dom)
        cid = code if cid is None else cid * (dom + 1) + code
    return cid


def dense_num_groups(domains: list[int]) -> int:
    n = 1
    for d in domains:
        n *= d + 1
    return n


def group_aggregate_dense(batch: ColumnBatch, key_names: list[str],
                          domains: list[int], specs: list[AggSpec]) -> ColumnBatch:
    """GROUP BY over dense-coded keys: one segment reduction per aggregate.

    Output capacity = prod(domain+1); absent groups are masked via sel."""
    key_cols = [batch.column(k) for k in key_names]
    ng = dense_num_groups(domains)
    gid = combined_dense_id(key_cols, domains)
    sel = batch.sel_mask()
    gid_live = jnp.where(sel, gid, ng)  # dead rows -> overflow bucket
    present = seg_sum(jnp.ones_like(gid_live, dtype=jnp.int32), gid_live,
                      num_segments=ng + 1)[:ng] > 0
    # reconstruct key columns from slot index
    out_names, out_cols = [], []
    slot = jnp.arange(ng, dtype=jnp.int32)
    rem = slot
    strides = []
    st = 1
    for dom in reversed(domains):
        strides.append(st)
        st *= dom + 1
    strides = list(reversed(strides))
    for name, c, dom, stride in zip(key_names, key_cols, domains, strides):
        code = (rem // stride) % (dom + 1)
        validity = code < dom if c.validity is not None else None
        code = jnp.where(code >= dom, 0, code)
        out_names.append(name)
        out_cols.append(Column(code.astype(c.data.dtype), validity, c.ltype, c.dictionary))
    pallas_cols = _pallas_dense_cols(batch, specs, gid, ng, sel)
    if pallas_cols is not None:
        out_names.extend(s.out_name for s in specs)
        out_cols.extend(pallas_cols)
    else:
        for s in specs:
            out_names.append(s.out_name)
            out_cols.append(_segment_one(batch, s, gid_live, ng, sel))
    return ColumnBatch(tuple(out_names), out_cols, present, None)


def _pallas_dense_cols(batch, specs, gid, ng: int, sel):
    """Mid-cardinality dense group-by through the Pallas MXU kernels
    (ops/pallas_kernels.py), when they're exact enough for the spec list:

    - only COUNT/COUNT(*)/SUM/AVG/MIN/MAX, no DISTINCT;
    - value columns must be floats (counts are exact; float sums carry the
      kernel's ~1e-7 relative error); MIN/MAX additionally need FLOAT32
      columns (f64 values would be rounded by the f32 pipeline);
    - group count in (select+reduce crossover, PALLAS_MAX_GROUPS];
    - TPU backend + FLAGS.pallas_group_kernels.

    Returns the aggregate Columns (spec order), or None to use segments."""
    import jax as _jax

    from ..utils.flags import FLAGS
    from . import segments
    from .pallas_kernels import (PALLAS_AVAILABLE, PALLAS_MAX_GROUPS,
                                 filtered_group_sum, fused_group_aggregate,
                                 partition_histogram)

    try:
        enabled = bool(FLAGS.pallas_group_kernels)
    except Exception:
        enabled = False
    if not (enabled and PALLAS_AVAILABLE
            and _jax.default_backend() not in ("cpu",)
            and segments._max_segments() < ng + 1 <= PALLAS_MAX_GROUPS):
        return None
    for s in specs:
        if s.distinct or s.op not in ("count_star", "count", "sum", "avg",
                                      "min", "max"):
            return None
        if s.op != "count_star":
            lt = batch.column(s.input).ltype
            if lt not in (LType.FLOAT32, LType.FLOAT64):
                return None
            if s.op in ("min", "max") and lt is not LType.FLOAT32:
                return None
    fused: dict = {}          # input name -> (cnt, sm, mn, mx)
    star_counts = None
    cols = []
    for s in specs:
        if s.op == "count_star":
            if star_counts is None:
                star_counts = partition_histogram(gid, sel, ng)
            cols.append(Column(star_counts.astype(jnp.int64), None,
                               LType.INT64))
            continue
        c = batch.column(s.input)
        if s.input not in fused:
            live = c.valid_mask() & sel
            # min/max lanes cost extra VPU work per group: only the full
            # kernel when some spec on this column asks for them
            if any(x.op in ("min", "max") and x.input == s.input
                   for x in specs):
                fused[s.input] = fused_group_aggregate(gid, c.data, live, ng)
            else:
                cnt_, sm_ = filtered_group_sum(gid, c.data, live, ng)
                fused[s.input] = (cnt_, sm_, None, None)
        cnt, sm, mn, mx = fused[s.input]
        nonempty = cnt > 0
        if s.op == "count":
            cols.append(Column(cnt.astype(jnp.int64), None, LType.INT64))
        elif s.op == "sum":
            cols.append(Column(sm.astype(jnp.float64), nonempty,
                               agg_result_type("sum", c.ltype)))
        elif s.op == "avg":
            cols.append(Column(sm.astype(jnp.float64)
                               / jnp.maximum(cnt, 1).astype(jnp.float64),
                               nonempty, LType.FLOAT64))
        elif s.op == "min":
            cols.append(Column(mn.astype(c.data.dtype), nonempty, c.ltype))
        else:
            cols.append(Column(mx.astype(c.data.dtype), nonempty, c.ltype))
    return cols


def _segment_one(batch: ColumnBatch, s: AggSpec, gid, ng: int, sel) -> Column:
    """One aggregate via segment reduction; gid==ng is the dead-row bucket."""
    if s.op == "count_star":
        v = seg_sum(jnp.ones_like(gid, jnp.int64), gid, num_segments=ng + 1)[:ng]
        return Column(v, None, LType.INT64)
    c = batch.column(s.input)
    live = c.valid_mask() & sel
    gid_v = jnp.where(live, gid, ng)
    if s.distinct:
        return _segment_distinct(c, gid_v, ng, s)
    if s.op == "count":
        v = seg_sum(jnp.ones_like(gid, jnp.int64), gid_v, num_segments=ng + 1)[:ng]
        return Column(v, None, LType.INT64)
    if s.op == "sum":
        dt = _sum_dtype(c)
        v = seg_sum(c.data.astype(dt), gid_v, num_segments=ng + 1)[:ng]
        ct = seg_sum(jnp.ones_like(gid, jnp.int32), gid_v, num_segments=ng + 1)[:ng]
        return Column(v, ct > 0, agg_result_type("sum", c.ltype))
    if s.op == "avg":
        sm = seg_sum(c.data.astype(jnp.float64), gid_v, num_segments=ng + 1)[:ng]
        ct = seg_sum(jnp.ones_like(gid, jnp.int32), gid_v, num_segments=ng + 1)[:ng]
        return Column(sm / jnp.maximum(ct, 1), ct > 0, LType.FLOAT64)
    if s.op == "min":
        v = seg_min(jnp.where(live, c.data, _minmax_identity(c, True)),
                                jnp.where(live, gid, ng), num_segments=ng + 1)[:ng]
        ct = seg_sum(jnp.where(live, 1, 0), gid_v, num_segments=ng + 1)[:ng]
        return Column(v, ct > 0, c.ltype, c.dictionary)
    if s.op == "max":
        v = seg_max(jnp.where(live, c.data, _minmax_identity(c, False)),
                                jnp.where(live, gid, ng), num_segments=ng + 1)[:ng]
        ct = seg_sum(jnp.where(live, 1, 0), gid_v, num_segments=ng + 1)[:ng]
        return Column(v, ct > 0, c.ltype, c.dictionary)
    if s.op == "sumsq":
        x = c.data.astype(jnp.float64)
        v = seg_sum(jnp.where(live, x * x, 0.0), gid_v, num_segments=ng + 1)[:ng]
        ct = seg_sum(jnp.where(live, 1, 0), gid_v, num_segments=ng + 1)[:ng]
        return Column(v, ct > 0, LType.FLOAT64)
    if s.op in ("stddev", "stddev_samp", "variance", "var_samp"):
        x = c.data.astype(jnp.float64)
        sm = seg_sum(jnp.where(live, x, 0.0), gid_v, num_segments=ng + 1)[:ng]
        s2 = seg_sum(jnp.where(live, x * x, 0.0), gid_v, num_segments=ng + 1)[:ng]
        n = seg_sum(jnp.where(live, 1.0, 0.0), gid_v, num_segments=ng + 1)[:ng]
        n1 = jnp.maximum(n, 1.0)
        var = s2 / n1 - (sm / n1) ** 2
        denom_n = n1 if s.op in ("stddev", "variance") else jnp.maximum(n - 1.0, 1.0)
        var = jnp.maximum(var * (n1 / denom_n), 0.0)
        v = jnp.sqrt(var) if s.op.startswith("stddev") else var
        return Column(v, n > 0, LType.FLOAT64)
    if s.op == "approx_count_distinct":
        regs = _hll_registers(c, live, gid_v, ng)
        return Column(_hll_estimate(regs), None, LType.INT64)
    if s.op == "percentile":
        v, ok = _segment_percentile(c, gid_v, ng, s.param)
        return Column(v, ok, LType.FLOAT64)
    raise ValueError(f"unknown aggregate {s.op}")


def _segment_distinct(c: Column, gid, ng: int, s: AggSpec) -> Column:
    """Per-group DISTINCT via (gid, value) sort + boundary dedup."""
    order = jnp.argsort(c.data, stable=True)
    order = order[jnp.argsort(gid[order], stable=True)]
    g = gid[order]
    v = c.data[order]
    idx = jnp.arange(g.shape[0])
    new = (idx == 0) | (g != jnp.roll(g, 1)) | (v != jnp.roll(v, 1))
    live = g < ng
    w = new & live
    if s.op == "count":
        out = seg_sum(w.astype(jnp.int64), jnp.where(live, g, ng),
                                  num_segments=ng + 1)[:ng]
        return Column(out, None, LType.INT64)
    dt = _sum_dtype(c)
    sm = seg_sum(jnp.where(w, v.astype(dt), 0), jnp.where(live, g, ng),
                             num_segments=ng + 1)[:ng]
    if s.op == "sum":
        ct = seg_sum(w.astype(jnp.int32), jnp.where(live, g, ng),
                                 num_segments=ng + 1)[:ng]
        return Column(sm, ct > 0, agg_result_type("sum", c.ltype))
    ct = seg_sum(w.astype(jnp.int32), jnp.where(live, g, ng),
                             num_segments=ng + 1)[:ng]
    return Column(sm.astype(jnp.float64) / jnp.maximum(ct, 1), ct > 0, LType.FLOAT64)


# ----------------------------------------------------------------------
# sort-based group-by (general fallback)


def group_aggregate_sorted(batch: ColumnBatch, key_names: list[str],
                           specs: list[AggSpec], max_groups: int,
                           with_overflow: bool = False, order=None):
    """General GROUP BY: lexicographic stable sort, boundary cumsum group ids,
    segment reductions into a static max_groups-slot table.

    ``max_groups`` must upper-bound the true group count (the planner supplies
    it from statistics or len(batch)); groups fill slots densely, output
    carries num_rows = group count."""
    n = len(batch)
    key_cols = [batch.column(k) for k in key_names]
    sel = batch.sel_mask()
    # canonicalize NULL lanes to 0 so all NULL keys form ONE group regardless
    # of the garbage data under the invalid lanes (MySQL: NULLs group together)
    key_data = []
    for c in key_cols:
        d = c.data
        if d.dtype == jnp.bool_:
            d = d.astype(jnp.int32)
        if c.validity is not None:
            d = jnp.where(c.validity, d, jnp.zeros((), d.dtype))
        key_data.append(d)
    if order is not None:
        # host-precomputed per-version key order (the secondary-index
        # read): only the query-dependent liveness partition remains, and
        # a stable boolean partition is O(n) prefix-sum arithmetic — no
        # on-device sort at all
        live_o = sel[order]
        n_live = jnp.sum(live_o)
        dest = jnp.where(live_o, jnp.cumsum(live_o) - 1,
                         n_live + jnp.cumsum(~live_o) - 1)
        perm = jnp.zeros(n, order.dtype).at[dest].set(order)
    else:
        perm = jnp.arange(n)
        for c, d in zip(reversed(key_cols), reversed(key_data)):
            perm = perm[jnp.argsort(d[perm], stable=True)]
            if c.validity is not None:
                perm = perm[jnp.argsort(c.validity[perm], stable=True)]  # NULLs first
        perm = perm[jnp.argsort(~sel[perm], stable=True)]  # dead rows last

    sel_s = sel[perm]
    idx = jnp.arange(n)
    boundary = idx == 0
    for c, dd in zip(key_cols, key_data):
        d = dd[perm]
        boundary = boundary | (d != jnp.roll(d, 1))
        if c.validity is not None:
            v = c.validity[perm]
            boundary = boundary | (v != jnp.roll(v, 1))
    flags = boundary & sel_s
    gid = jnp.cumsum(flags.astype(jnp.int32)) - 1
    gid = jnp.where(sel_s & (gid >= 0) & (gid < max_groups), gid, max_groups)
    ngroups = jnp.minimum(jnp.sum(flags), max_groups).astype(jnp.int32)

    # scatter first-occurrence key values into group slots
    out_names, out_cols = [], []
    scatter_to = jnp.where(flags, jnp.clip(gid, 0, max_groups - 1), max_groups)
    for name, c in zip(key_names, key_cols):
        d = c.data[perm]
        buf = jnp.zeros((max_groups + 1,), d.dtype).at[scatter_to].set(d)[:max_groups]
        validity = None
        if c.validity is not None:
            vb = jnp.zeros((max_groups + 1,), bool).at[scatter_to].set(c.validity[perm])[:max_groups]
            validity = vb
        out_names.append(name)
        out_cols.append(Column(buf, validity, c.ltype, c.dictionary))

    sorted_batch = batch.gather(perm)
    sorted_batch.sel = sel_s
    for s in specs:
        out_names.append(s.out_name)
        out_cols.append(_segment_one(sorted_batch, s, gid, max_groups, sel_s))
    present = jnp.arange(max_groups) < ngroups
    out = ColumnBatch(tuple(out_names), out_cols, present, ngroups)
    if with_overflow:
        return out, jnp.sum(flags) > max_groups
    return out


# ----------------------------------------------------------------------
# partial-aggregate merge protocol (for distributed / multi-shard merge)

MERGE_OP = {
    "count": "sum", "count_star": "sum", "sum": "sum", "sumsq": "sum",
    "min": "min", "max": "max",
}


def partial_specs(specs: list[AggSpec]) -> tuple[list[AggSpec], dict]:
    """Rewrite aggregates into mergeable partials (AVG -> SUM+COUNT, STDDEV ->
    SUM+SUMSQ+COUNT), the analog of the reference's AGG partial/MERGE_AGG split
    (plan.proto:14-16).  Returns (partial specs, finalize plan)."""
    parts: list[AggSpec] = []
    finalize: dict[str, tuple] = {}
    seen = {}

    def add(op, inp, distinct=False):
        key = (op, inp, distinct)
        if key in seen:
            return seen[key]
        name = f"__p{len(parts)}_{op}"
        parts.append(AggSpec(op, inp, name, distinct))
        seen[key] = name
        return name

    for s in specs:
        if s.op in ROW_AGGS:
            # these need each group's ROWS, not a mergeable scalar partial;
            # the distribute pass must have routed them via repartition
            raise ValueError(f"{s.op} has no scalar partial form")
        if s.op == "avg":
            finalize[s.out_name] = ("avg", add("sum", s.input, s.distinct),
                                    add("count", s.input, s.distinct))
        elif s.op in ("stddev", "stddev_samp", "variance", "var_samp"):
            sq = add("sumsq", s.input)
            finalize[s.out_name] = (s.op, add("sum", s.input), sq, add("count", s.input))
        elif s.distinct:
            # distinct cannot merge from partials; executed post-shuffle
            finalize[s.out_name] = ("passthrough", add(s.op, s.input, True))
        else:
            finalize[s.out_name] = ("passthrough", add(s.op, s.input))
    return parts, finalize


def finalize_partials(batch: ColumnBatch, finalize: dict, key_names: list[str]) -> ColumnBatch:
    """Apply the finalize plan from partial_specs to a merged-partials batch."""
    names = list(key_names)
    cols = [batch.column(k) for k in key_names]
    for out_name, plan in finalize.items():
        kind = plan[0]
        if kind == "passthrough":
            c = batch.column(plan[1])
        elif kind == "avg":
            sm, ct = batch.column(plan[1]), batch.column(plan[2])
            ctv = ct.data.astype(jnp.float64)
            c = Column(sm.data.astype(jnp.float64) / jnp.maximum(ctv, 1), ctv > 0, LType.FLOAT64)
        else:  # stddev family from (op, sum, sumsq, count)
            op, sm, sq, ct = plan[0], batch.column(plan[1]), batch.column(plan[2]), batch.column(plan[3])
            n = ct.data.astype(jnp.float64)
            n1 = jnp.maximum(n, 1.0)
            var = sq.data / n1 - (sm.data.astype(jnp.float64) / n1) ** 2
            denom = n1 if op in ("stddev", "variance") else jnp.maximum(n - 1.0, 1.0)
            var = jnp.maximum(var * (n1 / denom), 0.0)
            v = jnp.sqrt(var) if op.startswith("stddev") else var
            c = Column(v, n > 0, LType.FLOAT64)
        names.append(out_name)
        cols.append(c)
    return ColumnBatch(tuple(names), cols, batch.sel, batch.num_rows)
