"""Selection-mask materialization (mask -> dense prefix).

XLA requires static shapes, so filters refine a bool ``sel`` mask instead of
shrinking batches (SURVEY.md §7 hard part #3: dynamic result cardinality).
``compact`` stable-partitions live rows to the front and returns the same-
capacity batch plus a traced live count — the pattern the reference never
needs (Acero emits variable-length batches) but which keeps every downstream
kernel shape-static on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..column.batch import ColumnBatch


def compact(batch: ColumnBatch) -> ColumnBatch:
    """Move live rows to the front (stable); sets num_rows, clears sel."""
    if batch.sel is None and batch.num_rows is None:
        return batch
    if batch.sel is None:
        return batch
    if batch.live_prefix:
        # bucket-padded batches promise live rows already form a leading
        # prefix (sel == arange < live), so the argsort+gather is the
        # identity — just surface the count
        n = batch.live_count()
        return ColumnBatch(batch.names, batch.columns,
                           jnp.arange(len(batch)) < n, n, live_prefix=True)
    sel = batch.sel
    n = jnp.sum(sel).astype(jnp.int32)
    if len(batch) == 0:
        out = batch.gather(jnp.zeros((0,), jnp.int32))
        out.num_rows = n
        out.sel = jnp.zeros((0,), bool)
        return out
    # O(n) prefix-sum partition, not an O(n log n) stable argsort — same
    # live-first stable order, and the dominant cost of a selective point
    # read's final compact at full capacity
    order = stable_partition(sel)
    out = batch.gather(order)
    out.num_rows = n
    # rows past n keep stale data; mark them dead for any mask-aware consumer
    out.sel = jnp.arange(len(batch)) < n
    return out


def stable_partition(live) -> "jnp.ndarray":
    """Permutation moving live rows to the front, STABLY, via prefix sums
    and one scatter — O(n), no sort.  order[j] = source index of output
    row j; the live prefix preserves input order (so an input sorted over
    its live rows stays sorted)."""
    n = live.shape[0]
    nl = jnp.cumsum(live)
    dest = jnp.where(live, nl - 1, nl[-1] + jnp.cumsum(~live) - 1)
    return jnp.zeros(n, jnp.int32).at[dest].set(
        jnp.arange(n, dtype=jnp.int32))


def shrink(batch: ColumnBatch, cap: int):
    """Pack live rows into a batch of STATIC capacity ``cap`` (smaller than
    the input's), returning (packed batch, needed live count).

    The sel-mask architecture never compacts, so a selective join chain
    drags the base table's full capacity through every downstream operator
    — a 1.2M-lane gather/searchsorted per op for 10k live rows (the TPC-H
    q21 profile).  ``shrink`` is the capacity cut: one nonzero+gather pass,
    then everything above runs at ``cap``.  When the live count exceeds
    ``cap`` the caller's overflow-retry protocol re-traces with a bigger
    cap (same contract as the join cap flags).
    """
    if cap >= len(batch):
        return batch, jnp.int32(0)        # no cut possible: pass through
    sel = batch.sel
    if sel is None:
        n = jnp.int32(len(batch)) if batch.num_rows is None \
            else jnp.asarray(batch.num_rows, jnp.int32)
        sel = jnp.arange(len(batch)) < n
    n = jnp.sum(sel).astype(jnp.int32)
    (idx,) = jnp.nonzero(sel, size=cap, fill_value=0)
    out = batch.gather(idx)
    out.sel = jnp.arange(cap) < jnp.minimum(n, cap)
    out.num_rows = None
    return out, n


def head(batch: ColumnBatch, limit: int, offset: int = 0) -> ColumnBatch:
    """LIMIT/OFFSET over live rows (reference: src/exec/limit_node.cpp)."""
    b = compact(batch)
    n = b.live_count()
    idx = jnp.arange(len(b))
    keep = (idx >= offset) & (idx < jnp.minimum(n, offset + limit))
    return ColumnBatch(b.names, b.columns, keep, None)
