"""Selection-mask materialization (mask -> dense prefix).

XLA requires static shapes, so filters refine a bool ``sel`` mask instead of
shrinking batches (SURVEY.md §7 hard part #3: dynamic result cardinality).
``compact`` stable-partitions live rows to the front and returns the same-
capacity batch plus a traced live count — the pattern the reference never
needs (Acero emits variable-length batches) but which keeps every downstream
kernel shape-static on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..column.batch import ColumnBatch


def compact(batch: ColumnBatch) -> ColumnBatch:
    """Move live rows to the front (stable); sets num_rows, clears sel."""
    if batch.sel is None and batch.num_rows is None:
        return batch
    if batch.sel is None:
        return batch
    sel = batch.sel
    n = jnp.sum(sel).astype(jnp.int32)
    order = jnp.argsort(~sel, stable=True)
    out = batch.gather(order)
    out.num_rows = n
    # rows past n keep stale data; mark them dead for any mask-aware consumer
    out.sel = jnp.arange(len(batch)) < n
    return out


def head(batch: ColumnBatch, limit: int, offset: int = 0) -> ColumnBatch:
    """LIMIT/OFFSET over live rows (reference: src/exec/limit_node.cpp)."""
    b = compact(batch)
    n = b.live_count()
    idx = jnp.arange(len(b))
    keep = (idx >= offset) & (idx < jnp.minimum(n, offset + limit))
    return ColumnBatch(b.names, b.columns, keep, None)
