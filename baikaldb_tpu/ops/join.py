"""Equi-join kernels (reference: src/exec/join_node.cpp + joiner.cpp — hash
join build/probe, index nested-loop join; Acero hashjoin declaration).

A chasing hash table is hostile to the VPU, so the TPU design is a *sort
join*: sort the build side by key once, then probe with vectorized binary
search (``jnp.searchsorted``) — O(log n) fully-unrolled compare ladders that
XLA vectorizes across all probe rows.  Duplicate build keys are handled by
[lo, hi) match ranges plus an offset-inversion expansion (the static-shape
analog of emitting one output row per match).

Join keys: one column of any fixed-width type, or two int32-ish columns packed
into one int64.  String keys join on dictionary codes: ``join`` aligns the two
sides' dictionaries host-side (column/dictionary.merge) at trace time before
comparing codes.

NULL keys never match (SQL semantics); dead rows (sel=False) never match.
Output cardinality is static: ``cap`` rows (planner-estimated); an overflow
flag is returned so the executor can retry with a larger cap.
"""

from __future__ import annotations

from dataclasses import replace

import jax.numpy as jnp

from ..column.batch import Column, ColumnBatch
from ..column.dictionary import NULL_CODE, merge as dict_merge
from ..types import LType


def _align_string_keys(probe: ColumnBatch, probe_keys: list[str],
                       build: ColumnBatch, build_keys: list[str]):
    """Remap string key columns of both sides onto merged dictionaries so code
    equality == string equality.  Host work is O(|dict|), done at trace time."""

    def retag(batch, name, col):
        cols = list(batch.columns)
        cols[batch.names.index(name)] = col
        return ColumnBatch(batch.names, cols, batch.sel, batch.num_rows)

    for pk, bk in zip(probe_keys, build_keys):
        pc, bc = probe.column(pk), build.column(bk)
        if pc.ltype is not LType.STRING and bc.ltype is not LType.STRING:
            continue
        if pc.dictionary is None or bc.dictionary is None:
            raise ValueError(f"string join key {pk}/{bk} lacks a dictionary")
        if pc.dictionary is bc.dictionary or pc.dictionary._id == bc.dictionary._id:
            continue
        m, ra, rb = dict_merge(pc.dictionary, bc.dictionary)
        ta, tb = jnp.asarray(ra), jnp.asarray(rb)
        pd = jnp.where(pc.data >= 0, jnp.take(ta, jnp.clip(pc.data, 0, None), mode="clip"),
                       NULL_CODE)
        bd = jnp.where(bc.data >= 0, jnp.take(tb, jnp.clip(bc.data, 0, None), mode="clip"),
                       NULL_CODE)
        probe = retag(probe, pk, replace(pc, data=pd, dictionary=m))
        build = retag(build, bk, replace(bc, data=bd, dictionary=m))
    return probe, build


_PACK32_TYPES = (LType.BOOL, LType.INT8, LType.INT16, LType.INT32,
                 LType.UINT32, LType.DATE, LType.STRING)


def _key_array(batch: ColumnBatch, names: list[str],
               wide_keys_ok: bool = False):
    """Pack 1-2 key columns into a single sortable array + validity.

    ``wide_keys_ok``: the PLANNER verified (from statistics) that wider
    integer values fit 32-bit packing; without it, only types whose every
    value packs losslessly are accepted — an unbounded int64 must fail
    loudly, not alias silently."""
    cols = [batch.column(n) for n in names]
    valid = None
    for c in cols:
        if c.validity is not None:
            valid = c.validity if valid is None else (valid & c.validity)
    if len(cols) == 1:
        d = cols[0].data
        if d.dtype == jnp.bool_:
            d = d.astype(jnp.int32)
        return d, valid
    if len(cols) == 2:
        for c in cols:
            ok = c.ltype in _PACK32_TYPES or \
                (wide_keys_ok and c.ltype.is_integer)
            if not ok:
                raise ValueError(
                    "2-key sort-join requires 32-bit-packable keys "
                    "(or planner-verified bounds); demote to residual "
                    "equality otherwise")
        a = cols[0].data.astype(jnp.int64)
        b = cols[1].data.astype(jnp.int64)
        return (a << 32) | (b & jnp.int64(0xFFFFFFFF)), valid
    raise ValueError(">2 join key columns: planner must demote extras to "
                     "residual equality")


def _sentinel_max(dtype):
    return (jnp.iinfo if dtype.kind in "iu" else jnp.finfo)(dtype).max


def _build_dead(build: ColumnBatch, bvalid):
    """Dead mask for the build side: sel-dead or NULL-key rows."""
    dead = jnp.zeros(len(build), bool)
    if build.sel is not None:
        dead = dead | ~build.sel
    if bvalid is not None:
        dead = dead | ~bvalid
    return dead


def _probe_dead(probe: ColumnBatch, pvalid):
    """(sel_dead, dead): sel-dead alone, and sel-dead-or-NULL-key."""
    sel_dead = ~probe.sel if probe.sel is not None \
        else jnp.zeros(len(probe), bool)
    dead = sel_dead
    if pvalid is not None:
        dead = dead | ~pvalid
    return sel_dead, dead


def semi_join_neq(probe: ColumnBatch, probe_keys: list[str],
                  build: ColumnBatch, build_keys: list[str],
                  neq_probe: str, neq_build: str, how: str = "semi",
                  order=None):
    """[NOT] EXISTS with equality keys plus ONE ``build_col <> probe_col``
    residual — the TPC-H q21 shape — WITHOUT expanding the many-to-many
    match space.  For each probe row the residual-satisfying match count is

        #(key matches with build_col NOT NULL)  -  #(key, build_col=probe_col)

    both computable as range counts over ONE build array sorted by the
    packed (key, residual column): two extra binary searches instead of an
    output-cardinality join (the reference runs this as an expanded hash
    join + dedup, join_node.cpp — this path beats it asymptotically).
    Returns (out_batch, 0).  Key and residual columns must be 32-bit-safe
    (the planner checks)."""
    probe, build = _align_string_keys(probe, probe_keys, build, build_keys)
    pk, pvalid = _key_array(probe, probe_keys)
    bk, bvalid = _key_array(build, build_keys)
    a = probe.column(neq_probe)
    b = build.column(neq_build)

    bdead = _build_dead(build, bvalid)
    # rows whose residual column is NULL can never satisfy b <> a (NULL
    # comparisons are not TRUE): dead for BOTH counts
    if b.validity is not None:
        bdead = bdead | ~b.validity

    mask32 = jnp.int64(0xFFFFFFFF)
    pk2 = (bk.astype(jnp.int64) << 32) | (b.data.astype(jnp.int64) & mask32)
    base = pk.astype(jnp.int64) << 32
    pp = base | (a.data.astype(jnp.int64) & mask32)
    if order is not None:
        # host-precomputed per-version sort of the base table (the
        # secondary-index read): NO on-device sort.  Dead rows (filtered /
        # NULL) sit interspersed at their value positions; a prefix sum of
        # deadness converts value-range counts into LIVE counts
        pk2_sorted = pk2[order]
        dead_sorted = bdead[order].astype(jnp.int32)
        cum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(dead_sorted)])

        def live_range(lo_v, hi_v, lo_side, hi_side):
            lo = jnp.searchsorted(pk2_sorted, lo_v, side=lo_side)
            hi = jnp.searchsorted(pk2_sorted, hi_v, side=hi_side)
            return (hi - lo) - (cum[hi] - cum[lo])

        key_cnt = live_range(base, base | mask32, "left", "right")
        eq_cnt = live_range(pp, pp, "left", "right")
    else:
        order2 = jnp.lexsort((pk2, bdead))
        n_live = jnp.sum(~bdead).astype(jnp.int32)
        pk2_sorted = jnp.where(jnp.arange(len(build)) < n_live,
                               pk2[order2], _sentinel_max(pk2.dtype))
        first_dead = n_live.astype(jnp.int32)
        clamp = lambda x: jnp.minimum(x.astype(jnp.int32), first_dead)  # noqa: E731
        key_lo = clamp(jnp.searchsorted(pk2_sorted, base, side="left"))
        # upper bound via side="right" on the all-ones low word: adding
        # 2^32 would overflow int64 for a key at dtype max (the clamp
        # keeps a live key whose packed value EQUALS the sentinel correct)
        key_hi = clamp(jnp.searchsorted(pk2_sorted, base | mask32,
                                        side="right"))
        pp_lo = clamp(jnp.searchsorted(pk2_sorted, pp, side="left"))
        pp_hi = clamp(jnp.searchsorted(pk2_sorted, pp, side="right"))
        key_cnt = key_hi - key_lo
        eq_cnt = pp_hi - pp_lo

    psel_dead, pdead = _probe_dead(probe, pvalid)
    if a.validity is not None:
        pdead = pdead | ~a.validity      # a NULL: residual never TRUE
    counts = jnp.where(pdead, 0, key_cnt - eq_cnt)
    if how == "semi":
        return probe.and_sel(counts > 0), jnp.int32(0)
    if how == "anti":
        return probe.and_sel(counts == 0), jnp.int32(0)
    raise ValueError(f"semi_join_neq: unsupported how {how!r}")


def join(probe: ColumnBatch, probe_keys: list[str],
         build: ColumnBatch, build_keys: list[str],
         how: str = "inner", cap: int | None = None,
         suffix: str = "_r", wide_keys_ok: bool = False,
         build_sorted: bool = False, order=None):
    """Returns (out_batch, needed_rows).

    ``needed_rows`` (traced int32) is the true output cardinality; the caller
    retries with cap >= needed_rows when it exceeds ``cap`` (the static-shape
    overflow protocol — one exact retry instead of blind growth).

    how: inner | left | semi | anti.
    - semi/anti keep probe's capacity and just refine sel (no expansion;
      needed_rows is 0).
    - inner/left emit up to ``cap`` rows (default: probe capacity), pairing
      each probe row with every matching build row.
    Column names: probe names keep their own; clashing build names get suffix.
    """
    probe, build = _align_string_keys(probe, probe_keys, build, build_keys)
    pk, pvalid = _key_array(probe, probe_keys, wide_keys_ok)
    bk, bvalid = _key_array(build, build_keys, wide_keys_ok)

    # build side: order by (is_dead, key) — liveness primary — so live rows
    # form a contiguous sorted prefix of exactly n_live entries.  A sentinel
    # replaces the dead tail's keys to keep the array globally sorted; a LIVE
    # key equal to dtype-max still sorts before every dead row, so the
    # first-dead clamp below is exact for all key values
    bdead = _build_dead(build, bvalid)
    if order is not None:
        # host-precomputed per-version key permutation of the base table
        # (the secondary-index read): compose with a stable deadness
        # partition so filtered/NULL rows land in the tail — no on-device
        # sort at all
        from .compact import stable_partition

        o = jnp.asarray(order)
        order = o[stable_partition(~bdead[o])]
    elif build_sorted:
        # the planner proved the build side arrives key-sorted over its
        # LIVE rows (e.g. the output of a sorted group-by on exactly these
        # keys): a STABLE partition by deadness — O(n) prefix sums, no
        # bitonic sort — yields the same layout lexsort would
        from .compact import stable_partition

        order = stable_partition(~bdead)
    else:
        order = jnp.lexsort((bk, bdead))
    n_live = jnp.sum(~bdead).astype(jnp.int32)
    bk_sorted = jnp.where(jnp.arange(len(build)) < n_live,
                          bk[order], _sentinel_max(bk.dtype))

    lo = jnp.searchsorted(bk_sorted, pk, side="left")
    hi = jnp.searchsorted(bk_sorted, pk, side="right")
    psel_dead, pdead = _probe_dead(probe, pvalid)
    counts = jnp.where(pdead, 0, hi - lo)
    # drop matches that land in the dead tail (probe key == sentinel value)
    first_dead = n_live.astype(lo.dtype)
    counts = jnp.where(lo >= first_dead, 0, jnp.minimum(counts, first_dead - lo))

    if how == "semi":
        return probe.and_sel(counts > 0), jnp.int32(0)
    if how == "anti":
        return probe.and_sel(counts == 0), jnp.int32(0)

    def bidx_of(pi_c, k):
        bpos = lo[pi_c] + k                    # index into sorted build
        return order[jnp.clip(bpos, 0, len(build) - 1)]

    return _expand_matches(probe, build, how, cap, counts, psel_dead,
                           bidx_of, suffix)


def _expand_matches(probe: ColumnBatch, build: ColumnBatch, how: str,
                    cap: int | None, counts, psel_dead, bidx_of,
                    suffix: str):
    """Shared match-expansion machinery of every join kernel: per-probe
    match counts -> cumsum offsets -> output rows up to ``cap`` with the
    exact total reported for the retry protocol.  ``bidx_of(pi_c, k)``
    maps (probe row, match ordinal) -> build row index — the only part
    that differs between the globally-sorted and radix layouts."""
    if how == "left":
        # NULL-key probe rows still survive a LEFT JOIN (with NULL build side);
        # only sel-dead rows are dropped
        out_counts = jnp.maximum(counts, jnp.where(psel_dead, 0, 1))
    elif how == "inner":
        out_counts = counts
    else:
        raise ValueError(f"unknown join type {how}")

    if cap is None:
        cap = len(probe)
    offsets = jnp.cumsum(out_counts)
    total = (offsets[-1] if len(probe) else jnp.int32(0)).astype(jnp.int32)
    starts = offsets - out_counts
    # output row j -> probe row i = searchsorted(offsets, j, 'right')
    j = jnp.arange(cap)
    pi = jnp.searchsorted(offsets, j, side="right")
    pi_c = jnp.clip(pi, 0, len(probe) - 1)
    k = j - starts[pi_c]                      # match ordinal within probe row
    live_out = j < total
    matched = k < counts[pi_c]
    bidx = bidx_of(pi_c, k)

    out_p = probe.gather(pi_c, valid=None)
    bvalid_out = jnp.where(matched, True, False) & live_out
    out_b = build.gather(bidx, valid=None)

    names = list(out_p.names)
    cols = list(out_p.columns)
    for n, c in zip(out_b.names, out_b.columns):
        if how == "left":
            v = c.validity & bvalid_out if c.validity is not None else bvalid_out
            c = replace(c, validity=v)
        name = n if n not in names else n + suffix
        names.append(name)
        cols.append(c)
    out = ColumnBatch(tuple(names), cols, live_out, None)
    return out, total


def radix_join(probe: ColumnBatch, probe_keys: list[str],
               build: ColumnBatch, build_keys: list[str],
               how: str = "inner", cap: int | None = None,
               suffix: str = "_r", wide_keys_ok: bool = False,
               n_buckets: int = 256, width: int = 1024):
    """Hash-partitioned variant of ``join`` (reference: hash join,
    src/exec/join_node.cpp; ops/radix.py for the partition machinery).

    The build side partitions into ``n_buckets`` by key hash and sorts
    per-bucket (batched log^2(width) stages instead of one global
    log^2(n) bitonic); probes binary-search only their bucket.  Returns
    (out_batch, needed_rows, needed_width): ``needed_width`` reports the
    true max bucket occupancy — when it exceeds ``width`` (skew), the
    caller re-traces with a bigger width, the same contract as join caps.
    Semantics identical to ``join`` (inner/left/semi/anti, NULL handling,
    name suffixing)."""
    from .radix import radix_build, radix_probe

    probe, build = _align_string_keys(probe, probe_keys, build, build_keys)
    pk, pvalid = _key_array(probe, probe_keys, wide_keys_ok)
    bk, bvalid = _key_array(build, build_keys, wide_keys_ok)
    bdead = _build_dead(build, bvalid)
    sort_src, sort_keys, needed_width = radix_build(bk, bdead, n_buckets,
                                                    width)
    psel_dead, pdead = _probe_dead(probe, pvalid)
    b, lo, hi = radix_probe(pk, pdead, sort_keys, n_buckets)
    # clamp to each bucket's LIVE occupancy: live rows sort to the front of
    # their bucket row, so a probe key equal to the padding sentinel can't
    # overcount into the pad
    live_w = jnp.sum(sort_src < len(build), axis=1).astype(jnp.int32)
    lo = jnp.minimum(lo, live_w[b])
    hi = jnp.minimum(hi, live_w[b])
    counts = jnp.where(pdead, 0, hi - lo)

    if how == "semi":
        return probe.and_sel(counts > 0), jnp.int32(0), needed_width
    if how == "anti":
        return probe.and_sel(counts == 0), jnp.int32(0), needed_width

    flat_src = sort_src.reshape(-1)

    def bidx_of(pi_c, k):
        bpos = (b[pi_c].astype(jnp.int64) * width
                + lo[pi_c].astype(jnp.int64) + k)
        return jnp.clip(flat_src[jnp.clip(bpos, 0, flat_src.shape[0] - 1)],
                        0, len(build) - 1)

    out, total = _expand_matches(probe, build, how, cap, counts, psel_dead,
                                 bidx_of, suffix)
    return out, total, needed_width


def _align_multiway_strings(probe: ColumnBatch, level_keys: list[list[str]],
                            builds: list):
    """Align string key columns of the probe and EVERY build side onto one
    shared code space.  ``level_keys[i]`` holds side i's probe key columns
    (identical lists in the one-shared-key shape; per-level columns under
    the keyed exchange scheduler — sides on different probe columns simply
    never interact).  Two passes: the first grows the probe's dictionary
    to the union of all sides; the second re-aligns each build against that
    union (a second merge with a subset is value-stable, so every side ends
    up comparing codes in the same space — a single probe column compared
    against N independently-dictionaried builds must not stop at pairwise
    merges, or build_1's codes would be stale after build_2 widened the
    probe's dictionary)."""
    for i, (bb, bk) in enumerate(builds):
        probe, bb = _align_string_keys(probe, level_keys[i], bb, bk)
        builds[i] = (bb, bk)
    for i, (bb, bk) in enumerate(builds):
        probe, bb = _align_string_keys(probe, level_keys[i], bb, bk)
        builds[i] = (bb, bk)
    return probe, builds


def multiway_join(probe: ColumnBatch, probe_keys: list[str],
                  builds: list, hows: list[str],
                  cap: int | None = None, suffix: str = "_r",
                  wide_keys_ok: bool = False,
                  level_keys: list[list[str]] | None = None,
                  packs: list[bool] | None = None):
    """Fused multiway equi-join: ONE probe stream joined against N build
    sides in a single pass (the Efficient Multiway Hash Join shape;
    PAPERS.md).  Every level's key columns live ON THE PROBE STREAM:
    by default all levels share ``probe_keys`` (the PR 7 one-shared-key
    shape); ``level_keys[i]`` gives level i its own probe columns (the
    keyed exchange scheduler's mixed-key segments — co-location across
    levels is the SCHEDULER's proof, via equality classes, not this
    kernel's concern).

    ``builds``: list of (build_batch, build_key_names); ``hows[i]``:
    inner | left per level.  Semantically identical to the left-deep chain
    ``((probe ⋈ build_1) ⋈ build_2) ⋈ ...`` — each build side sorts by
    (deadness, key) once, the probe binary-searches every side, and the
    output expansion enumerates the cross product of per-side match ranges
    via one mixed-radix decode (last build fastest-varying, matching the
    chained expansion order).  The probe's key columns are packed/searched
    once per side but the probe rows themselves are materialized ONCE —
    no intermediate join result exists.

    Returns (out_batch, needed_rows): ``needed_rows`` is the exact fused
    output cardinality for the overflow retry protocol (int64 — a chain of
    expansions can overflow int32 counts)."""
    builds = list(builds)
    if level_keys is None:
        level_keys = [list(probe_keys)] * len(builds)
    if packs is None:
        packs = [wide_keys_ok] * len(builds)
    probe, builds = _align_multiway_strings(probe, level_keys, builds)
    psel_dead = ~probe.sel if probe.sel is not None \
        else jnp.zeros(len(probe), bool)

    per_side = []       # (oc, counts, lo, order, nbuild) per build
    pk_cache: dict = {}  # shared-key levels pack the probe columns ONCE
    for (bb, bkeys), how, pkeys, wide in zip(builds, hows, level_keys,
                                             packs):
        ck = (tuple(pkeys), bool(wide))
        if ck not in pk_cache:
            pk_cache[ck] = _key_array(probe, pkeys, wide)
        pk, pvalid = pk_cache[ck]
        pdead = psel_dead if pvalid is None else (psel_dead | ~pvalid)
        bk, bvalid = _key_array(bb, bkeys, wide)
        bdead = _build_dead(bb, bvalid)
        order = jnp.lexsort((bk, bdead))
        n_live = jnp.sum(~bdead).astype(jnp.int32)
        bk_sorted = jnp.where(jnp.arange(len(bb)) < n_live,
                              bk[order], _sentinel_max(bk.dtype))
        lo = jnp.searchsorted(bk_sorted, pk, side="left")
        hi = jnp.searchsorted(bk_sorted, pk, side="right")
        counts = jnp.where(pdead, 0, hi - lo)
        first_dead = n_live.astype(lo.dtype)
        counts = jnp.where(lo >= first_dead, 0,
                           jnp.minimum(counts, first_dead - lo))
        if how == "left":
            # NULL-key probe rows still survive (NULL build side); only
            # sel-dead probe rows are dropped — the binary-join contract
            oc = jnp.maximum(counts, jnp.where(psel_dead, 0, 1))
        elif how == "inner":
            oc = counts
        else:
            raise ValueError(f"multiway_join: unsupported how {how!r}")
        per_side.append((oc, counts, lo, order, len(bb)))

    out_counts = jnp.ones(len(probe), jnp.int64)
    for oc, _c, _lo, _o, _n in per_side:
        out_counts = out_counts * oc.astype(jnp.int64)

    if cap is None:
        cap = len(probe)
    if cap > 0x7FFF0000:
        # the overflow-retry loop feeds the int64 needed_rows back as the
        # next cap; the int32 expansion below cannot index past 2^31 (and
        # a 2-billion-row static batch would not fit regardless) — fail
        # with a clear message instead of wrapped indices
        raise ValueError(f"multiway_join cap {cap} exceeds the int32 "
                         "expansion range")
    offsets = jnp.cumsum(out_counts)
    total = (offsets[-1] if len(probe) else jnp.int64(0)).astype(jnp.int64)
    starts = offsets - out_counts
    # the EXPANSION arithmetic runs in int32: every live ordinal is
    # bounded by cap (rem = j - start < cap < 2^31), and per-side counts
    # are bounded by the build length.  Only the cumulative offsets /
    # ``total`` (the overflow flag — a chain of expansions can genuinely
    # exceed int32) stay int64; an output slot corrupted by the int32
    # clamp can only occur on a run whose flag already reports overflow,
    # and the session discards that output and retries.
    off32 = jnp.minimum(offsets, jnp.int64(0x7FFFFFF0)).astype(jnp.int32)
    st32 = jnp.minimum(starts, jnp.int64(0x7FFFFFF0)).astype(jnp.int32)
    j = jnp.arange(cap, dtype=jnp.int32)
    pi = jnp.searchsorted(off32, j, side="right")
    pi_c = jnp.clip(pi, 0, len(probe) - 1)
    k = j - st32[pi_c]
    live_out = j.astype(jnp.int64) < total

    # mixed-radix decode of the per-probe-row match ordinal: last build
    # varies fastest (== the chained left-deep expansion order)
    ordinals = [None] * len(per_side)
    rem = k
    for i in reversed(range(len(per_side))):
        oc_i = per_side[i][0][pi_c].astype(jnp.int32)
        d = jnp.maximum(oc_i, 1)
        ordinals[i] = rem % d
        rem = rem // d

    out_p = probe.gather(pi_c, valid=None)
    names = list(out_p.names)
    cols = list(out_p.columns)
    for (oc, counts, lo, order, nbuild), how, ki, (bb, _bk) in zip(
            per_side, hows, ordinals, builds):
        matched = ki < counts[pi_c].astype(jnp.int32)
        bpos = lo[pi_c].astype(jnp.int32) + ki
        bidx = order[jnp.clip(bpos, 0, max(nbuild - 1, 0))]
        out_b = bb.gather(jnp.clip(bidx, 0, max(nbuild - 1, 0)), valid=None)
        bvalid_out = matched & live_out
        for n, c in zip(out_b.names, out_b.columns):
            if how == "left":
                v = c.validity & bvalid_out if c.validity is not None \
                    else bvalid_out
                c = replace(c, validity=v)
            names.append(n if n not in names else n + suffix)
            cols.append(c)
    out = ColumnBatch(tuple(names), cols, live_out, None)
    return out, total


def _dense_slots(batch: ColumnBatch, keys: list[str],
                 los: list[int], spans: list[int]):
    """Row -> slot in the row-major product space of the key domains,
    plus an in-domain/valid mask (NULL or out-of-bounds keys excluded)."""
    slot = jnp.zeros(len(batch), jnp.int32)
    ok = jnp.ones(len(batch), bool)
    stride = 1
    for k, lo, sp in reversed(list(zip(keys, los, spans))):
        c = batch.column(k)
        # bounds-check in int64 BEFORE narrowing: a value beyond int32 (or
        # an int32 subtraction that would wrap) must fall out of domain,
        # not alias a slot after truncation
        wide = c.data.astype(jnp.int64) - lo
        ok = ok & (wide >= 0) & (wide < sp)
        if c.validity is not None:
            ok = ok & c.validity
        slot = slot + jnp.where(ok, wide, 0).astype(jnp.int32) * stride
        stride *= sp
    return slot, ok


def dense_join(probe: ColumnBatch, probe_keys: list[str],
               build: ColumnBatch, build_keys: list[str],
               los: list[int], spans: list[int], how: str = "inner",
               suffix: str = "_r"):
    """PK-FK join over a dense integer key domain — the TPU-native hash
    join.  When the build side's key (or composite key) is UNIQUE
    (primary/unique index) with host statistics bounding each column to
    [lo, lo+span), the hash table degenerates to a dense position table
    over the product space: one scatter builds it, one gather probes it.
    No sort, no binary-search ladder, and — because a unique build key
    means at most one match per probe row — the output keeps the probe's
    static shape: no expansion, no overflow/retry protocol.  This is the
    join the MXU-era plan wants for every TPC-H PK-FK edge (the
    reference's JoinTypeAnalyzer picking index-join over hash-join,
    src/physical_plan/join_type_analyzer.cpp).

    Returns (out_batch, 0) — the 0 matching the no-retry contract of
    semi/anti in ``join``.
    """
    probe, build = _align_string_keys(probe, probe_keys, build, build_keys)
    size = 1
    for sp in spans:
        size *= sp

    slot_b, ok_b = _dense_slots(build, build_keys, los, spans)
    if build.sel is not None:
        ok_b = ok_b & build.sel
    # dead / out-of-domain rows scatter into the spillway slot `size`
    table = jnp.full((size + 1,), -1, jnp.int32)
    table = table.at[jnp.where(ok_b, slot_b, size)].set(
        jnp.arange(len(build), dtype=jnp.int32), mode="drop")

    psel_dead = ~probe.sel if probe.sel is not None \
        else jnp.zeros(len(probe), bool)
    slot_p, ok_p = _dense_slots(probe, probe_keys, los, spans)
    in_dom = ok_p & ~psel_dead
    bidx = table[jnp.clip(slot_p, 0, size - 1)]
    matched = in_dom & (bidx >= 0)

    if how == "semi":
        return probe.and_sel(matched), jnp.int32(0)
    if how == "anti":
        return probe.and_sel(~matched), jnp.int32(0)
    if how == "inner":
        sel = probe.sel_mask() & matched
    elif how == "left":
        # NULL-key probe rows survive a LEFT JOIN (with NULL build side);
        # only sel-dead rows are dropped
        sel = probe.sel_mask()
    else:
        raise ValueError(f"unknown dense join type {how}")

    out_b = build.gather(jnp.clip(bidx, 0, max(len(build) - 1, 0)),
                         valid=None)
    names = list(probe.names)
    cols = list(probe.columns)
    for n, c in zip(out_b.names, out_b.columns):
        v = c.validity & matched if c.validity is not None else matched
        cols.append(replace(c, validity=v))
        names.append(n if n not in names else n + suffix)
    return ColumnBatch(tuple(names), cols, sel, None), jnp.int32(0)


def cross_join(probe: ColumnBatch, build: ColumnBatch, cap: int | None = None,
               suffix: str = "_r"):
    """Cartesian product with static cap (reference: JoinNode without
    equality conditions falls back to nested loop)."""
    np_, nb = len(probe), len(build)
    if cap is None:
        cap = np_ * nb
    j = jnp.arange(cap)
    pi = j // nb
    bi = j % nb
    live = (j < np_ * nb)
    live = live & probe.sel_mask()[jnp.clip(pi, 0, np_ - 1)] & build.sel_mask()[jnp.clip(bi, 0, nb - 1)]
    out_p = probe.gather(jnp.clip(pi, 0, np_ - 1))
    out_b = build.gather(jnp.clip(bi, 0, nb - 1))
    names = list(out_p.names)
    cols = list(out_p.columns)
    for n, c in zip(out_b.names, out_b.columns):
        names.append(n if n not in names else n + suffix)
        cols.append(c)
    needed = jnp.int64(np_ * nb)     # full capacity, not live count: the
    # positional pi/bi mapping above needs cap >= np_*nb rows to be exact
    # (int64: a runaway cross product must report, not overflow, its size)
    return ColumnBatch(tuple(names), cols, live, None), needed
