"""Vector ANN search on the MXU (BASELINE config #4).

The reference wraps faiss (IVF-Flat / HNSW) per region with a RocksDB scalar
payload + delete bitmap (src/vector_index/vector_index.cpp:2341,
include/vector_index/vector_index.h:42).  On TPU the hardware answer is
different: a brute-force scan IS a matmul — [q, d] x [d, n] on the systolic
array at bf16 — so exact search saturates the MXU up to millions of vectors,
and an IVF-style two-stage search (coarse centroids then probed clusters)
covers the rest.  Deleted rows are a validity mask, MVCC-style, like the
reference's delete bitmap.

Distances: L2 and inner-product/cosine, matching the reference's
faiss metric choices.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .segments import seg_sum


def _scores(queries, base, metric: str, precision: str):
    q = queries
    b = base
    if precision == "bf16":
        q = q.astype(jnp.bfloat16)
        b = b.astype(jnp.bfloat16)
    dots = jnp.matmul(q, b.T, preferred_element_type=jnp.float32)
    if metric == "ip":
        return dots
    if metric == "cosine":
        qn = jnp.linalg.norm(queries, axis=1, keepdims=True).astype(jnp.float32)
        bn = jnp.linalg.norm(base, axis=1, keepdims=True).astype(jnp.float32)
        return dots / jnp.maximum(qn * bn.T, 1e-30)
    if metric == "l2":
        # ||q-b||^2 = ||q||^2 - 2qb + ||b||^2; score = -distance
        q2 = jnp.sum(queries.astype(jnp.float32) ** 2, axis=1, keepdims=True)
        b2 = jnp.sum(base.astype(jnp.float32) ** 2, axis=1)
        return -(q2 - 2.0 * dots + b2[None, :])
    raise ValueError(f"unknown metric {metric}")


@partial(jax.jit, static_argnames=("k", "metric", "precision"))
def brute_force_topk(queries, base, valid, k: int, metric: str = "l2",
                     precision: str = "bf16"):
    """Exact top-k: [q, d] queries against [n, d] base -> (scores, indices).

    ``valid`` is the live-row mask (deletes / MVCC visibility — the analog of
    the reference's faiss delete bitmap merged at search time)."""
    s = _scores(queries, base, metric, precision)
    if valid is not None:
        s = jnp.where(valid[None, :], s, -jnp.inf)
    return jax.lax.top_k(s, k)


@partial(jax.jit, static_argnames=("k", "nprobe", "metric", "precision"))
def ivf_topk(queries, base, valid, centroids, assign, k: int, nprobe: int,
             metric: str = "l2", precision: str = "bf16"):
    """IVF-Flat: probe the nprobe nearest centroid clusters only.

    assign: [n] centroid id per base vector.  Scores for rows outside probed
    clusters are masked.  Static shapes: full scores computed then masked —
    on TPU the matmul is usually cheaper than a gather for n <= a few M; for
    larger n a pallas gather kernel takes over (later round)."""
    cs = _scores(queries, centroids, metric, precision)
    _, probe = jax.lax.top_k(cs, nprobe)              # [q, nprobe]
    s = _scores(queries, base, metric, precision)      # [q, n]
    in_probe = jnp.any(assign[None, :, None] == probe[:, None, :], axis=-1)
    if valid is not None:
        in_probe = in_probe & valid[None, :]
    s = jnp.where(in_probe, s, -jnp.inf)
    return jax.lax.top_k(s, k)


def pack_ivf(vectors: np.ndarray, assign: np.ndarray,
             n_clusters: int | None = None):
    """Cluster-sorted layout for the gather-based IVF path: rows of one
    cluster are contiguous, so probing nprobe clusters gathers nprobe
    ranges instead of scoring the whole base (the faiss inverted-list
    layout).  -> (order, starts, counts, max_count); base rows must be
    reindexed by ``order``.

    ``n_clusters`` MUST be the centroid count when clusters can be empty
    (k-means keeps old centroids for empty clusters): the search scores
    every centroid, so starts/counts must cover them all."""
    order = np.argsort(assign, kind="stable")
    sa = assign[order]
    nc = n_clusters if n_clusters is not None else \
        (int(assign.max()) + 1 if len(assign) else 1)
    counts = np.bincount(sa, minlength=nc)
    starts = np.zeros(nc, np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    return (order.astype(np.int64), starts,
            counts.astype(np.int64), int(counts.max() if len(counts) else 1))


def _np_scores(q: np.ndarray, rows: np.ndarray, metric: str,
               norms=None) -> np.ndarray:
    dots = rows @ q
    if metric == "ip":
        return dots
    if metric == "cosine":
        qn = np.linalg.norm(q)
        rn = np.sqrt(norms) if norms is not None \
            else np.linalg.norm(rows, axis=1)
        return dots / np.maximum(rn * qn, 1e-30)
    if norms is None:
        norms = (rows * rows).sum(1)
    return -(norms - 2.0 * dots + float(q @ q))                  # l2


def ivf_search_host(qvec: np.ndarray, matrix_sorted: np.ndarray,
                    valid_sorted, centroids: np.ndarray,
                    starts: np.ndarray, counts: np.ndarray,
                    k: int, nprobe: int, metric: str = "l2",
                    norms_sorted=None):
    """Host-side IVF over the packed layout: gather EXACTLY the probed
    clusters' rows (variable length is free outside jit) and score with
    BLAS.  This is the frontend's candidate-generation path — the work
    scales with the probed fraction, so it beats the full matmul on CPU
    hosts; the jitted re-rank of the candidates then runs on the
    accelerator.  -> (scores, positions-into-sorted-order)."""
    q = np.asarray(qvec, np.float32)
    cs = _np_scores(q, centroids, metric)
    nprobe = min(nprobe, len(centroids))
    probe = np.argpartition(cs, -nprobe)[-nprobe:]
    idx = np.concatenate([np.arange(starts[p], starts[p] + counts[p])
                          for p in probe]) if len(probe) else \
        np.zeros(0, np.int64)
    if len(idx) == 0:
        return np.zeros(0, np.float32), np.zeros(0, np.int64)
    s = _np_scores(q, matrix_sorted[idx], metric,
                   norms_sorted[idx] if norms_sorted is not None else None)
    if valid_sorted is not None:
        s = np.where(valid_sorted[idx], s, -np.inf)
    kk = min(k, len(idx))
    top = np.argpartition(s, -kk)[-kk:]
    top = top[np.argsort(-s[top])]
    return s[top], idx[top]


def kmeans(vectors: np.ndarray, n_clusters: int, iters: int = 10,
           seed: int = 0):
    """Lloyd's k-means on device (for IVF training — the faiss train analog).

    Returns (centroids [c, d], assign [n])."""
    rng = np.random.default_rng(seed)
    idx = rng.choice(len(vectors), size=n_clusters, replace=False)
    centroids = jnp.asarray(vectors[idx], jnp.float32)
    x = jnp.asarray(vectors, jnp.float32)

    # x rides as an ARGUMENT, never a closure capture: a captured array
    # becomes an XLA constant and constant-folding grinds through the
    # whole base matrix at compile time (minutes at 1M rows)
    @partial(jax.jit, static_argnames=("nc",))
    def step(x, c, nc):
        d = _scores(x, c, "l2", "f32")                # [n, nc] (neg dist)
        a = jnp.argmax(d, axis=1)
        sums = seg_sum(x, a, num_segments=nc)
        cnt = seg_sum(jnp.ones((x.shape[0],)), a, num_segments=nc)
        newc = sums / jnp.maximum(cnt[:, None], 1.0)
        # keep old centroid for empty clusters
        newc = jnp.where(cnt[:, None] > 0, newc, c)
        return newc, a

    assign = None
    for _ in range(iters):
        centroids, assign = step(x, centroids, n_clusters)
    # egress of the jitted k-means: ONE fused explicit transfer (two
    # np.asarray calls would each block on their own device round-trip)
    return jax.device_get((centroids, assign))


class VectorIndex:
    """Per-table vector index: exact by default, IVF above a size threshold.

    API mirrors the reference's VectorIndex surface (insert/delete/search with
    payload ids + visibility) minus the RocksDB persistence, which the storage
    tier provides."""

    def __init__(self, dim: int, metric: str = "l2", ivf_threshold: int = 65536,
                 n_clusters: int | None = None, nprobe: int = 8):
        self.dim = dim
        self.metric = metric
        self.ivf_threshold = ivf_threshold
        self.n_clusters = n_clusters
        self.nprobe = nprobe
        self._vecs = np.zeros((0, dim), np.float32)
        self._ids = np.zeros((0,), np.int64)
        self._live = np.zeros((0,), bool)
        self._device = None           # (base, valid, centroids, assign) | None

    def __len__(self):
        return int(self._live.sum())

    def add(self, ids: np.ndarray, vectors: np.ndarray):
        vectors = np.asarray(vectors, np.float32).reshape(-1, self.dim)
        ids = np.asarray(ids, np.int64)
        self._vecs = np.concatenate([self._vecs, vectors])
        self._ids = np.concatenate([self._ids, ids])
        self._live = np.concatenate([self._live, np.ones(len(ids), bool)])
        self._device = None

    def delete(self, ids) -> int:
        mask = np.isin(self._ids, np.asarray(list(ids), np.int64)) & self._live
        self._live[mask] = False
        if self._device is not None:
            # deletes only flip visibility: refresh the mask, keep the base
            # matrix and IVF centroids/assignments (no retrain)
            base, _, cent, assign = self._device
            self._device = (base, jnp.asarray(self._live), cent, assign)
        return int(mask.sum())

    def _prepare(self):
        if self._device is not None:
            return self._device
        base = jnp.asarray(self._vecs)
        valid = jnp.asarray(self._live)
        cent = assign = None
        if len(self._vecs) >= self.ivf_threshold:
            nc = self.n_clusters or max(16, int(np.sqrt(len(self._vecs))))
            c, a = kmeans(self._vecs, nc)
            cent, assign = jnp.asarray(c), jnp.asarray(a)
        self._device = (base, valid, cent, assign)
        return self._device

    def search(self, queries: np.ndarray, k: int):
        """-> (ids [q, k], scores [q, k]); dead slots get id -1."""
        if len(self._vecs) == 0:
            q = np.atleast_2d(queries).shape[0]
            return np.full((q, k), -1, np.int64), np.full((q, k), -np.inf)
        base, valid, cent, assign = self._prepare()
        q = jnp.atleast_2d(jnp.asarray(queries, jnp.float32))
        kk = min(k, base.shape[0])
        if cent is None:
            scores, idx = brute_force_topk(q, base, valid, kk, self.metric)
        else:
            scores, idx = ivf_topk(q, base, valid, cent, assign, kk,
                                   min(self.nprobe, cent.shape[0]), self.metric)
        # result egress: one fused explicit transfer for both arrays
        scores, idx = jax.device_get((scores, idx))
        scores = scores.astype(np.float64)
        idx = np.asarray(idx)
        ids = self._ids[idx]
        ids = np.where(np.isfinite(scores), ids, -1)
        if kk < k:
            pad = k - kk
            ids = np.pad(ids, ((0, 0), (0, pad)), constant_values=-1)
            scores = np.pad(scores, ((0, 0), (0, pad)), constant_values=-np.inf)
        return ids, scores
