"""Sort / Top-K kernels (reference: src/exec/sort_node.cpp,
src/runtime/sorter.cpp, topn_sorter.cpp, Acero order_by declarations in
src/exec/select_manager_node.cpp:259-265).

Multi-key ORDER BY is a composition of stable single-key argsorts from the
least-significant key to the most-significant one (classic LSD radix-style
composition).  NULL ordering follows MySQL: NULLs first under ASC, last under
DESC.  Dead rows (sel=False) always sort to the end, so LIMIT after ORDER BY
is a static slice.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..column.batch import Column, ColumnBatch
from ..types import LType


@dataclass(frozen=True)
class SortKey:
    name: str
    asc: bool = True


def _orderable(c: Column):
    d = c.data
    if d.dtype == jnp.bool_:
        d = d.astype(jnp.int32)
    return d


def sort_permutation(batch: ColumnBatch, keys: list[SortKey]):
    """Permutation putting rows in ORDER BY order, dead rows last."""
    n = len(batch)
    perm = jnp.arange(n)
    for k in reversed(keys):
        c = batch.column(k.name)
        d = _orderable(c)[perm]
        # descending argsort (not negation: negation breaks for unsigned 0
        # wraparound and INT_MIN overflow)
        perm = perm[jnp.argsort(d, stable=True, descending=not k.asc)]
        if c.validity is not None:
            v = c.validity[perm]
            # ASC: nulls first -> sort by validity ascending=False first
            keyv = v if k.asc else ~v
            perm = perm[jnp.argsort(keyv, stable=True)]
    if batch.sel is not None:
        dead = ~batch.sel[perm]
        perm = perm[jnp.argsort(dead, stable=True)]
    return perm


def sort_batch(batch: ColumnBatch, keys: list[SortKey]) -> ColumnBatch:
    perm = sort_permutation(batch, keys)
    out = batch.gather(perm)
    if batch.sel is not None:
        n = jnp.sum(batch.sel).astype(jnp.int32)
        out.sel = jnp.arange(len(batch)) < n
        out.num_rows = n
    return out


def top_k(batch: ColumnBatch, keys: list[SortKey], k: int) -> ColumnBatch:
    """ORDER BY + LIMIT k (reference: TopNSorter).  Full sort then static
    slice; the gather after slicing touches only k rows per column, so for
    k << N the HBM traffic is the sort keys, not the payload."""
    perm = sort_permutation(batch, keys)
    k = min(k, len(batch))
    perm_k = perm[:k]
    live = jnp.arange(k) < batch.live_count() if (batch.sel is not None) else None
    out = batch.gather(perm_k)
    if live is not None:
        out.sel = live
    return out
