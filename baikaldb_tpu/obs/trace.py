"""Query-lifecycle tracing: cheap nestable spans from wire to device.

The reference instruments everything with bvars and slow-SQL collection
(include/protocol/network_server.h:82-107, the print_agg_sql pipeline);
those are COUNTERS — they cannot answer "where did this query's 40 ms go:
parse, plan-cache miss, XLA compile, device execute, egress densify, raft
append, or binlog flush?".  PAPERS.md ("Query Processing on Tensor
Computation Runtimes", "Tailwind") argues host<->device handoffs dominate
TCR query latency and per-stage attribution is what makes them tunable.
This module is that attribution:

- ``root(kind, text)`` opens a per-query trace at the dispatch seam
  (session execute / wire _query); ``span(name, **attrs)`` nests stages
  under it.  Both are context managers costing one contextvar read when
  tracing is off (the ``debug_guards`` off-switch discipline: the
  ``tracing`` flag off means the shared no-op singleton, no allocation).
- Sampling is head-based (``trace_sample_n``: keep 1 in N roots) with an
  always-keep override for queries slower than ``slow_query_ms`` — spans
  record while a trace is live and the keep/drop decision lands at root
  close, so a slow query is never lost to the sampler.
- Kept traces land in a bounded in-memory store (``TRACER``), surfaced by
  SHOW PROFILES / SHOW PROFILE [FOR QUERY n], the
  ``information_schema.trace_spans`` virtual table, and
  ``TRACER.export_chrome(path)`` (chrome://tracing / Perfetto format).
- Cross-RPC propagation: ``wire_context()`` rides utils/net.py requests as
  a ``trace`` header; the serving daemon ``adopt()``s it (recording even
  when its local flag is off — the sampling decision propagates, like every
  distributed tracer), and the finished spans ship back on the response for
  ``absorb()`` to stitch into the frontend tree under one trace_id.

Spans are HOST-side objects.  Inside a jit trace they would bake into the
compiled program (timing nothing) or leak tracers — tpulint's SPANINJIT
rule rejects tracer calls in traced scope; instrumentation belongs at the
dispatch layer around ``fn(batches)``, never inside it.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
import uuid
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Optional

from ..utils import metrics
from ..utils.flags import FLAGS, define

define("tracing", False,
       "query-lifecycle span tracing: off = zero-overhead no-op spans "
       "(the debug_guards off-switch discipline); on = per-query trace "
       "trees, head-sampled by trace_sample_n with always-keep for "
       "queries over slow_query_ms")
define("trace_sample_n", 1,
       "head sampling: keep 1 in N query traces (1 = every query); "
       "slow queries (> slow_query_ms) are always kept regardless")
define("trace_store_max", 128,
       "bounded in-memory trace store: kept traces beyond this evict "
       "oldest-first (their spans count in metrics.trace_spans_dropped)")
define("trace_max_spans", 512,
       "per-trace span cap; spans beyond it drop (counted in "
       "metrics.trace_spans_dropped) so a pathological statement cannot "
       "balloon one trace")

# cached master switch (the hot path must not parse a flag per statement)
_ON = False


def _refresh(value=None) -> None:
    global _ON
    _ON = bool(FLAGS.tracing if value is None else value)


_refresh()
FLAGS.on_change("tracing", _refresh)


def on() -> bool:
    return _ON


# span ids only need uniqueness within one trace; the pid tag keeps ids
# from different processes (frontend vs store daemons) from colliding when
# remote spans stitch into one tree
_PID_TAG = format(os.getpid() & 0xFFFF, "x")
_SIDS = itertools.count(1)
_SAMPLE = itertools.count()


def _new_sid() -> str:
    return f"{_PID_TAG}.{next(_SIDS)}"


class _Ctx:
    """One live trace: the recording buffer plus the current span cursor.
    Mutated only by the thread driving the query (or, server-side, the one
    RPC handler thread that adopted it)."""

    __slots__ = ("trace_id", "span_id", "buf", "n", "dropped", "sampled",
                 "force", "keep", "max_spans", "node")

    def __init__(self, trace_id: str, parent: str = "", sampled: bool = True,
                 force: bool = False, node: str = ""):
        self.trace_id = trace_id
        self.span_id = parent        # children of the adopt seam stitch here
        self.buf: list[dict] = []
        self.n = 0
        self.dropped = 0
        self.sampled = sampled
        self.force = force
        self.keep = True
        self.max_spans = max(16, int(FLAGS.trace_max_spans))
        self.node = node


_CUR: contextvars.ContextVar[Optional[_Ctx]] = \
    contextvars.ContextVar("baikal_trace", default=None)


def _record(ctx: _Ctx, rec: dict) -> None:
    if ctx.n >= ctx.max_spans:
        ctx.dropped += 1
        metrics.trace_spans_dropped.add(1)
        return
    ctx.n += 1
    ctx.buf.append(rec)


class _Noop:
    """Shared do-nothing span: the entire cost of tracing=off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _Noop()


class _Span:
    __slots__ = ("ctx", "name", "attrs", "sid", "parent", "t0", "ts")

    def __init__(self, ctx: _Ctx, name: str, attrs: dict):
        self.ctx = ctx
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        ctx = self.ctx
        self.parent = ctx.span_id
        self.sid = _new_sid()
        ctx.span_id = self.sid
        self.ts = time.time() * 1e6
        self.t0 = time.perf_counter()
        return self

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def __exit__(self, et, ev, tb):
        ctx = self.ctx
        ctx.span_id = self.parent
        if et is not None:
            self.attrs.setdefault("error", et.__name__)
        _record(ctx, {"span_id": self.sid, "parent_id": self.parent,
                      "name": self.name, "ts_us": self.ts,
                      "dur_ms": round((time.perf_counter() - self.t0) * 1e3,
                                      4),
                      "node": ctx.node, "attrs": self.attrs})
        return False


def span(name: str, /, **attrs):
    """A child span of the active trace; the no-op singleton when no trace
    is live (one contextvar read — safe on any host path, any frequency).
    ``name`` is positional-only so attrs may freely use any keyword."""
    ctx = _CUR.get()
    if ctx is None:
        return _NOOP
    return _Span(ctx, name, attrs)


def active() -> bool:
    """True when a trace is live (one contextvar read) — lets callers skip
    building span batches whose every member would be the no-op."""
    return _CUR.get() is not None


def event(name: str, /, **attrs) -> None:
    """Zero-duration span: attach a point-in-time record (telemetry the
    renderers re-read) to the active trace."""
    ctx = _CUR.get()
    if ctx is None:
        return
    _record(ctx, {"span_id": _new_sid(), "parent_id": ctx.span_id,
                  "name": name, "ts_us": time.time() * 1e6, "dur_ms": 0.0,
                  "node": ctx.node, "attrs": attrs})


def discard() -> None:
    """Never keep the active trace (SHOW PROFILE introspection must not
    pollute the store it reads)."""
    ctx = _CUR.get()
    if ctx is not None:
        ctx.keep = False


class _Root:
    """Trace root at the dispatch seam.  Opening a root under an already
    live trace degrades to a plain child span (the wire server and the
    session both call root(); whichever runs first owns the trace)."""

    __slots__ = ("kind", "text", "force", "ctx", "token", "inner",
                 "trace_id", "query_id", "t0", "ts")

    def __init__(self, kind: str, text: str, force: bool):
        self.kind = kind
        self.text = text
        self.force = force
        self.query_id: Optional[int] = None

    def __enter__(self):
        outer = _CUR.get()
        if outer is not None:
            if self.force:
                outer.force = True   # EXPLAIN ANALYZE under a sampled-out
                #                      root: the enclosing trace must keep
                # forced sections render their OUTPUT from these span
                # records — guarantee them a full span budget even when
                # the enclosing trace (a long multi-statement batch, a
                # floor-set trace_max_spans) already spent its cap, or
                # EXPLAIN ANALYZE would silently lose its timing lines
                outer.max_spans = max(
                    outer.max_spans,
                    outer.n + max(16, int(FLAGS.trace_max_spans)))
            self.inner = _Span(outer, self.kind,
                               {"text": self.text} if self.text else {})
            self.inner.__enter__()
            self.ctx = None
            self.trace_id = outer.trace_id
            return self
        self.inner = None
        n = int(FLAGS.trace_sample_n)
        sampled = self.force or n <= 1 or (next(_SAMPLE) % n == 0)
        self.trace_id = uuid.uuid4().hex[:16]
        self.ctx = _Ctx(self.trace_id, sampled=sampled, force=self.force)
        self.ctx.span_id = _new_sid()      # children reference the root span
        self.token = _CUR.set(self.ctx)
        self.ts = time.time() * 1e6
        self.t0 = time.perf_counter()
        return self

    def set(self, **attrs):
        if self.inner is not None:
            self.inner.set(**attrs)
        return self

    def __exit__(self, et, ev, tb):
        if self.inner is not None:
            return self.inner.__exit__(et, ev, tb)
        ctx = self.ctx
        dur_ms = (time.perf_counter() - self.t0) * 1e3
        attrs = {"text": self.text} if self.text else {}
        if et is not None:
            attrs["error"] = et.__name__
        _record(ctx, {"span_id": ctx.span_id, "parent_id": "",
                      "name": self.kind, "ts_us": self.ts,
                      "dur_ms": round(dur_ms, 4), "node": ctx.node,
                      "attrs": attrs})
        _CUR.reset(self.token)
        slow = dur_ms > float(FLAGS.slow_query_ms)
        if ctx.keep and (ctx.sampled or ctx.force or slow):
            self.query_id = TRACER.store(self.kind, self.text, ctx, dur_ms)
        return False


def root(kind: str, text: str = "", force: bool = False):
    """Open a trace at a dispatch seam.  ``force`` bypasses both the
    tracing flag and the sampler (EXPLAIN ANALYZE: the span store is its
    timing source, so its trace always exists)."""
    if not _ON and not force and _CUR.get() is None:
        return _NOOP
    return _Root(kind, text, force)


# -- live-buffer introspection (EXPLAIN ANALYZE renders FROM these) ---------

def mark() -> int:
    ctx = _CUR.get()
    return len(ctx.buf) if ctx is not None else 0


def since(m: int) -> list[dict]:
    ctx = _CUR.get()
    return list(ctx.buf[m:]) if ctx is not None else []


# -- cross-RPC propagation ---------------------------------------------------

def wire_context() -> Optional[dict]:
    """The header utils/net.py attaches to outbound RPCs, or None when no
    trace is live (the common case: zero wire overhead)."""
    ctx = _CUR.get()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "parent_span": ctx.span_id}


@contextmanager
def adopt(wire: dict, name: str, node: str = ""):
    """Server-side: record handler spans under the caller's trace/span ids.
    Yields the live span buffer; after the block it holds every finished
    span dict, ready to ship back on the response.  Recording ignores the
    local tracing flag — the caller already made the sampling decision and
    it propagates (standard distributed-tracer semantics)."""
    tid = str(wire.get("trace_id") or "")
    if not tid:
        yield []
        return
    ctx = _Ctx(tid, parent=str(wire.get("parent_span") or ""), node=node)
    token = _CUR.set(ctx)
    sp = _Span(ctx, name, {})
    sp.__enter__()
    try:
        yield ctx.buf
    finally:
        sp.__exit__(None, None, None)
        _CUR.reset(token)


def absorb(spans: list) -> None:
    """Client-side: stitch spans a peer shipped back into the live trace
    (they already carry this trace's ids — parent pointers land on the
    rpc span that crossed the wire)."""
    ctx = _CUR.get()
    if ctx is None or not isinstance(spans, list):
        return
    for s in spans:
        if isinstance(s, dict) and s.get("span_id"):
            _record(ctx, s)


# -- the bounded trace store -------------------------------------------------

class Tracer:
    """Kept traces, query-id keyed, oldest-evicted (the slow-SQL ring of
    the reference, upgraded from one log line to a span tree)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._traces: "OrderedDict[int, dict]" = OrderedDict()
        self._qids = itertools.count(1)

    def store(self, kind: str, text: str, ctx: _Ctx, dur_ms: float) -> int:
        rec = {"trace_id": ctx.trace_id, "kind": kind, "text": text,
               "duration_ms": round(dur_ms, 4), "spans": list(ctx.buf),
               "dropped": ctx.dropped, "ts": time.time()}
        with self._mu:
            qid = next(self._qids)
            rec["query_id"] = qid
            self._traces[qid] = rec
            cap = max(1, int(FLAGS.trace_store_max))
            while len(self._traces) > cap:
                _, old = self._traces.popitem(last=False)
                metrics.trace_spans_dropped.add(len(old["spans"]))
        metrics.traces_sampled.add(1)
        return qid

    def get(self, query_id: int) -> Optional[dict]:
        with self._mu:
            return self._traces.get(int(query_id))

    def last(self) -> Optional[dict]:
        with self._mu:
            if not self._traces:
                return None
            return next(reversed(self._traces.values()))

    def by_trace(self, trace_id: str) -> Optional[dict]:
        with self._mu:
            for rec in reversed(self._traces.values()):
                if rec["trace_id"] == trace_id:
                    return rec
        return None

    def list(self) -> list[dict]:
        with self._mu:
            return list(self._traces.values())

    def clear(self) -> None:
        with self._mu:
            self._traces.clear()

    def export_chrome(self, path: str,
                      query_id: Optional[int] = None) -> int:
        """Write kept traces (or one) as Chrome trace_event JSON — load in
        chrome://tracing or https://ui.perfetto.dev.  Returns the event
        count.  Nodes (frontend / each store daemon) render as processes."""
        recs = [self.get(query_id)] if query_id is not None else self.list()
        recs = [r for r in recs if r is not None]
        pids: dict[str, int] = {}
        events: list[dict] = []
        for rec in recs:
            for s in rec["spans"]:
                node = s.get("node") or "frontend"
                pid = pids.setdefault(node, len(pids) + 1)
                args = {"trace_id": rec["trace_id"],
                        "query_id": rec["query_id"]}
                args.update(s.get("attrs") or {})
                events.append({"name": s["name"], "ph": "X",
                               "ts": s["ts_us"],
                               "dur": s["dur_ms"] * 1e3,
                               "pid": pid, "tid": pid, "args": args})
        meta = [{"name": "process_name", "ph": "M", "pid": pid,
                 "args": {"name": node}} for node, pid in pids.items()]
        with open(path, "w") as f:
            json.dump({"traceEvents": meta + events,
                       "displayTimeUnit": "ms"}, f, default=str)
        return len(events)


TRACER = Tracer()


def span_tree(rec: dict) -> list[tuple[int, dict]]:
    """DFS-flatten a kept trace's spans to (depth, span) rows, children
    ordered by start time — the SHOW PROFILE rendering order.  Spans whose
    parent is missing (dropped by the cap, or a remote fragment whose rpc
    parent was evicted) root at depth 0."""
    spans = rec["spans"]
    by_id = {s["span_id"]: s for s in spans}
    kids: dict[str, list[dict]] = {}
    roots: list[dict] = []
    for s in spans:
        p = s.get("parent_id") or ""
        if p and p in by_id:
            kids.setdefault(p, []).append(s)
        else:
            roots.append(s)
    out: list[tuple[int, dict]] = []

    def walk(s: dict, depth: int) -> None:
        out.append((depth, s))
        for c in sorted(kids.get(s["span_id"], ()),
                        key=lambda x: x["ts_us"]):
            walk(c, depth + 1)

    for r in sorted(roots, key=lambda x: x["ts_us"]):
        walk(r, 0)
    return out
