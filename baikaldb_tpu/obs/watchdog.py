"""Fleet watchdogs: detect wedged queries and stuck daemons, loudly.

A fleet of accelerator-backed daemons fails in ways counters don't show:
a query wedged on a dead peer's RPC, a device collective that never
completes (every participant blocked in one XLA program), a raft apply
loop that stopped draining committed entries.  All three have the same
observable signature — SOMETHING THAT SHOULD ADVANCE STOPPED ADVANCING —
so the watchdog is one generic scanner with pluggable probes:

- :class:`QueryWatchdog` (frontend): a live query whose last progress
  beat (obs/progress.py) is older than ``watchdog_stall_s`` is stalled.
  A wedged collective surfaces here too: the query sits in its exec
  phase with no beat, because the one thread that would beat is blocked
  in the device call.
- :class:`StoreWatchdog` (store daemon): the raft tick loop going silent
  (elections stop, every region freezes), and a region whose apply lag
  is nonzero while applied_index stopped moving (committed entries not
  draining).

Detections count ONCE per continuous stall episode in
``metrics.watchdog_stalls_detected`` and surface three ways: the daemon
``health`` RPC, ``SHOW STATUS`` ``health.*`` rows, and the stalled
query's own SHOW PROCESSLIST State cell (flagged STALLED).  Scans run on
a detached per-daemon thread (``watchdog_interval_s``) or synchronously
via ``scan_now()`` — never on a query path, never touching device state.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..utils import metrics
from ..utils.flags import FLAGS, define
from .progress import PROGRESS

define("watchdog_stall_s", 5.0,
       "a live query with no progress beat (or a raft apply lag with no "
       "applied_index movement) for this many seconds is a stall")
define("watchdog_interval_s", 1.0,
       "watchdog scan period for the background thread; scans are a "
       "registry walk, no locks shared with the query path")


class Watchdog:
    """Generic stall scanner.  Subclasses implement ``probe() ->
    [(subject, detail), ...]`` returning everything CURRENTLY stalled;
    the base class handles episode dedup, counters, the background
    thread, and the health/status renderings."""

    # ranked below store.table_lock(10): StoreWatchdog.probe reads the
    # region map while holding the scan lock, never the other way around
    RANK = 8

    def __init__(self, name: str = "frontend"):
        from ..analysis.runtime import GuardedLock

        self.name = name
        self._mu = GuardedLock("watchdog.scan_mu", rank=self.RANK)
        self._live: dict[str, dict] = {}      # subject -> stall record
        self._detected_total = 0
        self._last_scan = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- override point ----------------------------------------------------
    def probe(self) -> list[tuple[str, str]]:
        return []

    # -- scanning ----------------------------------------------------------
    def scan_now(self) -> list[dict]:
        """One synchronous scan; -> the currently-live stall records.
        The probe runs under _mu too: the background thread and a health
        RPC can scan concurrently, and StoreWatchdog.probe mutates its
        _apply_seen tracking dict in place."""
        with self._mu:
            found = dict(self.probe())
            now = time.time()
            self._last_scan = now
            for subject, detail in found.items():
                rec = self._live.get(subject)
                if rec is not None:
                    rec["detail"] = detail
                else:
                    # new episode: count once, hold until it recovers
                    self._live[subject] = {"subject": subject,
                                           "detail": detail, "since": now}
                    self._detected_total += 1
                    metrics.watchdog_stalls_detected.add(1)
            for subject in list(self._live):
                if subject not in found:      # recovered: a later re-stall
                    del self._live[subject]   # is a new episode
            return [dict(r) for r in self._live.values()]

    def health(self) -> dict:
        """The ``health`` RPC body / dashboard unit."""
        stalls = self.scan_now()
        with self._mu:
            total = self._detected_total
        return {"daemon": self.name,
                "status": "stalled" if stalls else "ok",
                "stalls": stalls, "stalls_detected": total,
                "ts": time.time()}

    def status_rows(self) -> dict:
        """SHOW STATUS rows (string values, ``health.`` prefixed)."""
        h = self.health()
        return {"health.status": h["status"],
                "health.stalls_live": str(len(h["stalls"])),
                "health.stalls_detected": str(h["stalls_detected"]),
                "health.watchdog": self.name}

    # -- lifecycle ---------------------------------------------------------
    def start(self, interval_s: Optional[float] = None) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.scan_now()
                except Exception:                       # noqa: BLE001 — the
                    # watchdog must never die of what it watches
                    metrics.count_swallowed(f"watchdog.{self.name}")
                self._stop.wait(max(0.05, float(
                    interval_s if interval_s is not None
                    else FLAGS.watchdog_interval_s)))

        self._thread = threading.Thread(
            target=loop, daemon=True, name=f"watchdog-{self.name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None


class QueryWatchdog(Watchdog):
    """Frontend: wedged-query detection over the live progress registry
    (filtered to one Database identity — engines coexist in-process)."""

    def __init__(self, db=None, name: str = "frontend"):
        super().__init__(name=name)
        self.db = db

    def probe(self) -> list[tuple[str, str]]:
        stall_s = max(0.1, float(FLAGS.watchdog_stall_s))
        now = time.monotonic()
        out = []
        for qp in PROGRESS.live(self.db):
            age = now - qp.beat_mono
            if age > stall_s:
                qp.stalled = True     # SHOW PROCESSLIST State flags it
                out.append((f"query:{qp.query_id}",
                            f"no progress beat for {age:.1f}s "
                            f"(conn {qp.conn_id}, phase {qp.phase}"
                            f"{', op ' + qp.operator if qp.operator else ''})"
                            ))
            elif qp.stalled:          # beating again: drop the flag so the
                qp.stalled = False    # State cell reflects the recovery
        return out


class StoreWatchdog(Watchdog):
    """Store daemon: raft-clock liveness + apply-lag drain.  Reads the
    region map under the store's core lock exactly like the telemetry
    scrape does; per-scan cost is a few fields per region."""

    def __init__(self, store):
        super().__init__(name=f"store-{store.store_id}")
        self.store = store
        # region -> (applied_index, first seen stuck at, monotonic ts)
        self._apply_seen: dict[int, tuple[int, float]] = {}

    def probe(self) -> list[tuple[str, str]]:
        stall_s = max(0.1, float(FLAGS.watchdog_stall_s))
        now = time.monotonic()
        out: list[tuple[str, str]] = []
        last_tick = getattr(self.store, "_last_tick", None)
        if last_tick is not None and not self.store._stop.is_set() \
                and now - last_tick > stall_s:
            out.append(("tick",
                        f"raft clock silent for {now - last_tick:.1f}s"))
        with self.store._mu:
            snap = [(rid, r.core.commit_index, r.applied_index)
                    for rid, r in self.store.regions.items()]
        for rid, commit, applied in snap:
            lag = max(0, commit - applied)
            if lag <= 0:
                self._apply_seen.pop(rid, None)
                continue
            prev = self._apply_seen.get(rid)
            if prev is None or applied > prev[0]:
                self._apply_seen[rid] = (applied, now)   # still draining
                continue
            if now - prev[1] > stall_s:
                out.append((f"region:{rid}",
                            f"apply lag {lag} stuck for "
                            f"{now - prev[1]:.1f}s (applied={applied})"))
        stale = set(self._apply_seen) - {rid for rid, _, _ in snap}
        for rid in stale:                     # dropped/migrated region
            self._apply_seen.pop(rid, None)
        return out


# lockset witness enrollment (see analysis/runtime.py): stall records are
# mutated by the scan thread and health RPCs concurrently
from ..analysis.runtime import LOCK_RANKS as _LOCK_RANKS  # noqa: E402
from ..analysis.runtime import register_witness  # noqa: E402

register_witness(Watchdog, "baikaldb_tpu/obs/watchdog.py:Watchdog")
_LOCK_RANKS.setdefault("watchdog.scan_mu", Watchdog.RANK)
