"""Live query introspection: per-query progress beats + cooperative KILL.

The reference operates BaikalDB as a shared fleet: SHOW PROCESSLIST and
KILL are how an operator sees and stops a runaway query
(src/protocol/show_helper.cpp processlist rendering, the kill path through
state_machine.cpp).  On a tensor runtime the need is sharper — PAPERS.md
("Query Processing on Tensor Computation Runtimes", "Tailwind") — because
the execute phase is one opaque device program: progress attribution must
come from the HOST seams around it, never from inside it.

This module is the registry both features share:

- ``track(...)`` opens a :class:`QueryProgress` for one statement at the
  session dispatch seam (a contextvar next to the obs/trace root; nested
  opens degrade to the outer record).  Live records are registered in the
  process-global :data:`PROGRESS` table so OTHER threads — SHOW
  PROCESSLIST, the watchdog, a KILL from another connection — can read
  them.
- ``beat(phase=..., operator=..., batches_done=...)`` hooks ride the
  existing span seams (``exec.batches``, ``mpp.*``, ``batch.enqueue``,
  ``egress.*``): plain attribute writes under the GIL, nothing shared is
  locked on the query path, and NO device sync is ever introduced —
  tpulint's PROGRESSINJIT rule rejects any beat/checkpoint in jit-traced
  scope, exactly like spans.
- every beat is also a cancellation point: ``KILL QUERY <id>`` flips the
  record's :class:`CancelToken`, and the next beat (batch boundary,
  shuffle-round boundary, dispatch queue wait, idempotent RPC wait) raises
  :class:`QueryKilled` — mapped to MySQL error 1317 (ER_QUERY_INTERRUPTED)
  by server/errors.py.  Checks sit only at side-effect-free points, so a
  killed DML is fully applied or fully absent (exactly-once preserved).

The ``progress_tracking`` flag (default ON — processlist is an always-on
operator surface) gates everything behind the cached-module-bool
off-switch discipline: off means the shared no-op record, one attribute
read per hook, and KILL degrades to "Unknown thread id".
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from typing import Optional

from ..utils import metrics
from ..utils.flags import FLAGS, define

define("progress_tracking", True,
       "per-query live progress records (SHOW PROCESSLIST phase/operator/"
       "batches, KILL targeting, watchdog beats); off = the shared no-op "
       "record — no registry writes, and KILL cannot find queries")

# cached master switch (the per-statement path must not parse a flag)
_ON = True


def _refresh(value=None) -> None:
    global _ON
    _ON = bool(FLAGS.progress_tracking if value is None else value)


_refresh()
FLAGS.on_change("progress_tracking", _refresh)


def on() -> bool:
    return _ON


# MySQL's exact ER_QUERY_INTERRUPTED text: server/errors.py pattern-maps
# it to errno 1317 / sqlstate 70100
_KILLED_MSG = "Query execution was interrupted"


class QueryKilled(RuntimeError):
    """Cooperative cancellation: raised at the next progress beat after a
    KILL flipped this query's token.  NOT an OSError — it must fly past
    the RPC client's transport-retry handlers untouched."""

    def __init__(self, msg: str = _KILLED_MSG):
        super().__init__(msg)


class CancelToken:
    """One flag per query, flipped by the killer's thread, polled by the
    victim's.  A bare bool write/read under the GIL — no lock on the
    query path."""

    __slots__ = ("_killed", "reason")

    def __init__(self):
        self._killed = False
        self.reason = ""

    def kill(self, reason: str = "killed") -> None:
        self.reason = reason
        self._killed = True

    def killed(self) -> bool:
        return self._killed

    def check(self) -> None:
        if self._killed:
            raise QueryKilled()


_QIDS = itertools.count(1)


class QueryProgress:
    """One live statement's operator-visible state.  Written only by the
    thread driving the query; read racily (single attribute loads) by
    processlist renderers, the watchdog, and KILL — every field is a
    scalar or immutable, so a torn read is impossible."""

    __slots__ = ("query_id", "conn_id", "user", "host", "db", "dbname",
                 "text", "command", "phase", "operator", "batches_done",
                 "batches_total", "rows_done", "rows_est", "round_no",
                 "rounds_total", "chunk_no", "chunks_total",
                 "queue_wait_ms", "started", "beat_mono",
                 "token", "plan", "exchange", "stalled", "_phase_mono",
                 "_phase_ms")

    def __init__(self, text: str, conn_id: int = 0, user: str = "",
                 host: str = "embedded", db=None, dbname: str = ""):
        self.query_id = next(_QIDS)
        self.conn_id = conn_id
        self.user = user
        self.host = host
        self.db = db                 # Database identity, filters registry
        self.dbname = dbname
        self.text = text
        self.command = "Query"
        self.phase = "starting"
        self.operator = ""
        self.batches_done = 0
        self.batches_total = 0
        self.rows_done = 0
        self.rows_est = 0
        self.round_no = 0
        self.rounds_total = 0
        self.chunk_no = 0            # streamed scan: chunks folded so far
        self.chunks_total = 0        # streamed scan: chunks kept post-prune
        self.queue_wait_ms = 0.0
        self.started = time.time()
        self.beat_mono = time.monotonic()
        self.token = CancelToken()
        self.plan = None             # host plan object, for forensic dumps
        self.exchange = None         # exchange_summary dict when MPP ran
        self.stalled = False         # set by the watchdog, never cleared
        self._phase_mono = self.beat_mono
        self._phase_ms: dict[str, float] = {}

    # -- the hot hook ------------------------------------------------------
    def beat(self, phase: Optional[str] = None,
             operator: Optional[str] = None, **counts) -> None:
        """Progress heartbeat + cancellation point.  Attribute writes only;
        raises QueryKilled when this query was killed."""
        now = time.monotonic()
        self.beat_mono = now
        if phase is not None and phase != self.phase:
            # close the previous phase's wall-clock bucket (the query_log
            # fallback timing source when tracing is off)
            self._phase_ms[self.phase.split(".", 1)[0]] = \
                self._phase_ms.get(self.phase.split(".", 1)[0], 0.0) + \
                (now - self._phase_mono) * 1e3
            self._phase_mono = now
            self.phase = phase
        if operator is not None:
            self.operator = operator
        for k, v in counts.items():
            setattr(self, k, v)
        self.token.check()

    def checkpoint(self) -> None:
        """Cancellation point without a state change (loop tops)."""
        self.beat_mono = time.monotonic()
        self.token.check()

    def phase_ms(self) -> dict:
        """Closed per-phase wall-clock buckets so far (ms), keyed by the
        phase's first dotted segment (parse/plan/exec/egress)."""
        return dict(self._phase_ms)

    def elapsed_s(self) -> float:
        return max(0.0, time.time() - self.started)

    def row(self) -> dict:
        """One information_schema.processlist row (racy snapshot)."""
        return {
            "id": self.conn_id, "user": self.user, "host": self.host,
            "db": self.dbname, "command": self.command,
            "time_s": int(self.elapsed_s()), "state": self.state(),
            "info": self.text, "query_id": self.query_id,
            "phase": self.phase, "operator": self.operator,
            "batches_done": self.batches_done,
            "batches_total": self.batches_total,
            "rows_done": self.rows_done, "rows_est": self.rows_est,
            "round": self.round_no, "rounds_total": self.rounds_total,
            "chunk_no": self.chunk_no, "chunks_total": self.chunks_total,
            "queue_wait_ms": round(self.queue_wait_ms, 3),
            "elapsed_ms": round(self.elapsed_s() * 1e3, 3),
        }

    def state(self) -> str:
        """The SHOW PROCESSLIST State cell: phase, operator, and whichever
        progress counters are live."""
        parts = [self.phase]
        if self.operator:
            parts.append(self.operator)
        if self.batches_total:
            parts.append(f"batch {self.batches_done}/{self.batches_total}")
        if self.rows_est:
            parts.append(f"rows {self.rows_done}/{self.rows_est}")
        if self.rounds_total:
            parts.append(f"round {self.round_no}/{self.rounds_total}")
        if self.chunks_total:
            parts.append(f"chunk {self.chunk_no}/{self.chunks_total}")
        if self.stalled:
            parts.append("STALLED")
        return " ".join(parts)


class _NoopProgress:
    """Shared do-nothing record: the entire cost of progress_tracking=off.
    Carries a token so KILL checks stay structurally identical."""

    __slots__ = ()
    query_id = 0
    token = CancelToken()

    def beat(self, phase=None, operator=None, **counts):
        return None

    def checkpoint(self):
        return None

    def phase_ms(self):
        return {}


_NOOP = _NoopProgress()

_CUR: contextvars.ContextVar[Optional[QueryProgress]] = \
    contextvars.ContextVar("baikal_progress", default=None)


def current():
    """The live record, or the no-op when none (one contextvar read —
    safe at any host-path frequency)."""
    qp = _CUR.get()
    return qp if qp is not None else _NOOP


def cancel_token() -> Optional[CancelToken]:
    """The live query's cancel token, or None — what utils/net.py polls
    to make idempotent RPC waits interruptible."""
    qp = _CUR.get()
    return qp.token if qp is not None else None


class _Track:
    """Context manager registering one QueryProgress for the statement;
    nested opens (wire server then session.execute) degrade to the outer
    record so one connection shows one processlist row."""

    __slots__ = ("qp", "_token", "_nested")

    def __init__(self, qp: QueryProgress):
        self.qp = qp

    def __enter__(self):
        outer = _CUR.get()
        if outer is not None:
            self._nested = True
            self.qp = outer
            return outer
        self._nested = False
        PROGRESS.register(self.qp)
        self._token = _CUR.set(self.qp)
        return self.qp

    def __exit__(self, et, ev, tb):
        if not self._nested:
            _CUR.reset(self._token)
            PROGRESS.unregister(self.qp)
        return False


class _NoopTrack:
    __slots__ = ()

    def __enter__(self):
        return _NOOP

    def __exit__(self, *exc):
        return False


_NOOP_TRACK = _NoopTrack()


def track(text: str, conn_id: int = 0, user: str = "",
          host: str = "embedded", db=None, dbname: str = ""):
    """Open the progress record at the session dispatch seam."""
    if not _ON:
        return _NOOP_TRACK
    return _Track(QueryProgress(text, conn_id=conn_id, user=user, host=host,
                                db=db, dbname=dbname))


class _Registry:
    """Process-global table of live queries, query-id keyed.  Engine
    instances coexist in one process (every test builds its own Database),
    so readers filter by the record's ``db`` identity."""

    def __init__(self):
        self._mu = threading.Lock()
        self._live: dict[int, QueryProgress] = {}

    def register(self, qp: QueryProgress) -> None:
        with self._mu:
            self._live[qp.query_id] = qp

    def unregister(self, qp: QueryProgress) -> None:
        with self._mu:
            self._live.pop(qp.query_id, None)

    def live(self, db=None) -> list[QueryProgress]:
        with self._mu:
            qps = list(self._live.values())
        if db is None:
            return qps
        return [q for q in qps if q.db is db]

    def kill(self, conn_id: Optional[int] = None,
             query_id: Optional[int] = None, db=None,
             reason: str = "killed") -> int:
        """Flip the cancel token of every matching live query; -> count.
        The killer only writes the token — the victim's own thread raises
        at its next beat, so no cross-thread exception injection."""
        n = 0
        for qp in self.live(db):
            if conn_id is not None and qp.conn_id != conn_id:
                continue
            if query_id is not None and qp.query_id != query_id:
                continue
            qp.token.kill(reason)
            n += 1
        if n:
            metrics.queries_killed.add(n)
        return n


PROGRESS = _Registry()
