"""Cluster telemetry plane: per-daemon snapshot polling, fleet merging,
and Prometheus exposition.

The reference dumps per-process bvars to the brpc HTTP port and leaves
cross-fleet aggregation to the scraper; our daemons instead expose one
``rpc_metrics`` snapshot method on the existing RPC plane (utils/net.py)
and the frontend carries this module's :class:`Telemetry` poller:

- each registered daemon is polled under the PR 5 retry policy (deadline
  budget + jittered resends inside one ``telemetry_rpc_timeout_s``); an
  unreachable daemon keeps its LAST snapshot, marked stale, so
  ``information_schema.cluster_metrics`` still answers with the rest of
  the fleet (bounded degradation, never an error),
- merging is type-aware: **counters sum**, **histograms sum bucket-wise**
  (exact — integer bin counts over identical fixed bounds), **gauges and
  latency rings keep per-daemon rows** (a ring of recent raw samples has
  no meaningful cross-process sum),
- any registry snapshot renders as Prometheus text exposition
  (``# TYPE`` / labels / cumulative ``_bucket`` lines), served over HTTP
  by :func:`start_http_exporter` (daemon ``--metrics-port``,
  tools/metrics_export.py) and returned in-band by each daemon's
  ``rpc_prometheus`` method.

Merging is deterministic: daemons are folded in sorted-name order, so the
merged row is a pure function of the snapshot SET, not of poll arrival
order (tests/test_metrics_plane.py pins this).
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Optional

from ..utils import metrics
from ..utils.flags import FLAGS, define
from ..utils.metrics import histogram_stats

define("telemetry_poll_s", 2.0,
       "background fleet-telemetry poll period (started automatically in "
       "cluster mode); 0 disables the thread (information_schema."
       "cluster_metrics then polls inline per query); also the re-probe "
       "holdoff for a daemon whose last scrape failed")
define("telemetry_rpc_timeout_s", 2.0,
       "per-daemon deadline budget for one telemetry scrape RPC (rides "
       "the utils/net.py retry policy); an exhausted budget marks the "
       "daemon's rows stale instead of failing the query")

FLEET = "fleet"          # pseudo-daemon name of the merged rows

# snapshot row fields that are NOT scalar values (carried for merging /
# exposition, not rendered as cluster_metrics rows)
_STRUCT_FIELDS = ("labels", "le", "buckets")


# -- fleet merging -----------------------------------------------------------

def merge_snapshots(snaps: dict[str, dict]) -> dict:
    """Merge per-daemon registry snapshots into one fleet snapshot holding
    the SUMMABLE metrics only: counters sum, histograms sum bucket-wise.
    Gauges and latency rings are per-daemon facts — they stay out of the
    merge and render as per-daemon rows.

    Deterministic: daemons fold in sorted-name order, so any poll order
    produces the identical result; bucket counts are integers, so the
    histogram merge is exact.  Histograms whose bucket bounds differ from
    the first-seen bounds are skipped (counted per metric in
    ``swallowed.telemetry.bucket_mismatch``) — summing mismatched bins
    would silently corrupt quantiles."""
    merged: dict = {}
    for daemon in sorted(snaps):
        for name, ent in (snaps[daemon] or {}).items():
            kind = ent.get("kind")
            if kind not in ("counter", "histogram"):
                continue
            m = merged.setdefault(
                name, {"kind": kind,
                       "label_names": list(ent.get("label_names", ())),
                       "rows": {}})
            if m["kind"] != kind:
                metrics.count_swallowed("telemetry.kind_mismatch")
                continue
            for row in ent.get("rows", ()):
                key = tuple(row.get("labels", ()))
                acc = m["rows"].get(key)
                if kind == "counter":
                    if acc is None:
                        m["rows"][key] = {
                            "labels": list(key),
                            "value": float(row.get("value", 0) or 0),
                            "per_second": float(
                                row.get("per_second", 0) or 0)}
                    else:
                        acc["value"] += float(row.get("value", 0) or 0)
                        acc["per_second"] += float(
                            row.get("per_second", 0) or 0)
                else:
                    le = list(row.get("le", ()))
                    buckets = list(row.get("buckets", ()))
                    if acc is None:
                        m["rows"][key] = {
                            "labels": list(key), "le": le,
                            "buckets": [int(b) for b in buckets],
                            "count": float(row.get("count", 0) or 0),
                            "sum": float(row.get("sum", 0) or 0)}
                    elif acc["le"] != le or \
                            len(acc["buckets"]) != len(buckets):
                        metrics.count_swallowed("telemetry.bucket_mismatch")
                    else:
                        acc["buckets"] = [a + int(b) for a, b in
                                          zip(acc["buckets"], buckets)]
                        acc["count"] += float(row.get("count", 0) or 0)
                        acc["sum"] += float(row.get("sum", 0) or 0)
    out: dict = {}
    for name in sorted(merged):
        ent = merged[name]
        rows = []
        for key in sorted(ent["rows"]):
            row = ent["rows"][key]
            if ent["kind"] == "histogram":
                stats = histogram_stats(row["le"], row["buckets"],
                                        row["count"], row["sum"])
                row = {"labels": row["labels"], **stats,
                       "le": row["le"], "buckets": row["buckets"]}
            rows.append(row)
        out[name] = {"kind": ent["kind"],
                     "label_names": ent["label_names"], "rows": rows}
    return out


# -- Prometheus text exposition ---------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str) -> str:
    n = _NAME_RE.sub("_", name)
    if not re.match(r"[a-zA-Z_:]", n):
        n = "_" + n
    return prefix + n


def _prom_labels(pairs: list[tuple[str, str]]) -> str:
    if not pairs:
        return ""
    body = ",".join(
        '%s="%s"' % (_NAME_RE.sub("_", k),
                     str(v).replace("\\", r"\\").replace('"', r'\"')
                     .replace("\n", r"\n"))
        for k, v in pairs)
    return "{" + body + "}"


def _fmt(v) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(snapshot: dict, prefix: str = "baikal_",
                      const_labels: Optional[dict] = None) -> str:
    """One registry snapshot -> Prometheus text exposition format 0.0.4.

    counters -> ``counter``; gauges -> ``gauge``; histograms -> classic
    ``histogram`` (cumulative ``_bucket{le=...}`` + ``_sum``/``_count``);
    latency rings -> ``summary`` with quantile rows.  ``const_labels``
    (e.g. ``{"daemon": "127.0.0.1:9101"}``) stamp every sample —
    how per-daemon identity survives a fleet-merged scrape."""
    return render_fleet_prometheus({"": snapshot}, prefix=prefix,
                                   base_labels=const_labels)


def render_fleet_prometheus(snaps: dict[str, dict], prefix: str = "baikal_",
                            base_labels: Optional[dict] = None) -> str:
    """Several (daemon name -> snapshot) blocks rendered as ONE exposition:
    each metric name declares its ``# TYPE`` once, with every daemon's
    samples grouped under it carrying a ``daemon`` label (empty daemon
    name = no label, the single-process case)."""
    base = list((base_labels or {}).items())
    by_name: dict[str, dict] = {}
    for daemon in sorted(snaps):
        for name, ent in (snaps[daemon] or {}).items():
            slot = by_name.setdefault(name, {"kind": ent.get("kind"),
                                             "label_names":
                                             list(ent.get("label_names",
                                                          ())),
                                             "samples": []})
            for row in ent.get("rows", ()):
                slot["samples"].append((daemon, row))
    lines: list[str] = []
    for name in sorted(by_name):
        ent = by_name[name]
        kind = ent["kind"]
        pname = _prom_name(name, prefix)
        ptype = {"counter": "counter", "gauge": "gauge",
                 "histogram": "histogram", "latency": "summary"}.get(
                     kind, "untyped")
        lines.append(f"# TYPE {pname} {ptype}")
        for daemon, row in ent["samples"]:
            labels = list(base)
            if daemon:
                labels.append(("daemon", daemon))
            labels += list(zip(ent["label_names"], row.get("labels", ())))
            if kind == "counter":
                lines.append(f"{pname}{_prom_labels(labels)} "
                             f"{_fmt(row.get('value', 0))}")
            elif kind == "gauge":
                lines.append(f"{pname}{_prom_labels(labels)} "
                             f"{_fmt(row.get('value', float('nan')))}")
            elif kind == "histogram":
                cum = 0
                le = row.get("le", ())
                buckets = row.get("buckets", ())
                for bound, c in zip(le, buckets):
                    cum += int(c)
                    lines.append(
                        f"{pname}_bucket"
                        f"{_prom_labels(labels + [('le', format(bound, 'g'))])}"
                        f" {cum}")
                cum += int(buckets[len(le)]) if len(buckets) > len(le) else 0
                lines.append(f"{pname}_bucket"
                             f"{_prom_labels(labels + [('le', '+Inf')])}"
                             f" {cum}")
                lines.append(f"{pname}_sum{_prom_labels(labels)} "
                             f"{_fmt(row.get('sum', 0))}")
                lines.append(f"{pname}_count{_prom_labels(labels)} "
                             f"{_fmt(row.get('count', 0))}")
            elif kind == "latency":
                for q, f in (("0.5", "p50_ms"), ("0.95", "p95_ms"),
                             ("0.99", "p99_ms")):
                    lines.append(
                        f"{pname}{_prom_labels(labels + [('quantile', q)])}"
                        f" {_fmt(row.get(f, 0))}")
                n = float(row.get("count", 0) or 0)
                lines.append(f"{pname}_sum{_prom_labels(labels)} "
                             f"{_fmt(n * float(row.get('avg_ms', 0) or 0))}")
                lines.append(f"{pname}_count{_prom_labels(labels)} "
                             f"{_fmt(n)}")
    return "\n".join(lines) + "\n"


# -- device-resource gauges --------------------------------------------------

def install_device_gauges(registry) -> None:
    """Accelerator memory gauges sampled at dump time: bytes in use / peak
    / limit summed over local devices.  Backends without memory_stats (CPU)
    report NaN — the row stays visible so dashboards show the gap, and a
    raising fn is already swallowed+counted by Gauge.stats()."""
    def mk(field: str):
        def fn():
            import jax
            total, seen = 0.0, False
            for d in jax.local_devices():
                ms = d.memory_stats()
                if ms and field in ms:
                    total += float(ms[field])
                    seen = True
            return total if seen else float("nan")
        return fn

    registry.gauge("device_hbm_in_use_bytes", fn=mk("bytes_in_use"))
    registry.gauge("device_hbm_peak_bytes", fn=mk("peak_bytes_in_use"))
    registry.gauge("device_hbm_limit_bytes", fn=mk("bytes_limit"))


# -- process-resource gauges -------------------------------------------------

_PROC_STARTED = time.time()


def install_process_gauges(registry) -> None:
    """OS-process gauges sampled at dump time — RSS, thread count, open
    fds, uptime, GC collections — so a watchdog stall correlates with
    resource pressure in the same scrape.  Standard library only (/proc +
    resource + gc); platforms without /proc report NaN, and a raising fn
    is already swallowed+counted by Gauge.stats()."""
    import gc
    import os

    def rss_bytes():
        try:
            with open("/proc/self/statm") as f:
                return float(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
        except (OSError, IndexError, ValueError):
            import resource
            # ru_maxrss is the PEAK (KiB on linux) — better than nothing
            # where /proc is absent
            return float(resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss) * 1024.0

    def thread_count():
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("Threads:"):
                        return float(line.split()[1])
        except OSError:
            pass
        import threading as _t
        return float(_t.active_count())      # python threads only

    def open_fds():
        try:
            return float(len(os.listdir("/proc/self/fd")))
        except OSError:
            return float("nan")

    def gc_collections():
        return float(sum(s.get("collections", 0) for s in gc.get_stats()))

    registry.gauge("process_rss_bytes", fn=rss_bytes)
    registry.gauge("process_threads", fn=thread_count)
    registry.gauge("process_open_fds", fn=open_fds)
    registry.gauge("process_uptime_s", fn=lambda: time.time() - _PROC_STARTED)
    registry.gauge("process_gc_collections", fn=gc_collections)


# -- the fleet poller --------------------------------------------------------

class Telemetry:
    """One per Database: registered daemon addresses, their cached
    snapshots with staleness state, and the merged fleet view."""

    # below store.table_lock(10): scrape bookkeeping never wraps storage
    RANK = 6

    def __init__(self, local_name: str = "frontend", registry=None,
                 device_gauges: bool = True):
        self.local_name = local_name
        self.registry = registry if registry is not None \
            else metrics.REGISTRY
        # registration + cache dict; ranked GuardedLock so the lockset
        # witness can assert _clients/_cache stay under it (RPC scrapes
        # themselves run OUTSIDE the lock — see poll)
        from ..analysis.runtime import GuardedLock
        self._mu = GuardedLock("telemetry.scrape_mu", rank=self.RANK)
        self._clients: dict[str, object] = {}
        # addr -> {"snapshot", "ts", "ok", "error"}; kept across failures
        # so a down daemon's last-known rows survive, marked stale
        self._cache: dict[str, dict] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._meta_addr: Optional[str] = None
        if device_gauges:
            install_device_gauges(self.registry)
            install_process_gauges(self.registry)

    # -- registration ------------------------------------------------------
    def attach_meta(self, meta_address: str) -> None:
        """Fleet self-discovery for the three-binary deployment: the meta
        daemon joins the scrape set, and every poll refreshes the store
        list from its ``instances`` registry — late-joining stores appear
        without frontend config."""
        self._meta_addr = meta_address
        self.register(meta_address)

    def _discover(self) -> None:
        if self._meta_addr is None:
            return
        with self._mu:
            meta = self._clients.get(self._meta_addr)
        if meta is None:
            return
        inst = meta.try_call("instances")
        if isinstance(inst, dict):
            for addr in inst:
                self.register(addr)

    def register(self, address: str) -> None:
        from ..utils.net import RpcClient
        with self._mu:
            if address not in self._clients:
                self._clients[address] = RpcClient(
                    address, timeout=float(FLAGS.telemetry_rpc_timeout_s))

    def unregister(self, address: str) -> None:
        with self._mu:
            self._clients.pop(address, None)
            self._cache.pop(address, None)

    def addresses(self) -> list[str]:
        with self._mu:
            return sorted(self._clients)

    def has_daemons(self) -> bool:
        with self._mu:
            return bool(self._clients)

    # -- polling -----------------------------------------------------------
    def poll(self) -> None:
        """One scrape round: every registered daemon's ``rpc_metrics``
        under the retry policy; failures keep the previous snapshot and
        flip the stale marker.  A daemon whose last attempt FAILED within
        ``telemetry_poll_s`` is held off (rows stay stale) — without this,
        every inline-polled view query pays the full RPC timeout per dead
        daemon, serially."""
        from ..utils.net import RpcError
        self._discover()
        now = time.monotonic()
        # inline mode (telemetry_poll_s=0) still needs the holdoff — it is
        # the mode where a dead daemon's timeout lands on a QUERY — so fall
        # back to the per-daemon RPC budget as the re-probe period
        holdoff = float(FLAGS.telemetry_poll_s) \
            or float(FLAGS.telemetry_rpc_timeout_s)
        with self._mu:
            clients = dict(self._clients)
            skip = {a for a, e in self._cache.items()
                    if not e["ok"] and holdoff > 0
                    and now - e.get("attempt_ts", 0.0) < holdoff}
        for addr, client in sorted(clients.items()):
            if addr in skip:
                continue
            try:
                resp = client.call("metrics")
                snap = resp.get("metrics") if isinstance(resp, dict) else None
                if not isinstance(snap, dict):
                    raise RpcError("malformed rpc_metrics response")
                t = time.monotonic()
                entry = {"snapshot": snap, "ts": t, "attempt_ts": t,
                         "ok": True, "error": ""}
                with self._mu:
                    self._cache[addr] = entry
            except (OSError, RpcError) as e:
                with self._mu:
                    prev = self._cache.get(addr)
                    if prev is not None:
                        # "ts" stays the last SUCCESS time (age_ms = how
                        # old the surviving rows are); attempt_ts drives
                        # the re-probe holdoff above
                        prev["ok"] = False
                        prev["attempt_ts"] = time.monotonic()
                        prev["error"] = f"{type(e).__name__}: {e}"
                    else:
                        t = time.monotonic()
                        self._cache[addr] = {
                            "snapshot": None, "ts": t, "attempt_ts": t,
                            "ok": False,
                            "error": f"{type(e).__name__}: {e}"}

    def entries(self, refresh: bool = True) -> dict[str, dict]:
        """Cached per-daemon state; polls inline first unless a background
        poller thread is live (then the cache is already fresh)."""
        if refresh and not self.running():
            self.poll()
        with self._mu:
            return {a: dict(e) for a, e in self._cache.items()}

    # -- background poller -------------------------------------------------
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self, interval_s: Optional[float] = None) -> None:
        if self.running():
            return
        period = float(FLAGS.telemetry_poll_s) \
            if interval_s is None else float(interval_s)
        if period <= 0:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.poll()
                except Exception:   # noqa: BLE001 — the poller must survive
                    metrics.count_swallowed("telemetry.poll")
                self._stop.wait(period)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="telemetry-poller")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    # -- views -------------------------------------------------------------
    def fleet_snapshots(self, refresh: bool = True
                        ) -> tuple[dict[str, dict], dict[str, dict]]:
        """(per-daemon snapshots incl. the local registry, per-daemon
        status).  Stale daemons contribute their last-known snapshot —
        the best available estimate for fleet sums — with status marking
        how old it is."""
        snaps = {self.local_name: self.registry.snapshot()}
        status = {self.local_name: {"stale": 0, "age_ms": 0.0, "error": ""}}
        entries = self.entries(refresh=refresh)
        now = time.monotonic()           # after the poll: ages are >= 0
        for addr, ent in entries.items():
            status[addr] = {"stale": 0 if ent["ok"] else 1,
                            "age_ms": (now - ent["ts"]) * 1e3,
                            "error": ent.get("error", "")}
            if ent.get("snapshot") is not None:
                snaps[addr] = ent["snapshot"]
        return snaps, status

    def cluster_rows(self, refresh: bool = True) -> list[tuple]:
        """information_schema.cluster_metrics rows:
        (daemon, metric, labels, field, value, stale, age_ms).  Per-daemon
        rows for everything + merged ``fleet`` rows for the summable
        kinds + one ``up`` row per daemon."""
        snaps, status = self.fleet_snapshots(refresh=refresh)
        rows: list[tuple] = []

        def emit(daemon: str, snap: dict, stale: int, age: float):
            for name in sorted(snap):
                ent = snap[name]
                lnames = ent.get("label_names", ())
                for row in ent.get("rows", ()):
                    ltag = ",".join(
                        f"{n}={v}"
                        for n, v in zip(lnames, row.get("labels", ())))
                    for f in sorted(row):
                        if f in _STRUCT_FIELDS:
                            continue
                        try:
                            v = float(row[f])
                        except (TypeError, ValueError):
                            continue
                        rows.append((daemon, name, ltag, f, v, stale, age))

        for daemon in sorted(snaps):
            st = status.get(daemon, {"stale": 0, "age_ms": 0.0})
            emit(daemon, snaps[daemon], int(st["stale"]),
                 float(st["age_ms"]))
        for daemon in sorted(status):
            if daemon == self.local_name:
                continue
            st = status[daemon]
            rows.append((daemon, "up", "", "value",
                         0.0 if st["stale"] else 1.0,
                         int(st["stale"]), float(st["age_ms"])))
        emit(FLEET, merge_snapshots(snaps), 0, 0.0)
        return rows

    def status_rows(self, refresh: bool = True) -> dict[str, str]:
        """SHOW STATUS extension: the merged fleet counters/histograms plus
        per-daemon liveness, flattened to ``cluster.*`` variable names."""
        snaps, status = self.fleet_snapshots(refresh=refresh)
        out: dict[str, str] = {}
        fleet = merge_snapshots(snaps)
        for name in sorted(fleet):
            ent = fleet[name]
            for row in ent.get("rows", ()):
                ltag = "".join(
                    "{%s}" % ",".join(
                        f"{n}={v}" for n, v in zip(ent["label_names"],
                                                   row.get("labels", ()))))\
                    if row.get("labels") else ""
                for f in sorted(row):
                    if f in _STRUCT_FIELDS:
                        continue
                    out[f"cluster.{name}{ltag}.{f}"] = str(row[f])
        for daemon, st in sorted(status.items()):
            if daemon == self.local_name:
                continue
            out[f"cluster.daemon.{daemon}.up"] = \
                "0" if st["stale"] else "1"
        return out

    def prometheus(self, refresh: bool = True) -> str:
        """The whole fleet as one Prometheus exposition: every daemon's
        samples labeled ``daemon=...`` plus the merged rows under
        ``daemon="fleet"``."""
        snaps, _status = self.fleet_snapshots(refresh=refresh)
        snaps = dict(snaps)
        snaps[FLEET] = merge_snapshots(snaps)
        return render_fleet_prometheus(snaps)


# -- HTTP exposition ---------------------------------------------------------

def start_http_exporter(render: Callable[[], str], port: int,
                        host: str = "127.0.0.1"):
    """Serve ``GET /metrics`` (any path, really) from ``render()`` — the
    brpc-HTTP-port analog for daemons (``--metrics-port``) and
    tools/metrics_export.py.  Returns the ThreadingHTTPServer; call
    ``.shutdown()`` to stop."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server contract
            try:
                body = render().encode()
                code = 200
            except Exception as e:  # noqa: BLE001 — a scrape failure must
                #   answer 500, not kill the exporter thread
                metrics.count_swallowed("telemetry.exporter")
                body = f"# exporter error: {type(e).__name__}: {e}\n".encode()
                code = 500
            self.send_response(code)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):      # scrapes are not access-log news
            pass

    srv = ThreadingHTTPServer((host, port), _Handler)
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name=f"metrics-http-{srv.server_address[1]}").start()
    return srv


# lockset witness enrollment (see analysis/runtime.py): the poller thread
# and inline-scrape query threads share the client/cache maps
from ..analysis.runtime import LOCK_RANKS as _LOCK_RANKS  # noqa: E402
from ..analysis.runtime import register_witness  # noqa: E402

register_witness(Telemetry, "baikaldb_tpu/obs/telemetry.py:Telemetry")
_LOCK_RANKS.setdefault("telemetry.scrape_mu", Telemetry.RANK)
