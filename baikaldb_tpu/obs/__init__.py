"""Observability tier: query-lifecycle tracing (obs/trace.py).

Counters (utils/metrics.py) answer "how much / how fast on average";
this package answers "where did THIS query's time go" — span trees from
the wire protocol down to device execution and back, stitched across RPC
boundaries, surfaced through SHOW PROFILE / information_schema.trace_spans
and a Chrome trace_event exporter.
"""

from . import trace  # noqa: F401
