"""Observability tier: tracing, live introspection, forensics, watchdogs.

Counters (utils/metrics.py) answer "how much / how fast on average";
this package answers the operator's live and postmortem questions:

- obs/trace.py — "where did THIS query's time go": span trees from the
  wire protocol down to device execution and back, stitched across RPC
  boundaries (SHOW PROFILE / information_schema.trace_spans / Chrome
  trace export).
- obs/progress.py — "what is that query doing RIGHT NOW, and stop it":
  per-query progress beats feeding SHOW PROCESSLIST, and the cancel
  tokens KILL flips.
- obs/flightrec.py — "what was it doing when it went bad": the bounded
  flight-recorder ring with forensic bundles for slow/killed/failed
  queries (information_schema.flight_recorder / tools/flightrec.py).
- obs/watchdog.py — "is anything wedged": stall detection over queries,
  raft apply lag, and daemon clocks (health RPC / SHOW STATUS health.*).
- obs/telemetry.py — the fleet metric plane (scrape, merge, Prometheus).
"""

from . import trace  # noqa: F401
from . import progress  # noqa: F401
