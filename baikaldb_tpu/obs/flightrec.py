"""Always-on flight recorder: bounded forensics for every finished query.

The reference keeps a slow-SQL ring and dumps it for postmortems
(include/protocol/network_server.h print_agg_sql); a fleet operator's
first question after an incident is "what was that query doing when it
went bad?", and by then the query is gone.  This module answers it after
the fact:

- EVERY completed statement appends a cheap summary row (text, status,
  duration, rows, phase timings) to a bounded ring (``flightrec_max``,
  oldest evicted) — always on, a dict append per query.
- slow (> ``slow_query_ms``), killed, and failed queries additionally
  carry a full forensic bundle: the plan text, the query's trace spans
  (when tracing was live), deltas of the engine counters over the query,
  per-device memory stats, the MPP exchange summary, and per-phase wall
  clock.  Bundles are built AFTER the query finished — nothing here runs
  on the hot path, and nothing touches device state beyond the host-side
  memory_stats() the device gauges already read.

Surfaces: ``information_schema.flight_recorder`` and the
``tools/flightrec.py`` dump CLI.  One recorder per Database (like
query_log), so engines coexisting in one process never mix forensics.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from typing import Optional

from ..utils import metrics
from ..utils.flags import FLAGS, define

define("flightrec_max", 256,
       "flight recorder ring capacity: completed-query records beyond "
       "this evict oldest-first (bundles evict with their record)")

# engine counters whose over-the-query delta rides a forensic bundle —
# the "which subsystem went bad" one-glance view
_DELTA_COUNTERS = (
    "shuffle_rounds", "shuffle_overflow_retries", "xla_retraces",
    "rpc_timeouts", "rpc_retries", "dispatch_fallbacks",
    "failpoint_trips", "aot_cache_hits", "plan_cache_hits",
    "plan_cache_misses",
)


def metric_marks() -> dict:
    """Cheap start-of-query counter snapshot (a few attribute reads) so a
    failure bundle can report per-query deltas."""
    out = {}
    for name in _DELTA_COUNTERS:
        c = getattr(metrics, name, None)
        if c is not None:
            out[name] = c.value
    return out


def metric_delta(marks: dict) -> dict:
    """Counter movement since ``metric_marks()``, zero rows dropped."""
    out = {}
    for name, base in marks.items():
        c = getattr(metrics, name, None)
        if c is not None:
            d = c.value - base
            if d:
                out[name] = d
    return out


def device_stats() -> list[dict]:
    """Host-side per-device memory stats (the device-gauge read, bundled
    per incident instead of per scrape).  Backends without memory_stats
    (CPU) contribute empty rows; any backend failure degrades to []."""
    try:
        import jax
        out = []
        for d in jax.local_devices():
            ms = d.memory_stats() or {}
            out.append({"device": str(d),
                        **{k: float(v) for k, v in ms.items()
                           if isinstance(v, (int, float))}})
        return out
    except Exception:                                   # noqa: BLE001
        metrics.count_swallowed("flightrec.device_stats")
        return []


class FlightRecorder:
    """The bounded ring.  ``record`` is the only writer (the query's own
    thread, post-completion); readers copy under the lock."""

    def __init__(self):
        self._mu = threading.Lock()
        self._ring: deque[dict] = deque()
        self._ids = itertools.count(1)

    def record(self, summary: dict, bundle: Optional[dict] = None) -> int:
        rec = dict(summary)
        bundled = bundle is not None
        with self._mu:
            rec["rec_id"] = next(self._ids)
            rec.setdefault("ts", time.time())
            rec["bundle"] = bundle
            self._ring.append(rec)
            cap = max(1, int(FLAGS.flightrec_max))
            while len(self._ring) > cap:
                self._ring.popleft()
        metrics.flightrec_records.add(1)
        if bundled:
            metrics.flightrec_bundles.add(1)
        return rec["rec_id"]

    def rows(self) -> list[dict]:
        with self._mu:
            return [dict(r) for r in self._ring]

    def get(self, rec_id: int) -> Optional[dict]:
        with self._mu:
            for r in self._ring:
                if r["rec_id"] == int(rec_id):
                    return dict(r)
        return None

    def bundles(self) -> list[dict]:
        """Only the records that carry a forensic bundle."""
        return [r for r in self.rows() if r.get("bundle") is not None]

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()

    def dump(self, path: str, rec_id: Optional[int] = None) -> int:
        """Write records (or one) as JSON lines; -> count written."""
        recs = [self.get(rec_id)] if rec_id is not None else self.rows()
        recs = [r for r in recs if r is not None]
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r, default=str) + "\n")
        return len(recs)
