"""Device-value taint analysis (the core of HOSTSYNC / RETRACE / TRACERLEAK).

A lightweight intra-function dataflow pass over the AST: values produced by
``jnp.*`` / ``jax.lax.*`` calls, ``.data`` / ``.validity`` / ``.sel``
attribute reads, and ``sel_mask()`` / ``valid_mask()`` / ``live_count()``
method calls are *device values* (tracers under jit).  Taint propagates
through arithmetic, comparisons, subscripts and helper calls; sinks are the
places a device value crosses back to the host:

- ``int(x)`` / ``float(x)`` / ``bool(x)`` on a device value    -> HOSTSYNC
- ``np.asarray(x)`` / any ``np.*`` call on a device value      -> HOSTSYNC
- ``x.item()`` / ``x.tolist()``                                -> HOSTSYNC
- ``jax.device_get`` / ``block_until_ready`` in traced scope   -> HOSTSYNC
- ``if`` / ``while`` / ternary / ``and`` / ``or`` on a device
  value (data-dependent control flow)                          -> RETRACE
- iterating a device array (python loop unroll)                -> RETRACE
- ``jnp.nonzero``-family without ``size=`` in traced scope
  (data-dependent output shape)                                -> RETRACE
- boolean-mask subscripts in traced scope                      -> RETRACE
- storing a device value on ``self`` / an object attribute /
  a ``global`` from traced scope                               -> TRACERLEAK

"Traced scope" = functions the engine jit-traces: anything decorated with
``jax.jit`` (directly or through ``functools.partial``) plus every function
in the configured hot modules (ops/, parallel/, column/, exec/executor.py,
expr compile layer).  Host-only sinks (the sanctioned ``jax.device_get``
spelling) only fire inside traced scope; implicit-conversion sinks fire
everywhere — a host-side ``int(device_scalar)`` is still a blocking
round-trip per call site.

The pass is deliberately conservative-but-quiet: taint starts only from the
explicit device sources above, so host-side planner/catalog code stays
silent without per-file configuration.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass


# attributes that read host metadata off device containers (never tracers)
HOST_ATTRS = {
    "dtype", "shape", "ndim", "size", "ltype", "names", "name", "columns",
    "num_rows", "live_prefix", "dictionary", "values", "kind", "at",
    "weak_type", "aval",
}
# engine attributes that ARE device arrays (column/batch.py containers)
DEVICE_ATTRS = {"data", "validity", "sel"}
# engine methods returning device values
DEVICE_METHODS = {"sel_mask", "valid_mask", "live_count"}
# device-array methods that stay on device
_ARRAY_METHODS = {
    "astype", "sum", "any", "all", "max", "min", "mean", "reshape", "ravel",
    "take", "clip", "cumsum", "argmax", "argmin", "transpose", "squeeze",
    "flatten", "round", "view", "bit_length",
}
# jnp/lax ops whose output shape depends on data unless size= is given
_DDSHAPE_FNS = {"nonzero", "flatnonzero", "argwhere", "unique",
                "unique_values", "extract", "compress"}
# jnp/jax functions that return HOST metadata (dtype/shape predicates) —
# calling them is not a device computation
_JNP_HOST_FNS = {"issubdtype", "isdtype", "iinfo", "finfo", "result_type",
                 "promote_types", "can_cast", "ndim", "shape", "size",
                 "dtype", "isscalar"}
# engine methods that build device-value containers even off a host object
# (store/table handles): the result's .data/.sel re-taint downstream
_CONTAINER_METHODS = {"device_table_batch", "from_arrow", "gather",
                      "and_sel", "rename"}

# namespace roots whose calls produce device values
_DEVICE_CALL_ROOTS = ("jax.numpy.", "jax.lax.", "jax.nn.", "jax.random.",
                      "jax.ops.", "jax.scipy.")
# jax entry points that RETURN HOST data / callables (not device values)
_JAX_HOST_FNS = {
    "jax.device_get", "jax.block_until_ready", "jax.jit", "jax.vmap",
    "jax.pmap", "jax.grad", "jax.eval_shape", "jax.devices",
    "jax.local_devices", "jax.device_count", "jax.default_backend",
    "jax.transfer_guard", "jax.checking_leaks", "jax.debug_nans",
}
# engine constructors: results are device-value CONTAINERS, not arrays
_CONTAINER_CTORS = {"ColumnBatch", "Column", "dreplace", "replace"}


@dataclass(frozen=True)
class T:
    """Taint value: ``array`` = is (or may be) a device array / tracer;
    ``container`` = host object that may hold device arrays (ColumnBatch,
    tuple of arrays); ``boolish`` = array known boolean-valued (mask)."""
    array: bool = False
    container: bool = False
    boolish: bool = False

    @property
    def tainted(self) -> bool:
        return self.array or self.container

    def __or__(self, other: "T") -> "T":
        return T(self.array or other.array,
                 self.container or other.container,
                 self.boolish or other.boolish)


UNT = T()
ARR = T(array=True)
BOOLARR = T(array=True, boolish=True)
CONT = T(container=True)


class ModuleIndex:
    """Alias table for one file: resolves dotted call targets through
    ``import``/``from`` aliases (collected file-wide, including imports
    inside function bodies)."""

    def __init__(self, tree: ast.AST):
        self.alias: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.alias[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        self.alias.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                mod = ("." * node.level) + (node.module or "")
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.alias[a.asname or a.name] = f"{mod}.{a.name}"

    def resolve(self, expr: ast.expr) -> str | None:
        """Dotted path of a Name/Attribute chain with the root alias
        expanded (``jnp.where`` -> ``jax.numpy.where``), else None."""
        parts: list[str] = []
        while isinstance(expr, ast.Attribute):
            parts.append(expr.attr)
            expr = expr.value
        if not isinstance(expr, ast.Name):
            return None
        root = self.alias.get(expr.id, expr.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def is_module_alias(self, name: str) -> bool:
        resolved = self.alias.get(name)
        return resolved is not None and not resolved.startswith(".") \
            and "." not in name


def param_taint(arg: ast.arg) -> T:
    """Initial taint of a parameter, from its annotation: engine containers
    taint as CONT (their ``.data`` etc. re-taints), explicit array types as
    ARR, everything else starts clean (host scalars/strings dominate)."""
    if arg.annotation is None:
        return UNT
    try:
        ann = ast.unparse(arg.annotation)
    except Exception:
        return UNT
    if "ColumnBatch" in ann or ann.strip() in ("Column", "Optional[Column]"):
        return CONT
    # jax arrays only: np.ndarray / pa.Array annotations are HOST data
    if "jnp." in ann or "jax.Array" in ann or "jax.numpy" in ann:
        return ARR
    return UNT


def merge_env(a: dict[str, T], b: dict[str, T]) -> dict[str, T]:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, UNT) | v
    return out


class FunctionTaint:
    """Run the taint pass over one function body, reporting sinks through
    ``report(rule, node, msg)``.  Nested defs/lambdas/classes are analyzed
    inline with the enclosing environment as closure state."""

    def __init__(self, fnode, modindex: ModuleIndex, traced: bool, report,
                 closure: dict[str, T] | None = None):
        self.f = fnode
        self.mi = modindex
        self.traced = traced
        self.report = report
        self.env: dict[str, T] = dict(closure or {})
        self.globals_decl: set[str] = set()
        # names bound to objects constructed IN this function (call results):
        # storing a tracer on those builds the return value, not a leak
        self.fresh: set[str] = set()
        a = fnode.args
        for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs,
                    *( [a.vararg] if a.vararg else []),
                    *( [a.kwarg] if a.kwarg else [])]:
            self.env[arg.arg] = param_taint(arg)

    def run(self) -> None:
        self.exec_body(self.f.body)

    # ---- statements -------------------------------------------------------

    def exec_body(self, stmts) -> None:
        for s in stmts:
            self.exec_stmt(s)

    def exec_stmt(self, s) -> None:  # noqa: C901 — flat dispatch
        if isinstance(s, ast.Assign):
            t = self.eval(s.value)
            fresh = isinstance(s.value, ast.Call)
            for tgt in s.targets:
                self.assign(tgt, t, s, fresh=fresh)
        elif isinstance(s, ast.AugAssign):
            t = self.eval(s.value) | self.eval_target_load(s.target)
            self.assign(s.target, t, s)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self.assign(s.target, self.eval(s.value), s,
                            fresh=isinstance(s.value, ast.Call))
        elif isinstance(s, ast.Expr):
            self.eval(s.value)
        elif isinstance(s, ast.If):
            self.branch_test(s.test)
            e0 = dict(self.env)
            self.exec_body(s.body)
            e1, self.env = self.env, dict(e0)
            self.exec_body(s.orelse)
            self.env = merge_env(e1, self.env)
        elif isinstance(s, ast.While):
            self.branch_test(s.test)
            self.loop_body(s.body)
            self.exec_body(s.orelse)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            it = self.eval(s.iter)
            if it.array:
                self.report("RETRACE", s.iter,
                            "python loop over a device array unrolls into "
                            "the trace (or host-syncs per element)")
            elem = CONT if it.container else (ARR if it.array else UNT)
            self.assign(s.target, elem, s)
            self.loop_body(s.body)
            self.exec_body(s.orelse)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                t = self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, t, s)
            self.exec_body(s.body)
        elif isinstance(s, ast.Try):
            self.exec_body(s.body)
            base = dict(self.env)
            for h in s.handlers:
                self.env = dict(base)
                self.exec_body(h.body)
                base = merge_env(base, self.env)
            self.env = base
            self.exec_body(s.orelse)
            self.exec_body(s.finalbody)
        elif isinstance(s, ast.Return):
            if s.value is not None:
                self.eval(s.value)
        elif isinstance(s, ast.Raise):
            if s.exc is not None:
                self.eval(s.exc)
        elif isinstance(s, ast.Assert):
            self.eval(s.test)
        elif isinstance(s, ast.Global):
            self.globals_decl.update(s.names)
        elif isinstance(s, ast.Nonlocal):
            self.globals_decl.update(s.names)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: traced-ness inherits (compile_plan's run_local)
            FunctionTaint(s, self.mi, self.traced, self.report,
                          closure=dict(self.env)).run()
            self.env[s.name] = UNT
        elif isinstance(s, ast.ClassDef):
            for b in s.body:
                if isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    FunctionTaint(b, self.mi, self.traced, self.report,
                                  closure=dict(self.env)).run()
            self.env[s.name] = UNT
        elif isinstance(s, ast.Delete):
            for tgt in s.targets:
                if isinstance(tgt, ast.Name):
                    self.env.pop(tgt.id, None)
        # Pass/Break/Continue/Import: no dataflow

    def loop_body(self, body) -> None:
        """Two passes approximate the loop fixpoint (taint only grows)."""
        snapshot = dict(self.env)
        self.exec_body(body)
        self.env = merge_env(snapshot, self.env)
        self.exec_body(body)

    def branch_test(self, test) -> None:
        t = self.eval(test)
        if t.array:
            self.report("RETRACE", test,
                        "python branch on a device value: concretizes the "
                        "tracer (error under jit, blocking sync outside)")

    # ---- assignment targets ----------------------------------------------

    def assign(self, tgt, t: T, stmt, fresh: bool = False) -> None:
        if isinstance(tgt, ast.Name):
            if tgt.id in self.globals_decl and t.tainted and self.traced:
                self.report("TRACERLEAK", stmt,
                            f"device value stored in global {tgt.id!r} from "
                            "traced scope: the tracer outlives its trace")
            self.env[tgt.id] = t
            (self.fresh.add if fresh else self.fresh.discard)(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self.assign(el, t, stmt, fresh=fresh)
        elif isinstance(tgt, ast.Starred):
            self.assign(tgt.value, t, stmt, fresh=fresh)
        elif isinstance(tgt, ast.Attribute):
            root = tgt.value
            while isinstance(root, ast.Attribute):
                root = root.value
            escapes = not (isinstance(root, ast.Name)
                           and root.id in self.fresh)
            if t.tainted and self.traced and escapes:
                owner = ast.unparse(tgt.value) if hasattr(ast, "unparse") \
                    else "<obj>"
                self.report("TRACERLEAK", stmt,
                            f"device value stored on {owner}.{tgt.attr} from "
                            "traced scope: the tracer outlives its trace "
                            "(and silently pins stale state outside)")
            self.eval(tgt.value)
        elif isinstance(tgt, ast.Subscript):
            self.eval(tgt.slice)
            if isinstance(tgt.value, ast.Name) and t.tainted:
                base = self.env.get(tgt.value.id, UNT)
                self.env[tgt.value.id] = base | CONT

    def eval_target_load(self, tgt) -> T:
        if isinstance(tgt, (ast.Name, ast.Attribute, ast.Subscript)):
            return self.eval(tgt)
        return UNT

    # ---- expressions ------------------------------------------------------

    def eval(self, e) -> T:  # noqa: C901 — flat dispatch
        if e is None or isinstance(e, ast.Constant):
            return UNT
        if isinstance(e, ast.Name):
            return self.env.get(e.id, UNT)
        if isinstance(e, ast.Attribute):
            if isinstance(e.value, ast.Name) and \
                    self.mi.is_module_alias(e.value.id):
                return UNT          # jnp.int32, np.float64, module constants
            vt = self.eval(e.value)
            # .data/.validity/.sel are device arrays only on engine column
            # containers — gate on owner taint (or hot scope, where any
            # unannotated container flows through) so arrow RegionData.data
            # / raft LogEntry.data stay host
            if e.attr in DEVICE_ATTRS and (self.traced or vt.tainted):
                return ARR
            if e.attr in HOST_ATTRS:
                return UNT
            if vt.tainted:
                return T(vt.array, vt.container, False)
            return UNT
        if isinstance(e, ast.Call):
            return self.eval_call(e)
        if isinstance(e, ast.BinOp):
            lt, rt = self.eval(e.left), self.eval(e.right)
            return ARR if (lt.array or rt.array) else UNT
        if isinstance(e, ast.UnaryOp):
            t = self.eval(e.operand)
            if isinstance(e.op, ast.Not) and t.array:
                self.report("RETRACE", e,
                            "python `not` on a device value concretizes the "
                            "tracer (use ~ / jnp.logical_not)")
                return UNT
            return T(t.array, False, t.boolish) if t.array else UNT
        if isinstance(e, ast.BoolOp):
            ts = [self.eval(v) for v in e.values]
            if any(t.array for t in ts):
                self.report("RETRACE", e,
                            "python and/or on a device value concretizes the "
                            "tracer (use & / | or jnp.where)")
                return BOOLARR
            return UNT
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                self.eval(e.left)
                for c in e.comparators:
                    self.eval(c)
                return UNT
            ts = [self.eval(e.left)] + [self.eval(c) for c in e.comparators]
            return BOOLARR if any(t.array for t in ts) else UNT
        if isinstance(e, ast.IfExp):
            self.branch_test(e.test)
            return self.eval(e.body) | self.eval(e.orelse)
        if isinstance(e, ast.Subscript):
            vt = self.eval(e.value)
            st = self.eval(e.slice)
            if st.array and st.boolish and vt.array and self.traced:
                self.report("RETRACE", e,
                            "boolean-mask subscript: data-dependent output "
                            "shape outside the sel-mask machinery (use "
                            "jnp.where / a sel mask)")
            if vt.array or vt.container:
                return ARR
            return ARR if st.array else UNT
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            ts = [self.eval(el) for el in e.elts]
            return CONT if any(t.tainted for t in ts) else UNT
        if isinstance(e, ast.Dict):
            ts = [self.eval(v) for v in (*e.keys, *e.values) if v is not None]
            return CONT if any(t.tainted for t in ts) else UNT
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            return self.eval_comp(e)
        if isinstance(e, ast.Lambda):
            sub = FunctionTaint(_LambdaShim(e), self.mi, self.traced,
                                self.report, closure=dict(self.env))
            sub.eval(e.body)
            return UNT
        if isinstance(e, ast.JoinedStr):
            for v in e.values:
                if isinstance(v, ast.FormattedValue):
                    self.eval(v.value)
            return UNT
        if isinstance(e, ast.FormattedValue):
            self.eval(e.value)
            return UNT
        if isinstance(e, ast.Starred):
            return self.eval(e.value)
        if isinstance(e, ast.NamedExpr):
            t = self.eval(e.value)
            self.assign(e.target, t, e)
            return t
        if isinstance(e, (ast.Await, ast.Yield, ast.YieldFrom)):
            return self.eval(e.value) if e.value is not None else UNT
        if isinstance(e, ast.Slice):
            for part in (e.lower, e.upper, e.step):
                if part is not None:
                    self.eval(part)
            return UNT
        return UNT

    def eval_comp(self, e) -> T:
        saved = dict(self.env)
        elem_t = UNT
        for gen in e.generators:
            it = self.eval(gen.iter)
            if it.array:
                self.report("RETRACE", gen.iter,
                            "comprehension over a device array unrolls into "
                            "the trace (or host-syncs per element)")
            self.assign(gen.target,
                        CONT if it.container else (ARR if it.array else UNT),
                        e)
            for cond in gen.ifs:
                self.eval(cond)
        if isinstance(e, ast.DictComp):
            elem_t = self.eval(e.key) | self.eval(e.value)
        else:
            elem_t = self.eval(e.elt)
        self.env = saved
        return CONT if elem_t.tainted else UNT

    # ---- calls ------------------------------------------------------------

    def call_args(self, e: ast.Call) -> T:
        t = UNT
        for a in e.args:
            t = t | self.eval(a)
        for kw in e.keywords:
            t = t | self.eval(kw.value)
        return t

    def eval_call(self, e: ast.Call) -> T:  # noqa: C901
        path = self.mi.resolve(e.func)

        # builtins that force a host value out of a device scalar
        if path in ("int", "float", "bool", "complex"):
            t = self.call_args(e)
            if t.array:
                self.report("HOSTSYNC", e,
                            f"{path}() on a device value: blocking "
                            "device->host round-trip (error under jit); "
                            "keep it on device or jax.device_get explicitly")
            return UNT
        if path in ("len", "str", "repr", "format", "hash", "id", "type",
                    "isinstance", "issubclass", "print", "getattr", "hasattr",
                    "sorted", "range", "zip", "enumerate", "iter", "next",
                    "abs", "min", "max", "sum"):
            at = self.call_args(e)
            if path in ("abs", "min", "max", "sum") and at.array:
                return ARR          # these stay lazy on jax arrays
            return UNT

        if path is not None:
            root = path.split(".")[0]
            if root == "numpy":
                t = self.call_args(e)
                if t.array:
                    self.report("HOSTSYNC", e,
                                f"{ast.unparse(e.func)}() on a device value "
                                "materializes it on host (blocking sync; "
                                "error under jit) — use the jnp equivalent "
                                "or an explicit jax.device_get at egress")
                return UNT
            if path in _JAX_HOST_FNS or path.endswith(".block_until_ready"):
                t = self.call_args(e)
                if self.traced and path in ("jax.device_get",
                                            "jax.block_until_ready"):
                    self.report("HOSTSYNC", e,
                                f"{path.split('.')[-1]} inside traced scope: "
                                "host sync baked into the compiled path")
                return UNT
            if path.startswith(_DEVICE_CALL_ROOTS) or root == "jax":
                self.call_args(e)
                fn = path.split(".")[-1]
                if fn in _JNP_HOST_FNS:
                    return UNT      # dtype/shape predicates are host values
                if fn in _DDSHAPE_FNS and self.traced and \
                        not any(kw.arg == "size" for kw in e.keywords):
                    self.report("RETRACE", e,
                                f"{fn}() without size=: data-dependent "
                                "output shape (errors under jit, retraces "
                                "otherwise)")
                if fn == "where" and len(e.args) == 1 and self.traced:
                    self.report("RETRACE", e,
                                "one-argument where(): data-dependent "
                                "output shape (use the three-argument form)")
                return ARR
            last = path.split(".")[-1]
            if last in _CONTAINER_CTORS:
                t = self.call_args(e)
                return CONT

        # method calls: obj.meth(...)
        if isinstance(e.func, ast.Attribute):
            owner_t = self.eval(e.func.value)
            meth = e.func.attr
            args_t = self.call_args(e)
            if meth in DEVICE_METHODS:
                return ARR
            if meth in _CONTAINER_METHODS:
                return CONT
            if meth in ("item", "tolist", "to_py") and owner_t.array:
                self.report("HOSTSYNC", e,
                            f".{meth}() on a device value: blocking "
                            "device->host round-trip (error under jit)")
                return UNT
            if meth == "block_until_ready":
                if self.traced:
                    self.report("HOSTSYNC", e,
                                "block_until_ready inside traced scope: host "
                                "sync baked into the compiled path")
                return owner_t
            if owner_t.array:
                return ARR if meth in _ARRAY_METHODS else ARR
            if owner_t.container:
                return CONT
            return ARR if args_t.array else UNT

        # plain / unresolved calls: conservative propagation through helpers
        args_t = self.call_args(e)
        if isinstance(e.func, ast.Name):
            self.eval(e.func)
        else:
            self.eval(e.func)
        return ARR if args_t.array else (CONT if args_t.container else UNT)


class _LambdaShim:
    """Adapter so FunctionTaint can bind a Lambda's params."""

    def __init__(self, lam: ast.Lambda):
        self.args = lam.args
        self.body = []
        self.name = "<lambda>"
