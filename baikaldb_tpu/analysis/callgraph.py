"""Conservative intra-package call graph with thread-entry roots.

The lockset race detector (analysis/ownership.py) needs to know which
functions can run *concurrently*: an unguarded access to lock-owned state
only races when two threads can reach it.  This module answers that with a
package-wide call graph whose roots are the places threads are born:

- ``threading.Thread(target=X)`` — every background loop in the tree
  (telemetry poller, store tick/heartbeat, watchdogs, stream prefetcher,
  RPC serve threads) is spawned this way;
- RPC handler registration: ``srv.register(name, fn)`` when ``fn`` is a
  direct reference, plus the ``rpc_*`` naming convention used by
  server/store_server.py and server/meta_server.py (their registration is
  a dynamic ``getattr(self, "rpc_" + name)`` loop the resolver cannot see
  through) — handlers run on utils/net.py's thread-per-connection serve
  threads;
- loop-shaped entry points by name (``run`` / ``serve*`` / ``tick`` /
  ``poll`` / ``stage`` / ``*_loop``): session worker threads enter the
  engine through these (mysql_server spawns ``_serve`` per connection;
  BatchDispatcher.run is entered by many session threads at once), and the
  layer-crossing dispatch between them is too dynamic to resolve edges
  through.

Call edges use the same resolution a reader can do (and locks.py uses):
``self.meth()`` -> same class, bare ``fn()`` -> same module, ``obj.meth()``
-> the unique package-wide definition when the name is not generic.  The
*main* thread is an implicit root everywhere — any function may be entered
from a session/test thread — so "reachable from >= 2 roots" reduces to
"reachable from at least one spawned/handler/loop root".
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

# names too generic for unique-name resolution (mirrors locks.py: unioning
# dict.get with a package-level get() would fabricate reachability)
_COMMON_NAMES = frozenset({
    "get", "put", "set", "add", "append", "appendleft", "pop", "popleft",
    "read", "write", "close", "clear", "update", "call", "wait",
    "remove", "release", "acquire", "observe", "send", "recv", "items",
    "keys", "values", "join", "start", "copy", "extend", "index",
    "insert", "sort", "split", "strip", "encode", "decode", "flush",
})

# loop-shaped entry points: threads live here (see module docstring)
_LOOP_NAME_RE = re.compile(r"^(run|serve.*|tick|poll|stage|_serve.*)$")


def _is_entry_name(name: str) -> bool:
    return name.endswith("_loop") or bool(_LOOP_NAME_RE.match(name)) \
        or name.startswith("rpc_") or name.startswith("_handle")


@dataclass
class FuncNode:
    module: str
    cls: str | None          # enclosing class (kept across nested defs)
    name: str
    line: int
    # callee refs: ("method", cls, name) for self.m(), ("func", None, name)
    # for bare calls, ("anymethod", None, name) for obj.m()
    calls: list = field(default_factory=list)

    @property
    def key(self) -> tuple:
        return (self.module, self.cls, self.name, self.line)

    def __str__(self) -> str:
        scope = f"{self.cls}." if self.cls else ""
        return f"{self.module}:{scope}{self.name}"


class _FileCallPass(ast.NodeVisitor):
    """One file: function nodes, their callee refs, and root declarations
    (thread targets + direct handler registrations)."""

    def __init__(self, module: str, tree: ast.AST):
        self.module = module
        self.funcs: list[FuncNode] = []
        # (ref, kind, line): refs spawned as threads / registered handlers
        self.root_refs: list[tuple] = []
        self._cls: str | None = None
        self._fn: FuncNode | None = None
        self.visit(tree)

    def visit_ClassDef(self, node):
        prev, self._cls = self._cls, node.name
        self.generic_visit(node)
        self._cls = prev

    def visit_FunctionDef(self, node):
        prev = self._fn
        self._fn = FuncNode(self.module, self._cls, node.name, node.lineno)
        self.funcs.append(self._fn)
        self.generic_visit(node)
        self._fn = prev

    visit_AsyncFunctionDef = visit_FunctionDef

    def _ref(self, expr):
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                return ("method", self._cls, expr.attr)
            return ("anymethod", None, expr.attr)
        if isinstance(expr, ast.Name):
            return ("func", None, expr.id)
        return None

    def visit_Call(self, node):
        callee = self._ref(node.func)
        if callee is not None and self._fn is not None:
            self._fn.calls.append(callee)
        # threading.Thread(target=X) — keyword or 3rd positional arg
        fpath = self._dotted(node.func)
        if fpath is not None and fpath.endswith("Thread"):
            tgt = None
            for kw in node.keywords:
                if kw.arg == "target":
                    tgt = kw.value
            if tgt is None and len(node.args) >= 3:
                tgt = node.args[2]
            ref = self._ref(tgt) if tgt is not None else None
            if ref is not None:
                self.root_refs.append((ref, "thread", node.lineno))
        # srv.register("name", fn) with a direct function reference
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "register" and len(node.args) >= 2:
            ref = self._ref(node.args[1])
            if ref is not None:
                self.root_refs.append((ref, "rpc", node.lineno))
        self.generic_visit(node)

    @staticmethod
    def _dotted(expr) -> str | None:
        parts = []
        while isinstance(expr, ast.Attribute):
            parts.append(expr.attr)
            expr = expr.value
        if not isinstance(expr, ast.Name):
            return None
        parts.append(expr.id)
        return ".".join(reversed(parts))


class CallGraph:
    """Package-wide aggregation.  ``build()`` resolves edges and runs the
    root reachability BFS; afterwards ``spawned_roots_of`` answers which
    non-main roots reach a function."""

    def __init__(self):
        self._files: list[_FileCallPass] = []
        self._built = False

    def add_file(self, module: str, tree: ast.AST) -> None:
        self._files.append(_FileCallPass(module, tree))
        self._built = False

    # -- resolution ---------------------------------------------------------

    def _resolve(self, fp: _FileCallPass, ref) -> list[FuncNode]:
        kind, cls, name = ref
        exact, same_mod, anywhere = [], [], []
        for f in self._by_name.get(name, ()):
            if f.module == fp.module and f.cls == cls:
                exact.append(f)
            if f.module == fp.module:
                same_mod.append(f)
            anywhere.append(f)
        if kind == "method" and exact:
            return exact
        if kind == "func":
            top = [f for f in same_mod if f.cls is None]
            if top:
                return top
            # nested defs keep the enclosing class: target=loop inside a
            # method resolves to the unique same-module def of that name
            if len(same_mod) == 1:
                return same_mod
        if len(anywhere) == 1 and name not in _COMMON_NAMES:
            return anywhere
        return []

    # -- build --------------------------------------------------------------

    def build(self) -> None:
        if self._built:
            return
        self._by_name: dict[str, list[FuncNode]] = {}
        for fp in self._files:
            for f in fp.funcs:
                self._by_name.setdefault(f.name, []).append(f)

        self._edges: dict[tuple, list[tuple]] = {}
        for fp in self._files:
            for f in fp.funcs:
                out = self._edges.setdefault(f.key, [])
                for c in f.calls:
                    out.extend(t.key for t in self._resolve(fp, c))

        # roots: declared spawns/registrations + loop-shaped entry names
        self.roots: dict[tuple, str] = {}
        for fp in self._files:
            for ref, kind, line in fp.root_refs:
                for t in self._resolve(fp, ref):
                    self.roots.setdefault(t.key, f"{kind}:{t}")
            for f in fp.funcs:
                if _is_entry_name(f.name):
                    self.roots.setdefault(f.key, f"loop:{f}")

        # BFS per root; functions accumulate the set of root labels
        self._reach: dict[tuple, set] = {}
        for rkey, label in self.roots.items():
            stack = [rkey]
            while stack:
                k = stack.pop()
                labels = self._reach.setdefault(k, set())
                if label in labels:
                    continue
                labels.add(label)
                stack.extend(self._edges.get(k, ()))
        self._built = True

    # -- queries ------------------------------------------------------------

    def spawned_roots_of(self, module: str, cls: str | None,
                         name: str, line: int) -> set:
        """Root labels (threads / handlers / loop entries) reaching the
        function; the implicit main root is NOT included."""
        self.build()
        return self._reach.get((module, cls, name, line), set())

    def concurrent_classes(self) -> set:
        """(module, cls) pairs with at least one method reachable from a
        spawned root — their instances are shared across >= 2 roots (the
        spawned one plus the implicit main thread)."""
        self.build()
        out = set()
        for fp in self._files:
            for f in fp.funcs:
                if f.cls is not None and self._reach.get(f.key):
                    out.add((f.module, f.cls))
        return out
