"""Per-file rule drivers: taint-based HOSTSYNC/RETRACE/TRACERLEAK plus the
syntactic BAREEXC and jit-misuse RETRACE checks.

Scope model: a function is *traced scope* when it is jit-decorated (directly
or through ``functools.partial(jax.jit, ...)``) or lives in a configured hot
module (the modules whose functions execute inside ``compile_plan``'s
traces).  Traced scope arms the traced-only sinks (device_get /
block_until_ready / data-dependent shapes / tracer leaks); the implicit
conversion sinks (``int()``/``np.asarray``/``.item()``) fire everywhere.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .taint import FunctionTaint, ModuleIndex


@dataclass(frozen=True)
class RawViolation:
    rule: str
    line: int
    col: int
    msg: str
    qualname: str


def _decorator_paths(fnode, mi: ModuleIndex):
    for d in fnode.decorator_list:
        yield d, mi.resolve(d.func if isinstance(d, ast.Call) else d)


def is_jit_decorated(fnode, mi: ModuleIndex) -> bool:
    for d, path in _decorator_paths(fnode, mi):
        if path is None:
            continue
        if "jax.jit" in path or path.endswith("pallas_call") or \
                path.endswith("pjit"):
            return True
        if path.endswith("partial") and isinstance(d, ast.Call) and d.args:
            first = mi.resolve(d.args[0])
            if first is not None and ("jax.jit" in first or
                                      first.endswith("pjit")):
                return True
    return False


def _static_argnames(fnode, mi: ModuleIndex) -> set[str]:
    """Names marked static on a jit decorator (hashability matters there)."""
    names: set[str] = set()
    for d, path in _decorator_paths(fnode, mi):
        if not isinstance(d, ast.Call) or path is None:
            continue
        if not (path.endswith("partial") or "jit" in path):
            continue
        for kw in d.keywords:
            if kw.arg == "static_argnames" and isinstance(
                    kw.value, (ast.Tuple, ast.List)):
                names.update(el.value for el in kw.value.elts
                             if isinstance(el, ast.Constant))
            if kw.arg == "static_argnames" and isinstance(
                    kw.value, ast.Constant):
                names.add(kw.value.value)
    return names


class _JitMisuse(ast.NodeVisitor):
    """RETRACE: jit caches defeated at the call site — a fresh jit wrapper
    per loop iteration / an immediately-invoked jit both recompile every
    execution; unhashable defaults on static params fail the cache key."""

    def __init__(self, mi: ModuleIndex, report):
        self.mi = mi
        self.report = report
        self.loop_depth = 0

    def visit_For(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_While = visit_For

    def visit_Call(self, node):
        path = self.mi.resolve(node.func)
        if path is not None and path.endswith("jax.jit"):
            if self.loop_depth:
                self.report("RETRACE", node,
                            "jax.jit inside a loop builds a fresh compile "
                            "cache every iteration — hoist and reuse")
            if isinstance(node.func, ast.Attribute) or \
                    isinstance(node.func, ast.Name):
                pass
        # jax.jit(f)(args): the wrapper (and its cache) dies immediately
        if isinstance(node.func, ast.Call):
            inner = self.mi.resolve(node.func.func)
            if inner is not None and inner.endswith("jax.jit"):
                self.report("RETRACE", node,
                            "immediately-invoked jax.jit(f)(...) recompiles "
                            "on every call — cache the jitted callable")
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        if is_jit_decorated(node, self.mi):
            static = _static_argnames(node, self.mi)
            args = node.args
            pos = [*args.posonlyargs, *args.args]
            defaults = args.defaults
            for arg, default in zip(pos[len(pos) - len(defaults):], defaults):
                if arg.arg in static and isinstance(
                        default, (ast.List, ast.Dict, ast.Set)):
                    self.report("RETRACE", default,
                                f"static arg {arg.arg!r} has an unhashable "
                                "default: every call misses the jit cache")
            for arg, default in zip(args.kwonlyargs, args.kw_defaults):
                if default is not None and arg.arg in static and isinstance(
                        default, (ast.List, ast.Dict, ast.Set)):
                    self.report("RETRACE", default,
                                f"static arg {arg.arg!r} has an unhashable "
                                "default: every call misses the jit cache")
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


# tracer entry points (obs/trace.py) that must stay host-side: inside a
# jit trace a span either bakes into the compiled program — its timing is
# trace-time, not run-time, i.e. it measures nothing — or captures tracers
# in the host-side span store (a leak).  Instrumentation belongs at the
# dispatch layer AROUND fn(batches), never inside the traced function.
_TRACER_FNS = frozenset({"span", "root", "event", "adopt"})


def _is_tracer_call(path: str | None) -> bool:
    if path is None or "." not in path:
        return False
    head, _, last = path.rpartition(".")
    if last not in _TRACER_FNS:
        return False
    h = head.lower()
    return "trace" in h or "tracer" in h or h.endswith("obs") or ".obs" in h


class _SpanInJit(ast.NodeVisitor):
    """SPANINJIT: tracer span calls inside traced scope (hot modules /
    jit-decorated functions).  Spans are host-side; in a trace they bake
    or leak — move them to the dispatch layer."""

    def __init__(self, mi: ModuleIndex, report):
        self.mi = mi
        self.report = report

    def visit_Call(self, node):
        if _is_tracer_call(self.mi.resolve(node.func)):
            self.report("SPANINJIT", node,
                        "tracer span inside jit-traced scope: spans are "
                        "host-side — under a trace they bake into the "
                        "program (timing nothing) or leak tracers; "
                        "instrument the dispatch layer instead")
        self.generic_visit(node)


# metric mutation entry points (utils/metrics.py) that must stay host-side:
# inside a jit trace an ``add``/``observe`` fires at TRACE time — the count
# bakes into nothing and moves once per compile, not once per execution —
# or captures tracers if fed a device value.  The one sanctioned exception
# is a counter that deliberately counts TRACES (exec/executor.py run_local's
# xla_retraces), which lives in the suppression registry.
_METRIC_METHODS = frozenset({"add", "observe"})


def _is_metric_call(mi: ModuleIndex, node: ast.Call) -> bool:
    path = mi.resolve(node.func)
    if path is not None and "." in path:
        head, _, last = path.rpartition(".")
        h = head.lower()
        if last == "count_swallowed" and "metrics" in h:
            return True
        if last in _METRIC_METHODS and "metrics" in h:
            return True
    # REGISTRY.counter("x").add(1): an add/observe on a registry-getter
    # call result — the getter resolves even though the receiver is a
    # transient value
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in _METRIC_METHODS \
            and isinstance(func.value, ast.Call):
        inner = mi.resolve(func.value.func)
        if inner is not None and "metrics" in inner.lower():
            return True
    return False


class _MetricInJit(ast.NodeVisitor):
    """METRICINJIT: registry increments/observes inside traced scope (hot
    modules / jit-decorated functions) — the SPANINJIT discipline applied
    to metrics: counts fire per TRACE, not per execution (bake), or leak
    tracers into host state.  Count at the dispatch layer instead."""

    def __init__(self, mi: ModuleIndex, report):
        self.mi = mi
        self.report = report

    def visit_Call(self, node):
        if _is_metric_call(self.mi, node):
            self.report("METRICINJIT", node,
                        "metric increment/observe inside jit-traced scope: "
                        "it fires at trace time (counting compiles, not "
                        "executions) or captures tracers — count at the "
                        "dispatch layer around the jitted call")
        self.generic_visit(node)


# progress-record entry points (obs/progress.py) that must stay host-side:
# a beat inside a jit trace fires at TRACE time (reporting compile-time
# progress, not run-time) and its CancelToken check can never interrupt a
# running device program — beats belong at the host seams around fn(batches)
_PROGRESS_METHODS = frozenset({"beat", "checkpoint"})


def _is_progress_call(mi: ModuleIndex, node: ast.Call) -> bool:
    path = mi.resolve(node.func)
    if path is not None and "." in path:
        head, _, last = path.rpartition(".")
        h = head.lower()
        if last in _PROGRESS_METHODS and ("progress" in h
                                          or "watchdog" in h):
            return True
        if last in ("current", "track", "cancel_token") and "progress" in h:
            return True
    # progress.current().beat(...): a beat on a getter's transient result —
    # the getter resolves even though the receiver is a local value
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in _PROGRESS_METHODS \
            and isinstance(func.value, ast.Call):
        inner = mi.resolve(func.value.func)
        if inner is not None and "progress" in inner.lower():
            return True
    return False


class _ProgressInJit(ast.NodeVisitor):
    """PROGRESSINJIT: progress beats/checkpoints inside traced scope (hot
    modules / jit-decorated functions) — the SPANINJIT discipline applied
    to the live-query registry: a beat under a trace reports trace-time
    progress (baking nothing into the program), and a cancellation check
    there can never stop a running device program anyway."""

    def __init__(self, mi: ModuleIndex, report):
        self.mi = mi
        self.report = report

    def visit_Call(self, node):
        if _is_progress_call(self.mi, node):
            self.report("PROGRESSINJIT", node,
                        "progress beat/checkpoint inside jit-traced scope: "
                        "it fires at trace time (progress of the compile, "
                        "not the run) and its kill check cannot interrupt "
                        "a device program — beat at the host seams around "
                        "the jitted call")
        self.generic_visit(node)


def _is_failpoint_hit(path: str | None) -> bool:
    if path is None or "." not in path:
        return False
    head, _, last = path.rpartition(".")
    return last == "hit" and "failpoint" in head.lower()


def _is_enable_check(mi: ModuleIndex, expr: ast.expr) -> bool:
    """``failpoint.ENABLED`` (any alias/relative spelling)."""
    path = mi.resolve(expr)
    if path is None or "." not in path:
        return False
    head, _, last = path.rpartition(".")
    return last == "ENABLED" and "failpoint" in head.lower()


class _FailpointHot(ast.NodeVisitor):
    """FAILPOINTHOT: every ``failpoint.hit(...)`` site must (a) stay out of
    jit-traced scope — a host-side sleep/raise inside a trace fires at
    TRACE time and bakes nothing into the program — and (b) sit behind the
    module-level enable check (``if failpoint.ENABLED: ...`` or the inline
    ``failpoint.ENABLED and failpoint.hit(...)``), so a disabled build
    pays one bool read per site, never a registry lookup."""

    def __init__(self, mi: ModuleIndex, report, hot_module: bool):
        self.mi = mi
        self.report = report
        self.hot_module = hot_module
        self.guard_depth = 0
        self.traced_depth = 0

    def _check_call(self, node: ast.Call) -> None:
        if not _is_failpoint_hit(self.mi.resolve(node.func)):
            return
        if self.hot_module or self.traced_depth:
            self.report("FAILPOINTHOT", node,
                        "failpoint site inside jit-traced scope: the "
                        "sleep/raise fires at trace time, not run time — "
                        "move it to the dispatch layer")
        elif not self.guard_depth:
            self.report("FAILPOINTHOT", node,
                        "failpoint.hit not behind the module-level enable "
                        "check — guard with `if failpoint.ENABLED:` so a "
                        "disabled site costs one bool read")

    def visit_Call(self, node):
        self._check_call(node)
        self.generic_visit(node)

    def visit_BoolOp(self, node):
        # `failpoint.ENABLED and failpoint.hit(...)`: values after the
        # enable check short-circuit behind it — guarded
        if isinstance(node.op, ast.And) and node.values and \
                _is_enable_check(self.mi, node.values[0]):
            self.guard_depth += 1
            for v in node.values[1:]:
                self.visit(v)
            self.guard_depth -= 1
        else:
            self.generic_visit(node)

    def visit_If(self, node):
        if _is_enable_check(self.mi, node.test) or (
                isinstance(node.test, ast.BoolOp) and
                isinstance(node.test.op, ast.And) and node.test.values and
                _is_enable_check(self.mi, node.test.values[0])):
            self.visit(node.test)           # BoolOp handler guards the rest
            self.guard_depth += 1
            for n in node.body:
                self.visit(n)
            self.guard_depth -= 1
            for n in node.orelse:
                self.visit(n)
        else:
            self.generic_visit(node)

    def visit_FunctionDef(self, node):
        # the guard is a RUNTIME check: an `if ENABLED:` around a def does
        # not guard the calls inside it, and traced-ness is per-function
        traced = is_jit_decorated(node, self.mi)
        prev_guard, self.guard_depth = self.guard_depth, 0
        if traced:
            self.traced_depth += 1
        self.generic_visit(node)
        if traced:
            self.traced_depth -= 1
        self.guard_depth = prev_guard

    visit_AsyncFunctionDef = visit_FunctionDef


def _dotted(node) -> str | None:
    """``a.b.c`` spelling of a Name/Attribute chain, or None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def _donated_positions(call: ast.Call) -> tuple | None:
    """The donate_argnums positions of a ``jax.jit(...)`` call as a tuple
    of ints, or None when absent/non-literal."""
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            vals = tuple(el.value for el in v.elts
                         if isinstance(el, ast.Constant)
                         and isinstance(el.value, int))
            return vals or None
        return None
    return None


class _DonatedUse:
    """DONATED: a buffer read after it was passed in a donated argument
    position of a jitted call.  ``jax.jit(step, donate_argnums=(0, 1))``
    hands the inputs' device buffers to the executable for reuse — on TPU
    a later read of the SAME python reference returns whatever the program
    scribbled there, silently (CPU merely declines the donation, so tests
    pass while the accelerator corrupts).  Per-statement linear scan of
    each scope: a call through a name bound to a donating jax.jit kills
    the names fed at donated positions; any later Load of a killed name
    reports; assignment (including the ``acc = step(acc, chunk)``
    self-recycle idiom) revives the target."""

    def __init__(self, mi: ModuleIndex, report):
        self.mi = mi
        self.report = report
        self.donated: dict[str, tuple] = {}

    def run(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            v = node.value
            if not isinstance(v, ast.Call):
                continue
            path = self.mi.resolve(v.func)
            if path is None or not (path.endswith("jax.jit")
                                    or path.endswith("pjit")):
                continue
            pos = _donated_positions(v)
            name = _dotted(node.targets[0])
            if pos and name:
                self.donated[name] = pos
        if not self.donated:
            return
        self._scan_block(tree.body, set())
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_block(node.body, set())

    # -- linear per-scope walk ------------------------------------------
    def _scan_block(self, body, dead: set) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue        # separate scope: scanned by run()
            if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                # conservative join over iterations: only intra-body
                # use-after-donation is claimed (the classic bug —
                # folding a chunk then reading it in the same body)
                dead.clear()
                self._scan_block(st.body, dead)
                self._scan_block(st.orelse, dead)
                dead.clear()
                continue
            if isinstance(st, ast.If):
                self._check_reads(st.test, dead)
                d1, d2 = set(dead), set(dead)
                self._scan_block(st.body, d1)
                self._scan_block(st.orelse, d2)
                dead |= d1 | d2
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    self._check_reads(item.context_expr, dead)
                self._scan_block(st.body, dead)
                continue
            if isinstance(st, ast.Try):
                self._scan_block(st.body, dead)
                for h in st.handlers:
                    self._scan_block(h.body, dead)
                self._scan_block(st.orelse, dead)
                self._scan_block(st.finalbody, dead)
                continue
            # reads happen before this statement's own donations land
            self._check_reads(st, dead)
            self._apply_donations(st, dead)
            self._clear_assigned(st, dead)

    def _check_reads(self, node, dead: set) -> None:
        if not dead:
            return
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in dead:
                self.report("DONATED", n,
                            f"{n.id!r} was donated to a jitted call "
                            "(donate_argnums) — its device buffer is "
                            "recycled by the executable; reading it here "
                            "returns garbage on TPU")

    def _apply_donations(self, st, dead: set) -> None:
        for n in ast.walk(st):
            if not isinstance(n, ast.Call):
                continue
            pos = self.donated.get(_dotted(n.func) or "")
            if not pos:
                continue
            for p in pos:
                if p < len(n.args):
                    for sub in ast.walk(n.args[p]):
                        if isinstance(sub, ast.Name):
                            dead.add(sub.id)

    def _clear_assigned(self, st, dead: set) -> None:
        targets = []
        if isinstance(st, ast.Assign):
            targets = st.targets
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            targets = [st.target]
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    dead.discard(n.id)


class _BareExc(ast.NodeVisitor):
    """BAREEXC: handlers that swallow everything.  A bare ``except:`` (or
    ``except BaseException:``) traps KeyboardInterrupt/SystemExit; an
    ``except Exception: pass`` hides real failures from operators — narrow
    the type, or count it in metrics so the swallow is observable."""

    def __init__(self, mi: ModuleIndex, report):
        self.mi = mi
        self.report = report

    def visit_ExceptHandler(self, node):
        reraises = any(isinstance(n, ast.Raise)
                       for n in ast.walk(ast.Module(body=node.body,
                                                    type_ignores=[])))
        path = None if node.type is None else self.mi.resolve(node.type)
        broad = node.type is None or (
            path is not None and path.endswith("BaseException"))
        swallowed = len(node.body) == 1 and isinstance(
            node.body[0], (ast.Pass, ast.Continue))
        if broad and not reraises:
            # cleanup-then-reraise unwind blocks legitimately catch
            # BaseException; SWALLOWING one traps KeyboardInterrupt/SystemExit
            self.report("BAREEXC", node,
                        "swallowed bare/BaseException handler traps "
                        "KeyboardInterrupt/SystemExit — catch Exception "
                        "(or narrower), or re-raise")
        elif swallowed and path is not None and path.endswith("Exception"):
            self.report("BAREEXC", node,
                        "except Exception: pass swallows every failure "
                        "invisibly — narrow the type or count it in "
                        "metrics")
        self.generic_visit(node)


def lint_tree(tree: ast.AST, hot_module: bool, report) -> None:
    """Run all per-file rules over one parsed module.

    ``report(rule, node, msg)`` receives every raw finding (suppression is
    the driver's job)."""
    mi = ModuleIndex(tree)
    _JitMisuse(mi, report).visit(tree)
    _BareExc(mi, report).visit(tree)
    _FailpointHot(mi, report, hot_module).visit(tree)
    _DonatedUse(mi, report).run(tree)

    def walk_defs(body, in_class: bool):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                traced = hot_module or is_jit_decorated(node, mi)
                FunctionTaint(node, mi, traced, report).run()
                if traced:
                    # nested defs inherit traced-ness (compile_plan's
                    # run_local pattern), so the whole subtree is checked
                    _SpanInJit(mi, report).visit(node)
                    _MetricInJit(mi, report).visit(node)
                    _ProgressInJit(mi, report).visit(node)
            elif isinstance(node, ast.ClassDef):
                walk_defs(node.body, True)

    walk_defs(tree.body, False)
