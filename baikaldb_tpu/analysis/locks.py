"""LOCKORDER: package-wide lock acquisition graph + discipline checks.

Phase A (per file) finds lock *definitions* — ``self.NAME =
threading.Lock()/RLock()`` inside a class, ``NAME = threading.Lock()`` at
module level (the analysis/runtime ``GuardedLock`` spellings count too) —
and, per function, the *acquisition structure*: which locks each ``with``
statement holds, which locks/calls happen inside those bodies.

Phase B stitches the package together:

- every nested acquisition ``with A: ... with B:`` adds the edge A -> B;
- calls made while holding A add A -> L for every lock L the callee may
  acquire (call graph limited to same-class methods and same-module
  functions, closed transitively — the resolution a reader can also do);
- a cycle in the resulting graph is a LOCKORDER violation (two threads
  taking the locks in opposite orders deadlock);
- a HOSTSYNC finding lexically inside a with-lock body is a LOCKORDER
  violation too: a blocking device->host round-trip while holding a lock
  stalls every thread queued on it (the binlog retry lock serializes
  thread-per-connection commits — one sync there is a fleet-wide stall).

When the graph is acyclic, ``derived_order`` is a topological order of all
locks that appear in edges — runtime.GuardedLock ranks are validated
against it in tests/test_lint.py, closing the static->runtime loop.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .taint import ModuleIndex

_LOCK_CTORS = ("threading.Lock", "threading.RLock", "GuardedLock",
               "ordered_lock")


def _is_lock_ctor(path: str | None) -> bool:
    return path is not None and any(path.endswith(c) for c in _LOCK_CTORS)


@dataclass(frozen=True)
class LockId:
    module: str             # repo-relative path of the defining file
    cls: str | None         # defining class, None for module-level locks
    attr: str

    def __str__(self) -> str:
        scope = f"{self.cls}." if self.cls else ""
        return f"{self.module}:{scope}{self.attr}"


@dataclass
class _FuncInfo:
    module: str
    cls: str | None
    name: str
    # raw acquisition refs: ("attr", name) for self/obj.NAME, ("name", name)
    acquires: list = field(default_factory=list)
    # (held_raw_ref, callee_key) pairs: call made while holding a lock
    held_calls: list = field(default_factory=list)
    # every callee key in the function (for transitive may-acquire summaries)
    all_calls: list = field(default_factory=list)
    # (held_raw_ref, acquired_raw_ref, line) nested-with edges
    nested: list = field(default_factory=list)
    # (raw_ref, start_line, end_line) with-body line ranges (minus nested
    # defs), for the sync-under-lock check
    held_ranges: list = field(default_factory=list)


class _FileLockPass(ast.NodeVisitor):
    def __init__(self, module: str, tree: ast.AST):
        self.module = module
        self.mi = ModuleIndex(tree)
        self.defs: list[LockId] = []
        self.funcs: list[_FuncInfo] = []
        self._cls: str | None = None
        self._fn: _FuncInfo | None = None
        self._held: list[tuple] = []
        self.visit(tree)

    # -- structure ----------------------------------------------------------

    def visit_ClassDef(self, node):
        prev, self._cls = self._cls, node.name
        self.generic_visit(node)
        self._cls = prev

    def visit_FunctionDef(self, node):
        prev_fn, prev_held = self._fn, self._held
        self._fn = _FuncInfo(self.module, self._cls, node.name)
        self._held = []
        self.funcs.append(self._fn)
        self.generic_visit(node)
        self._fn, self._held = prev_fn, prev_held

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- lock definitions ---------------------------------------------------

    def visit_Assign(self, node):
        if isinstance(node.value, ast.Call) and \
                _is_lock_ctor(self.mi.resolve(node.value.func)):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and self._cls:
                    self.defs.append(LockId(self.module, self._cls, tgt.attr))
                elif isinstance(tgt, ast.Name) and self._fn is None:
                    self.defs.append(LockId(self.module, None, tgt.id))
        self.generic_visit(node)

    # -- acquisitions -------------------------------------------------------

    def _lock_ref(self, expr):
        """Raw reference for a with-item that might be a lock."""
        if isinstance(expr, ast.Attribute) and expr.attr.endswith(
                ("lock", "mu", "mutex", "_lk")):
            return ("attr", expr.attr, self._cls
                    if isinstance(expr.value, ast.Name) and
                    expr.value.id == "self" else None)
        if isinstance(expr, ast.Name) and expr.id.endswith(
                ("lock", "mu", "mutex", "_lk")):
            return ("name", expr.id, None)
        return None

    def visit_With(self, node):
        refs = []
        for item in node.items:
            ref = self._lock_ref(item.context_expr)
            if ref is not None and self._fn is not None:
                if self._held:
                    self._fn.nested.append(
                        (self._held[-1], ref, node.lineno))
                self._fn.acquires.append(ref)
                end = getattr(node, "end_lineno", node.lineno)
                self._fn.held_ranges.append((ref, node.lineno, end))
                refs.append(ref)
                self._held.append(ref)
        self.generic_visit(node)
        for _ in refs:
            self._held.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        if self._fn is not None:
            callee = None
            if isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self":
                callee = ("method", self._cls, node.func.attr)
            elif isinstance(node.func, ast.Attribute):
                # obj.meth(): resolvable when the name is unique in the
                # package (e.g. guard.drain_binlog_retry under the store
                # lock — the edge the binlog retry protocol creates)
                callee = ("anymethod", None, node.func.attr)
            elif isinstance(node.func, ast.Name):
                callee = ("func", None, node.func.id)
            if callee is not None:
                self._fn.all_calls.append(callee)
                if self._held:
                    self._fn.held_calls.append(
                        (self._held[-1], callee, node.lineno))
        self.generic_visit(node)


@dataclass(frozen=True)
class LockFinding:
    module: str
    line: int
    msg: str


class LockGraph:
    """Package-wide aggregation; ``check`` yields LOCKORDER findings."""

    def __init__(self):
        self._files: list[_FileLockPass] = []

    def add_file(self, module: str, tree: ast.AST) -> None:
        self._files.append(_FileLockPass(module, tree))

    # -- resolution ---------------------------------------------------------

    def _resolve(self, fp: _FileLockPass, ref) -> LockId | None:
        kind, name, cls = ref
        defs = self._by_attr.get(name, ())
        if not defs:
            return None
        if kind == "attr" and cls is not None:
            for d in defs:
                if d.module == fp.module and d.cls == cls:
                    return d
        same_mod = [d for d in defs if d.module == fp.module]
        if len(same_mod) == 1:
            return same_mod[0]
        if len(defs) == 1:
            return defs[0]
        return None             # ambiguous: stay silent rather than guess

    # names too generic for unique-name call resolution (dict.get vs a
    # package-level get() would fabricate edges)
    _COMMON_NAMES = frozenset({
        "get", "put", "set", "add", "append", "appendleft", "pop", "popleft",
        "read", "write", "close", "clear", "update", "call", "wait",
        "remove", "release", "acquire", "observe", "send", "recv", "items",
        "keys", "values", "join", "start", "copy", "extend", "index",
        "insert", "sort", "split", "strip", "encode", "decode", "flush",
    })

    def _callee_infos(self, fp: _FileLockPass, callee) -> list:
        """Candidate callees.  obj.meth() resolves to EVERY same-named
        method in the package (unless the name is too generic): the caller
        cannot know which tier implementation it holds, so the may-acquire
        union over all of them is the sound answer (this is how the
        store-lock -> write_ops -> tier-lock edge is found)."""
        kind, cls, name = callee
        matches = []
        for f in self._funcs:
            if f.name != name:
                continue
            if kind == "method" and f.module == fp.module and f.cls == cls:
                return [f]
            if kind == "func" and f.module == fp.module and f.cls is None:
                return [f]
            matches.append(f)
        # unique names only: unioning multiply-defined names (leader /
        # advance / to_pylist across unrelated classes) fabricates edges
        # and false deadlock cycles.  Multiply-defined dispatch (write_ops
        # on the replicated vs remote tier) is a documented blind spot of
        # the static half — the runtime GuardedLock ranks cover it
        if kind == "anymethod" and len(matches) == 1 and \
                name not in self._COMMON_NAMES:
            return matches
        return []

    # -- analysis -----------------------------------------------------------

    def check(self, sync_sites: dict[str, list[int]]) -> tuple[
            list[LockFinding], list[str]]:
        """``sync_sites``: module -> lines of HOSTSYNC findings (pre-
        suppression: an intentional egress sync is still a stall under a
        lock).  Returns (findings, derived_order)."""
        self._by_attr: dict[str, list[LockId]] = {}
        self._funcs: list[_FuncInfo] = []
        for fp in self._files:
            for d in fp.defs:
                self._by_attr.setdefault(d.attr, []).append(d)
            self._funcs.extend(fp.funcs)

        # direct per-function acquisition summaries, then transitive closure
        # over the (same-class / same-module) call graph
        direct: dict[int, set[LockId]] = {}
        calls: dict[int, list] = {}
        fp_of: dict[int, _FileLockPass] = {}
        for fp in self._files:
            for f in fp.funcs:
                key = id(f)
                fp_of[key] = fp
                direct[key] = {lk for lk in
                               (self._resolve(fp, r) for r in f.acquires)
                               if lk is not None}
                calls[key] = [cand for c in f.all_calls
                              for cand in self._callee_infos(fp, c)]
        may: dict[int, set[LockId]] = {k: set(v) for k, v in direct.items()}
        for _ in range(len(self._funcs)):
            changed = False
            for k in may:
                for callee in calls[k]:
                    extra = may.get(id(callee), set()) - may[k]
                    if extra:
                        may[k] |= extra
                        changed = True
            if not changed:
                break

        # edges
        edges: dict[LockId, dict[LockId, tuple]] = {}

        def add_edge(a: LockId, b: LockId, module: str, line: int):
            if a == b:
                return      # re-entrant same-lock (RLock) — not an order
            edges.setdefault(a, {}).setdefault(b, (module, line))

        findings: list[LockFinding] = []
        for fp in self._files:
            for f in fp.funcs:
                for held_ref, ref, line in f.nested:
                    a, b = self._resolve(fp, held_ref), self._resolve(fp, ref)
                    if a is not None and b is not None:
                        add_edge(a, b, fp.module, line)
                for held_ref, callee, line in f.held_calls:
                    a = self._resolve(fp, held_ref)
                    if a is None:
                        continue
                    for target in self._callee_infos(fp, callee):
                        for b in may.get(id(target), ()):
                            add_edge(a, b, fp.module, line)
                # host syncs inside with-lock bodies
                lines = sync_sites.get(fp.module, ())
                for ref, lo, hi in f.held_ranges:
                    lk = self._resolve(fp, ref)
                    if lk is None:
                        continue
                    for ln in lines:
                        if lo < ln <= hi:
                            findings.append(LockFinding(
                                fp.module, ln,
                                f"host sync while holding {lk}: every "
                                "thread queued on the lock stalls for the "
                                "device round-trip — move the sync outside "
                                "the critical section"))

        # cycle detection (DFS), one finding per distinct cycle node-set
        seen_cycles: set[frozenset] = set()
        WHITE, GRAY, BLACK = 0, 1, 2
        color: dict[LockId, int] = {}
        stack: list[LockId] = []

        def dfs(u: LockId):
            color[u] = GRAY
            stack.append(u)
            for v, (module, line) in edges.get(u, {}).items():
                if color.get(v, WHITE) == WHITE:
                    dfs(v)
                elif color.get(v) == GRAY:
                    cyc = stack[stack.index(v):] + [v]
                    key = frozenset(cyc)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        findings.append(LockFinding(
                            module, line,
                            "lock order cycle: "
                            + " -> ".join(str(c) for c in cyc)
                            + " — threads taking these in opposite orders "
                            "deadlock"))
            stack.pop()
            color[u] = BLACK

        for node in list(edges):
            if color.get(node, WHITE) == WHITE:
                dfs(node)

        # derived order: topological over the edge graph (cycle-free part)
        order: list[str] = []
        mark: dict[LockId, int] = {}

        def topo(u: LockId):
            if mark.get(u):
                return
            mark[u] = 1
            for v in edges.get(u, {}):
                topo(v)
            order.append(str(u))

        for node in sorted(edges, key=str):
            topo(node)
        order.reverse()
        edge_list = sorted((str(a), str(b))
                           for a, m in edges.items() for b in m)
        return findings, order, edge_list
