"""Runtime enforcement of the statically-linted invariants (debug_guards).

tpulint proves the *source* clean; this module catches what static analysis
cannot see — dynamically-dispatched host syncs and lock acquisitions — by
arming two guards when the ``debug_guards`` flag is "log" or "disallow":

- ``hot_path_guard()`` wraps compiled-plan execution in a
  ``jax.transfer_guard_device_to_host`` scope: any implicit device->host
  transfer inside the hot path (a stray ``int(x)`` / ``np.asarray``) logs or
  raises instead of silently stalling the pipeline.  Host->device constant
  uploads stay allowed — they are part of tracing.
- ``GuardedLock`` is a drop-in threading.Lock/RLock whose acquisitions
  assert the statically-derived lock ORDER (tools/tpulint.py --lock-order):
  every lock carries a rank, and acquiring a lower/equal rank while holding
  a higher one is an inversion — the dynamic half of LOCKORDER.
  tests/test_lint.py cross-checks the declared ranks against the static
  acquisition graph, so the two layers cannot drift apart.

Trips surface in ``metrics`` (``guard_transfer_trips`` /
``guard_lock_trips``) and on the EXPLAIN ANALYZE ``-- guards:`` line.

CPU caveat: on the CPU backend device->host reads are zero-copy views, so
jax's transfer guard never fires there — the transfer half of debug_guards
is a no-op under JAX_PLATFORMS=cpu and bites on real accelerators, which is
exactly where the sync costs a round-trip.  The lock half is
backend-independent.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from ..utils import metrics
from ..utils.flags import FLAGS, define

define("debug_guards", "off",
       "runtime trace/transfer/lock guards on the hot path: off | log "
       "(transfers logged by jax to stderr, lock trips counted) | disallow "
       "(fail the query/acquisition; trips counted) — the dynamic half of "
       "tools/tpulint.py")

guard_transfer_trips = metrics.Counter("guard_transfer_trips")
guard_lock_trips = metrics.Counter("guard_lock_trips")

# the flag is re-read on every lock acquisition of the hottest paths:
# cache the resolved mode and refresh through the flag listener instead
_MODE = "off"


def _refresh_mode(value=None) -> None:
    global _MODE
    mode = str(FLAGS.debug_guards if value is None else value).lower()
    _MODE = mode if mode in ("log", "disallow") else "off"


_refresh_mode()
FLAGS.on_change("debug_guards", _refresh_mode)


def guard_mode() -> str:
    return _MODE


@contextmanager
def hot_path_guard():
    """Execution scope for compiled query programs: no implicit
    device->host transfer may happen inside.  Egress/flag reads belong
    AFTER this scope, spelled ``jax.device_get``."""
    mode = guard_mode()
    if mode == "off":
        yield
        return
    import jax

    # log mode defers to jax's own stderr logging (the C++ guard offers no
    # python hook to count), so guard_transfer_trips only moves in
    # disallow mode — where the failed query makes the trip loud anyway
    try:
        with jax.transfer_guard_device_to_host(
                "log" if mode == "log" else "disallow"):
            yield
    except Exception as e:
        if "transfer" in str(e).lower():
            guard_transfer_trips.add(1)
        raise


# declared lock ranks, validated against the static graph by
# tests/test_lint.py (every static edge A->B must have rank[A] < rank[B])
LOCK_RANKS: dict[str, int] = {}


class GuardedLock:
    """threading.Lock/RLock + rank-ordered acquisition assertion.

    With debug_guards off, acquire() is one module-global read plus the
    underlying C lock — no stack bookkeeping, no flag parse.  Arming the
    flag mid-hold therefore starts with an empty view of already-held
    locks (checks engage on the next full acquisition chain); that
    best-effort window is the price of a zero-cost production path."""

    _tls = threading.local()

    def __init__(self, name: str, rank: int, reentrant: bool = False):
        self._lk = threading.RLock() if reentrant else threading.Lock()
        self.name = name
        self.rank = rank
        LOCK_RANKS[name] = rank

    @classmethod
    def _stack(cls) -> list:
        st = getattr(cls._tls, "stack", None)
        if st is None:
            st = cls._tls.stack = []
        return st

    def _check_order(self) -> None:
        st = self._stack()
        # re-entering a lock this thread ALREADY holds is always safe
        # (RLock semantics) even if higher-rank locks were taken since
        if self in st:
            return
        # strict >: same-rank locks (two tables' store locks) may nest
        # freely — give locks DISTINCT ranks when their order matters
        if st and st[-1].rank > self.rank:
            guard_lock_trips.add(1)
            msg = (f"lock order violation: acquiring {self.name} "
                   f"(rank {self.rank}) while holding {st[-1].name} "
                   f"(rank {st[-1].rank}) — the static order "
                   "(tools/tpulint.py --lock-order) forbids this nesting")
            if _MODE == "disallow":
                raise RuntimeError(msg)
            import sys
            print(f"tpulint-guard: {msg}", file=sys.stderr)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _MODE == "off":      # production fast path: no bookkeeping
            return self._lk.acquire(blocking, timeout)
        self._check_order()
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            self._stack().append(self)
        return ok

    def release(self) -> None:
        if _MODE != "off":
            st = self._stack()
            if st and st[-1] is self:
                st.pop()
            elif self in st:    # out-of-order release: still unwind
                st.remove(self)
        elif getattr(self._tls, "stack", None):
            # flag flipped off mid-hold: drain stale entries lazily
            st = self._tls.stack
            if self in st:
                st.remove(self)
        self._lk.release()

    def __enter__(self) -> "GuardedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        lk = self._lk
        return lk.locked() if hasattr(lk, "locked") else False


def guard_stats() -> dict:
    """The EXPLAIN ANALYZE / SHOW METRICS payload."""
    return {"mode": guard_mode(),
            "transfer_trips": guard_transfer_trips.value,
            "lock_trips": guard_lock_trips.value}
