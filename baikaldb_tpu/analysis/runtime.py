"""Runtime enforcement of the statically-linted invariants (debug_guards).

tpulint proves the *source* clean; this module catches what static analysis
cannot see — dynamically-dispatched host syncs and lock acquisitions — by
arming two guards when the ``debug_guards`` flag is "log" or "disallow":

- ``hot_path_guard()`` wraps compiled-plan execution in a
  ``jax.transfer_guard_device_to_host`` scope: any implicit device->host
  transfer inside the hot path (a stray ``int(x)`` / ``np.asarray``) logs or
  raises instead of silently stalling the pipeline.  Host->device constant
  uploads stay allowed — they are part of tracing.
- ``GuardedLock`` is a drop-in threading.Lock/RLock whose acquisitions
  assert the statically-derived lock ORDER (tools/tpulint.py --lock-order):
  every lock carries a rank, and acquiring a lower/equal rank while holding
  a higher one is an inversion — the dynamic half of LOCKORDER.
  tests/test_lint.py cross-checks the declared ranks against the static
  acquisition graph, so the two layers cannot drift apart.
- the **lockset witness** is the dynamic half of GUARDEDBY
  (analysis/ownership.py): classes call ``register_witness`` with their
  statically-inferred ``{attr: lock}`` ownership, and arming the flag
  installs ``_OwnedAttr`` data descriptors that assert every access to an
  owned attribute happens while the owning ``GuardedLock`` is held by the
  accessing thread — the static model checked against real interleavings
  by the stress/chaos suites.

Trips surface in ``metrics`` (``guard_transfer_trips`` /
``guard_lock_trips`` / ``guard_owner_trips``) and on the EXPLAIN ANALYZE
``-- guards:`` line.

CPU caveat: on the CPU backend device->host reads are zero-copy views, so
jax's transfer guard never fires there — the transfer half of debug_guards
is a no-op under JAX_PLATFORMS=cpu and bites on real accelerators, which is
exactly where the sync costs a round-trip.  The lock half is
backend-independent.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from ..utils import metrics
from ..utils.flags import FLAGS, define

define("debug_guards", "off",
       "runtime trace/transfer/lock guards on the hot path: off | log "
       "(transfers logged by jax to stderr, lock trips counted) | disallow "
       "(fail the query/acquisition; trips counted) — the dynamic half of "
       "tools/tpulint.py")

guard_transfer_trips = metrics.Counter("guard_transfer_trips")
guard_lock_trips = metrics.Counter("guard_lock_trips")
guard_owner_trips = metrics.Counter("guard_owner_trips")

# the flag is re-read on every lock acquisition of the hottest paths:
# cache the resolved mode and refresh through the flag listener instead
_MODE = "off"


def _refresh_mode(value=None) -> None:
    global _MODE
    mode = str(FLAGS.debug_guards if value is None else value).lower()
    _MODE = mode if mode in ("log", "disallow") else "off"
    _arm_witnesses(_MODE != "off")


# -- lockset witness (dynamic GUARDEDBY) ---------------------------------

# registered classes: cls -> (static_id, {attr: lock_attr}); descriptors
# are installed/removed as the flag flips so production classes stay
# plain-attribute fast when guards are off
_WITNESSES: dict = {}
_ARMED = False


class _OwnedAttr:
    """Data descriptor asserting accesses to a lock-owned instance
    attribute happen while the owning lock is held BY THIS THREAD.  The
    value itself lives in the instance ``__dict__`` (the descriptor wins
    the lookup because it is a data descriptor); the first ``__set__``
    (construction, before the object is published) is exempt."""

    def __init__(self, name: str, lock_attr: str, static_id: str):
        self.name = name
        self.lock_attr = lock_attr
        self.static_id = static_id

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        try:
            val = obj.__dict__[self.name]
        except KeyError:
            raise AttributeError(self.name) from None
        self._check(obj, "read")
        return val

    def __set__(self, obj, value):
        if self.name in obj.__dict__:      # first set = construction
            self._check(obj, "write")
        obj.__dict__[self.name] = value

    def __delete__(self, obj):
        self._check(obj, "delete")
        del obj.__dict__[self.name]

    def _check(self, obj, verb: str) -> None:
        if _MODE == "off":      # descriptors may outlive a flag flip
            return
        lk = getattr(obj, self.lock_attr, None)
        if lk is None:
            return
        if isinstance(lk, GuardedLock):
            held = lk.held_by_me()
        else:                   # plain lock: best effort (any holder)
            held = bool(getattr(lk, "locked", lambda: True)())
        if held:
            return
        guard_owner_trips.add(1)
        msg = (f"lockset witness: {verb} of {self.static_id}.{self.name} "
               f"without holding self.{self.lock_attr} (statically "
               "inferred owner — analysis/ownership.py)")
        if _MODE == "disallow":
            raise RuntimeError(msg)
        import sys
        print(f"tpulint-guard: {msg}", file=sys.stderr)


def register_witness(cls, static_id: str,
                     attrs: dict | None = None) -> None:
    """Enroll ``cls`` in the lockset witness.  ``attrs`` ({attr:
    lock_attr}) defaults to the static pass's inferred ownership for
    ``static_id`` (``analysis.ownership.package_ownership()``), resolved
    lazily at ARM time so import-time registration costs nothing.
    Installs immediately if guards are already armed."""
    if getattr(cls, "__slots__", None) is not None:
        return                  # no instance __dict__ to host the values
    _WITNESSES[cls] = (static_id, attrs)
    if _ARMED:
        _install_witness(cls, static_id, attrs)


def _resolve_attrs(static_id: str, attrs: dict | None) -> dict:
    if attrs is not None:
        return attrs
    from .ownership import package_ownership
    return dict(package_ownership().get(static_id, {}))


def _install_witness(cls, static_id, attrs) -> None:
    for attr, lock_attr in _resolve_attrs(static_id, attrs).items():
        if not isinstance(cls.__dict__.get(attr), _OwnedAttr):
            setattr(cls, attr, _OwnedAttr(attr, lock_attr, static_id))


def _arm_witnesses(on: bool) -> None:
    global _ARMED
    if on == _ARMED:
        return
    _ARMED = on
    for cls, (static_id, attrs) in _WITNESSES.items():
        if on:
            _install_witness(cls, static_id, attrs)
        else:
            for attr, cur in list(cls.__dict__.items()):
                if isinstance(cur, _OwnedAttr):
                    delattr(cls, attr)


def witness_stats() -> dict:
    """Introspection: armed state + per-class witnessed attrs (resolved
    view — triggers the static parse when defaults are in play)."""
    return {"armed": _ARMED,
            "classes": {sid: sorted(_resolve_attrs(sid, attrs))
                        for sid, attrs in _WITNESSES.values()}}


_refresh_mode()
FLAGS.on_change("debug_guards", _refresh_mode)


def guard_mode() -> str:
    return _MODE


@contextmanager
def hot_path_guard():
    """Execution scope for compiled query programs: no implicit
    device->host transfer may happen inside.  Egress/flag reads belong
    AFTER this scope, spelled ``jax.device_get``."""
    mode = guard_mode()
    if mode == "off":
        yield
        return
    import jax

    # log mode defers to jax's own stderr logging (the C++ guard offers no
    # python hook to count), so guard_transfer_trips only moves in
    # disallow mode — where the failed query makes the trip loud anyway
    try:
        with jax.transfer_guard_device_to_host(
                "log" if mode == "log" else "disallow"):
            yield
    except Exception as e:
        if "transfer" in str(e).lower():
            guard_transfer_trips.add(1)
        raise


# declared lock ranks, validated against the static graph by
# tests/test_lint.py (every static edge A->B must have rank[A] < rank[B])
LOCK_RANKS: dict[str, int] = {}


class GuardedLock:
    """threading.Lock/RLock + rank-ordered acquisition assertion.

    With debug_guards off, acquire() is one module-global read plus the
    underlying C lock — no stack bookkeeping, no flag parse.  Arming the
    flag mid-hold therefore starts with an empty view of already-held
    locks (checks engage on the next full acquisition chain); that
    best-effort window is the price of a zero-cost production path."""

    _tls = threading.local()

    def __init__(self, name: str, rank: int, reentrant: bool = False):
        self._lk = threading.RLock() if reentrant else threading.Lock()
        self.name = name
        self.rank = rank
        LOCK_RANKS[name] = rank

    @classmethod
    def _stack(cls) -> list:
        st = getattr(cls._tls, "stack", None)
        if st is None:
            st = cls._tls.stack = []
        return st

    def _check_order(self) -> None:
        st = self._stack()
        # re-entering a lock this thread ALREADY holds is always safe
        # (RLock semantics) even if higher-rank locks were taken since
        if self in st:
            return
        # strict >: same-rank locks (two tables' store locks) may nest
        # freely — give locks DISTINCT ranks when their order matters
        if st and st[-1].rank > self.rank:
            guard_lock_trips.add(1)
            msg = (f"lock order violation: acquiring {self.name} "
                   f"(rank {self.rank}) while holding {st[-1].name} "
                   f"(rank {st[-1].rank}) — the static order "
                   "(tools/tpulint.py --lock-order) forbids this nesting")
            if _MODE == "disallow":
                raise RuntimeError(msg)
            import sys
            print(f"tpulint-guard: {msg}", file=sys.stderr)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _MODE == "off":      # production fast path: no bookkeeping
            return self._lk.acquire(blocking, timeout)
        self._check_order()
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            self._stack().append(self)
        return ok

    def release(self) -> None:
        if _MODE != "off":
            st = self._stack()
            if st and st[-1] is self:
                st.pop()
            elif self in st:    # out-of-order release: still unwind
                st.remove(self)
        elif getattr(self._tls, "stack", None):
            # flag flipped off mid-hold: drain stale entries lazily
            st = self._tls.stack
            if self in st:
                st.remove(self)
        self._lk.release()

    def __enter__(self) -> "GuardedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        lk = self._lk
        return lk.locked() if hasattr(lk, "locked") else False

    def held_by_me(self) -> bool:
        """Whether THIS thread is inside the lock.  Stack-based, so only
        meaningful while debug_guards is armed (acquisitions made with
        guards off were never pushed — the same best-effort window as
        the order check, see the class docstring)."""
        return self in self._stack()


def guard_stats() -> dict:
    """The EXPLAIN ANALYZE / SHOW METRICS payload."""
    return {"mode": guard_mode(),
            "transfer_trips": guard_transfer_trips.value,
            "lock_trips": guard_lock_trips.value,
            "owner_trips": guard_owner_trips.value}
