"""GUARDEDBY / LOCKHELDBLOCK / ATOMICITY: lockset race detection.

LOCKORDER (locks.py) proves locks are *acquired* in a consistent order;
this module proves guarded state is *accessed under its lock* — the
RacerD-style other half.  Three phases:

1. **Guarded-by inference.**  For every class owning a lock attribute
   (``self._mu = threading.Lock()`` — locks.py's discovery spellings), an
   instance attribute whose mutation sites are predominantly (strict
   majority, ``__init__`` excluded) inside ``with self._mu:`` bodies is
   *owned* by that lock.  Module-level dicts/sets/lists guarded by
   module-level locks are inferred the same way.  Helper methods whose
   every intra-package call site holds the lock (or that follow the
   ``*_locked`` naming convention) count as guarded — the lock is held
   through the caller.

2. **Race flagging (GUARDEDBY).**  The call graph (callgraph.py) marks a
   class *concurrent* when any of its methods is reachable from a spawned
   thread / RPC handler / loop-entry root; the main thread is an implicit
   second root.  Every read or write of owned state in a concurrent class
   on a path that does not hold the owning lock is a finding.  Ownership
   needs a strict majority on purpose: a class that is sloppy everywhere
   never had a locking discipline to enforce, while a disciplined class
   that forgot the lock *once* is exactly the bug this rule exists for.

3. **Blocking + atomicity (LOCKHELDBLOCK, ATOMICITY).**  LOCKHELDBLOCK
   flags calls that block the host — ``time.sleep``, RPC round-trips
   (``send_msg``/``recv_msg``/client ``.call``), ``jax.device_get`` /
   ``block_until_ready`` syncs, file/subprocess I/O — while any discovered
   lock is held: every thread queued on that lock inherits the stall
   (LOCKORDER's sync-under-lock check generalized beyond HOSTSYNC taint).
   ATOMICITY flags check-then-act: an ``if`` whose test reads owned state
   *outside* the lock and whose body re-acquires the lock to act on the
   same state — the decision is stale by the time the lock arrives.

``OwnershipGraph.check`` also returns the inferred ownership map
(``"module:Class" -> {attr: lock_attr}``), exported through
``run_lint.last_ownership`` and consumed by analysis/runtime.py's lockset
witness (``debug_guards``): the static model is asserted against real
interleavings by the stress/chaos suites.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .locks import _is_lock_ctor
from .taint import ModuleIndex

# attribute-name endings that look like locks in a with-item (locks.py)
_LOCKISH = ("lock", "mu", "mutex", "_lk")

# container-method calls that mutate the receiver
_MUTATORS = frozenset({
    "append", "appendleft", "add", "remove", "discard", "clear", "update",
    "setdefault", "pop", "popleft", "popitem", "insert", "extend",
    "move_to_end", "put",
})

# flagging exclusions: construction happens before the object is published
_PREPUBLISH = frozenset({"__init__", "__new__", "__del__"})

# module-global container constructors worth tracking
_CONTAINER_CTORS = frozenset({
    "dict", "set", "list", "defaultdict", "OrderedDict", "deque", "Counter",
})

# resolved call targets that block the host (LOCKHELDBLOCK); tail-matched
# names cover the from-import spellings (``from .net import send_msg``)
_BLOCKING_PATHS = {
    "time.sleep": "time.sleep",
    "jax.device_get": "device->host sync",
    "jax.block_until_ready": "device->host sync",
    "subprocess.run": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.Popen": "subprocess",
    "socket.create_connection": "network connect",
    "os.fsync": "file I/O",
    "os.replace": "file I/O",
    "open": "file I/O",
}
_BLOCKING_TAILS = {
    "send_msg": "network I/O",
    "recv_msg": "network I/O",
    "block_until_ready": "device->host sync",
}
# obj.call()/obj.try_call() is an RPC round-trip when the receiver is
# named like a client handle; bare ``.call`` alone is too generic
_RPCISH_RECEIVERS = ("client", "peer", "rpc", "stub", "cli", "conn")


@dataclass(frozen=True)
class _Access:
    scope: tuple            # ("cls", name) | ("mod", None)
    attr: str
    line: int
    mut: bool
    held: frozenset         # raw lock refs held at the access site
    func: tuple             # (cls, fname, lineno) of the enclosing function
    rebind: bool = False    # mutation is a whole-attribute ``x = ...``


@dataclass
class _OwnFunc:
    cls: str | None
    name: str
    line: int
    localized: frozenset = frozenset()   # bare names bound locally

    @property
    def key(self) -> tuple:
        return (self.cls, self.name, self.line)


class _FileOwnerPass(ast.NodeVisitor):
    """One file: lock defs, state accesses with their held-lock context,
    call sites (for held-through-caller), blocking calls, if-guard shapes."""

    def __init__(self, module: str, tree: ast.AST):
        self.module = module
        self.mi = ModuleIndex(tree)
        self.class_locks: dict[str, list[str]] = {}   # cls -> lock attrs
        self.module_locks: list[str] = []
        self.mod_state: set[str] = set()              # module-level containers
        self.accesses: list[_Access] = []
        # (callee_ref, held_frozenset, caller_func_key)
        self.calls: list[tuple] = []
        # (held_refs_tuple, line, desc, dotted_path, caller_func_key) —
        # recorded for EVERY blocking-shaped call; attribution to a lock
        # (lexically held or held through every caller) happens at check
        self.blocking: list[tuple] = []
        # (scope, attr, lock_ref, if_line, caller_func_key): test read the
        # attr without the lock, body touched it under the lock
        self.atomicity: list[tuple] = []
        self.funcs: list[_OwnFunc] = []
        self._cls: str | None = None
        self._fn: _OwnFunc | None = None
        self._held: list[tuple] = []
        self._ifs: list[dict] = []      # open if-contexts
        self._skip: set[int] = set()    # node ids already recorded
        self.visit(tree)

    # -- structure ----------------------------------------------------------

    def visit_ClassDef(self, node):
        prev, self._cls = self._cls, node.name
        self.generic_visit(node)
        self._cls = prev

    def visit_FunctionDef(self, node):
        localized = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and \
                    isinstance(sub.ctx, (ast.Store, ast.Del)):
                localized.add(sub.id)
            elif isinstance(sub, (ast.Global, ast.Nonlocal)):
                localized.difference_update(sub.names)
        prev_fn, prev_held, prev_ifs = self._fn, self._held, self._ifs
        self._fn = _OwnFunc(self._cls, node.name, node.lineno,
                            frozenset(localized))
        self.funcs.append(self._fn)
        self._held, self._ifs = [], []
        for arg_default in node.args.defaults + node.args.kw_defaults:
            if arg_default is not None:
                self.visit(arg_default)
        for stmt in node.body:
            self.visit(stmt)
        self._fn, self._held, self._ifs = prev_fn, prev_held, prev_ifs

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- definitions & mutations --------------------------------------------

    def visit_Assign(self, node):
        if isinstance(node.value, ast.Call) and \
                _is_lock_ctor(self.mi.resolve(node.value.func)):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and self._cls:
                    self.class_locks.setdefault(
                        self._cls, []).append(tgt.attr)
                elif isinstance(tgt, ast.Name) and self._fn is None:
                    self.module_locks.append(tgt.id)
            return
        if self._fn is None:
            # module level: collect container defs, skip access tracking
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and self._is_container(
                        node.value):
                    self.mod_state.add(tgt.id)
            self.visit(node.value)
            return
        for tgt in node.targets:
            # a plain ``self.x = ...`` is an atomic reference swap under
            # the GIL — _record keeps that distinction for swap-publish
            self._record_target(tgt, node.lineno, rebind=True)
        self.visit(node.value)

    @staticmethod
    def _is_container(value) -> bool:
        if isinstance(value, (ast.Dict, ast.Set, ast.List, ast.DictComp,
                              ast.SetComp, ast.ListComp)):
            return True
        return isinstance(value, ast.Call) and \
            isinstance(value.func, ast.Name) and \
            value.func.id in _CONTAINER_CTORS

    def visit_AugAssign(self, node):
        if self._fn is not None:
            self._record_target(node.target, node.lineno)
        self.visit(node.value)

    def visit_Delete(self, node):
        if self._fn is not None:
            for tgt in node.targets:
                self._record_target(tgt, node.lineno)

    def _record_target(self, tgt, line, rebind=False):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._record_target(elt, line, rebind)
        elif isinstance(tgt, ast.Starred):
            self._record_target(tgt.value, line, rebind)
        elif isinstance(tgt, ast.Attribute):
            if isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
                self._record("cls", tgt.attr, line, mut=True, rebind=rebind)
            else:
                self.visit(tgt.value)
        elif isinstance(tgt, ast.Subscript):
            base = tgt.value
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self":
                self._record("cls", base.attr, line, mut=True)
            elif isinstance(base, ast.Name) and base.id in self.mod_state:
                self._record("mod", base.id, line, mut=True)
            else:
                self.visit(base)
            self.visit(tgt.slice)
        elif isinstance(tgt, ast.Name):
            if tgt.id in self.mod_state:
                self._record("mod", tgt.id, line, mut=True, rebind=rebind)

    # -- reads, calls, blocking ---------------------------------------------

    def visit_Attribute(self, node):
        if id(node) in self._skip:
            self.visit(node.value)      # still descend into the receiver
            return
        if isinstance(node.value, ast.Name) and node.value.id == "self" \
                and isinstance(node.ctx, ast.Load) and self._fn is not None:
            self._record("cls", node.attr, node.lineno, mut=False)
            return
        self.visit(node.value)

    def visit_Name(self, node):
        if id(node) in self._skip:
            return
        if isinstance(node.ctx, ast.Load) and self._fn is not None and \
                node.id in self.mod_state:
            self._record("mod", node.id, node.lineno, mut=False)

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            self._skip.add(id(fn))
            base = fn.value
            if fn.attr in _MUTATORS and self._fn is not None:
                if isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id == "self":
                    self._skip.add(id(base))
                    self._record("cls", base.attr, node.lineno, mut=True)
                elif isinstance(base, ast.Name) and base.id in self.mod_state:
                    self._skip.add(id(base))
                    self._record("mod", base.id, node.lineno, mut=True)
            # callee ref for held-through-caller resolution
            if isinstance(base, ast.Name) and base.id == "self":
                self._add_call(("method", self._cls, fn.attr))
            else:
                self._add_call(("anymethod", None, fn.attr))
        elif isinstance(fn, ast.Name):
            self._add_call(("func", None, fn.id))
        if self._fn is not None:
            self._classify_blocking(node)
        self.generic_visit(node)

    def _add_call(self, ref):
        if self._fn is not None:
            self.calls.append((ref, frozenset(self._held), self._fn.key))

    def _classify_blocking(self, node):
        path = self.mi.resolve(node.func)
        desc = None
        if path is not None:
            desc = _BLOCKING_PATHS.get(path) \
                or _BLOCKING_TAILS.get(path.rsplit(".", 1)[-1])
        if desc is None and isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("call", "try_call"):
            recv = node.func.value
            name = recv.attr if isinstance(recv, ast.Attribute) else (
                recv.id if isinstance(recv, ast.Name) else "")
            if any(tag in name.lower() for tag in _RPCISH_RECEIVERS):
                desc, path = "RPC round-trip", f"{name}.{node.func.attr}"
        if desc is not None:
            self.blocking.append((tuple(self._held), node.lineno, desc,
                                  path, self._fn.key))

    # -- lock scopes & if-guard shapes --------------------------------------

    def _lock_ref(self, expr):
        if isinstance(expr, ast.Attribute) and expr.attr.endswith(_LOCKISH):
            return ("attr", expr.attr, self._cls
                    if isinstance(expr.value, ast.Name) and
                    expr.value.id == "self" else None)
        if isinstance(expr, ast.Name) and expr.id.endswith(_LOCKISH):
            return ("name", expr.id, None)
        return None

    def visit_With(self, node):
        refs = []
        for item in node.items:
            self.visit(item.context_expr)
            ref = self._lock_ref(item.context_expr)
            if ref is not None and self._fn is not None:
                refs.append(ref)
                self._held.append(ref)
        for stmt in node.body:
            self.visit(stmt)
        for _ in refs:
            self._held.pop()

    visit_AsyncWith = visit_With

    def visit_If(self, node):
        ctx = None
        if self._fn is not None:
            test_attrs = set()
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Attribute) and \
                        isinstance(sub.value, ast.Name) and \
                        sub.value.id == "self":
                    test_attrs.add(("cls", sub.attr))
                elif isinstance(sub, ast.Name) and sub.id in self.mod_state:
                    test_attrs.add(("mod", sub.id))
            if test_attrs:
                ctx = {"attrs": test_attrs, "held": frozenset(self._held),
                       "line": node.lineno, "func": self._fn.key}
        self.visit(node.test)
        if ctx is not None:
            self._ifs.append(ctx)
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        if ctx is not None:
            self._ifs.pop()

    # -- recording ----------------------------------------------------------

    def _record(self, kind, attr, line, mut, rebind=False):
        if self._fn is None:
            return
        scope = (kind, self._cls) if kind == "cls" else (kind, None)
        held = frozenset(self._held)
        self.accesses.append(_Access(scope, attr, line, mut, held,
                                     self._fn.key, rebind))
        # check-then-act: this access is under a lock the enclosing if's
        # test did NOT hold while reading the same state
        key = (kind, attr)
        for ref in self._held:
            for ctx in self._ifs:
                if key in ctx["attrs"] and ref not in ctx["held"]:
                    self.atomicity.append(
                        ((kind, self._cls if kind == "cls" else None),
                         attr, ref, ctx["line"], ctx["func"]))


@dataclass(frozen=True)
class OwnerFinding:
    rule: str
    module: str
    line: int
    msg: str


class OwnershipGraph:
    """Package-wide aggregation; ``check`` yields the three rules'
    findings plus the inferred ownership map."""

    def __init__(self):
        self._files: list[_FileOwnerPass] = []

    def add_file(self, module: str, tree: ast.AST) -> None:
        self._files.append(_FileOwnerPass(module, tree))

    # -- resolution ---------------------------------------------------------

    def _lock_name(self, fp: _FileOwnerPass, ref) -> str | None:
        """Resolve a raw held ref to a lock identity string
        ``module:Cls.attr`` / ``module:name`` using the discovered defs."""
        kind, name, cls = ref
        if kind == "attr":
            if cls is not None and name in fp.class_locks.get(cls, ()):
                return f"{fp.module}:{cls}.{name}"
            owners = [(fp.module, c) for c, attrs in fp.class_locks.items()
                      if name in attrs]
            if not owners:   # cross-file: unique attr name in the package
                owners = [(o.module, c) for o in self._files
                          for c, attrs in o.class_locks.items()
                          if name in attrs]
            if len(owners) == 1:
                return f"{owners[0][0]}:{owners[0][1]}.{name}"
            return None
        if name in fp.module_locks:
            return f"{fp.module}:{name}"
        return None

    def _holds(self, fp, access_held, cls, lock_attr) -> bool:
        for kind, name, hcls in access_held:
            if kind == "attr" and name == lock_attr and \
                    (hcls == cls or hcls is None):
                return True
        return False

    def _holds_mod(self, access_held, lock_name) -> bool:
        return any(kind == "name" and name == lock_name
                   for kind, name, _ in access_held)

    # -- held-through-caller fixpoint ---------------------------------------

    def _locked_context(self) -> dict:
        """(module, cls, fname) -> set of lock attrs held at EVERY intra-
        package call site — the lock is held *through the caller*, so the
        function's body is effectively inside the critical section.  The
        ``*_locked`` naming convention seeds the fixpoint; one iteration
        per nesting level closes chains like call -> _call_retrying ->
        _recv_cancellable."""
        sites: dict[tuple, list] = {}
        for fp in self._files:
            for ref, held, caller in fp.calls:
                kind, cls, name = ref
                tgt_mod = fp.module if kind in ("method", "func") else None
                sites.setdefault((tgt_mod, cls, name), []).append(
                    (fp.module, held, caller))
        out: dict[tuple, set] = {}
        for fp in self._files:
            for f in fp.funcs:
                if f.name.endswith("_locked") and f.cls is not None:
                    out.setdefault((fp.module, f.cls, f.name), set()).update(
                        fp.class_locks.get(f.cls, ()))
        for _ in range(8):          # fixpoint over call-chain depth
            changed = False
            for fp in self._files:
                for f in fp.funcs:
                    if f.cls is None:
                        continue
                    key = (fp.module, f.cls, f.name)
                    callers = sites.get((fp.module, f.cls, f.name), []) + \
                        sites.get((None, None, f.name), [])
                    if not callers:
                        continue
                    for lock_attr in fp.class_locks.get(f.cls, ()):
                        if lock_attr in out.get(key, ()):
                            continue
                        if all(self._holds(None, held, f.cls, lock_attr)
                               or lock_attr in out.get(
                                   (cmod, c[0], c[1]), ())
                               for cmod, held, c in callers):
                            out.setdefault(key, set()).add(lock_attr)
                            changed = True
            if not changed:
                break
        return out

    # -- analysis -----------------------------------------------------------

    def check(self, callgraph) -> tuple[list[OwnerFinding], dict]:
        findings: list[OwnerFinding] = []
        ownership: dict[str, dict[str, str]] = {}
        locked_ctx = self._locked_context()
        concurrent = callgraph.concurrent_classes() if callgraph else set()

        for fp in self._files:
            self._check_classes(fp, callgraph, concurrent, locked_ctx,
                                ownership, findings)
            self._check_module_state(fp, callgraph, findings)
            self._check_blocking(fp, locked_ctx, findings)
        findings.sort(key=lambda f: (f.module, f.line, f.rule))
        return findings, ownership

    def _guarded(self, fp, acc: _Access, cls, lock_attr, locked_ctx) -> bool:
        if self._holds(fp, acc.held, cls, lock_attr):
            return True
        fcls, fname, fline = acc.func
        return lock_attr in locked_ctx.get((fp.module, fcls, fname), ())

    def _check_classes(self, fp, callgraph, concurrent, locked_ctx,
                       ownership, findings):
        for cls, lock_attrs in fp.class_locks.items():
            accs = [a for a in fp.accesses
                    if a.scope == ("cls", cls) and a.attr not in lock_attrs]
            by_attr: dict[str, list[_Access]] = {}
            for a in accs:
                by_attr.setdefault(a.attr, []).append(a)
            owned: dict[str, str] = {}
            for attr, alist in by_attr.items():
                muts = [a for a in alist if a.mut
                        and a.func[1] not in _PREPUBLISH]
                best, best_n = None, 0
                for lk in lock_attrs:
                    n = sum(1 for m in muts
                            if self._guarded(fp, m, cls, lk, locked_ctx))
                    if n > best_n:
                        best, best_n = lk, n
                # strict majority: a disciplined class that slipped once is
                # the target; a class with no discipline is not inferred
                if best is not None and 2 * best_n > len(muts):
                    owned[attr] = best
            # the exported map (the runtime witness's assertion input)
            # excludes swap-published attrs: their lockless reads are
            # legal (see the downgrade below), so a per-read runtime
            # assertion on them would trip on correct code
            exported = {
                attr: lk for attr, lk in owned.items()
                if not all(a.rebind for a in by_attr[attr] if a.mut)}
            if exported:
                ownership[f"{fp.module}:{cls}"] = exported
            if (fp.module, cls) not in concurrent:
                continue
            for attr, lk in sorted(owned.items()):
                # swap-publish downgrade: when EVERY mutation site is a
                # whole-attribute rebind (never subscript/aug/del/mutator),
                # an unguarded read is an atomic reference load under the
                # GIL — the copy-then-rebind publish idiom (catalog _snap,
                # binlog _table) is safe by construction.  Unguarded
                # WRITES still race (lost update between two rebinds).
                swap_pub = all(a.rebind for a in by_attr[attr] if a.mut)
                for a in by_attr[attr]:
                    if a.func[1] in _PREPUBLISH or \
                            (not a.mut and swap_pub) or \
                            self._guarded(fp, a, cls, lk, locked_ctx):
                        continue
                    kind = "write to" if a.mut else "read of"
                    findings.append(OwnerFinding(
                        "GUARDEDBY", fp.module, a.line,
                        f"unguarded {kind} {cls}.{attr} (owned by "
                        f"self.{lk}: its other mutation sites hold the "
                        f"lock, and {cls} runs on >= 2 threads) — take "
                        f"the lock or move the access under an existing "
                        "critical section"))
                self._check_atomicity(fp, ("cls", cls), attr, lk, findings)

    def _check_module_state(self, fp, callgraph, findings):
        if not fp.module_locks or not fp.mod_state:
            return
        by_name: dict[str, list[_Access]] = {}
        for a in fp.accesses:
            if a.scope == ("mod", None):
                fn = next((f for f in fp.funcs
                           if f.key == a.func), None)
                if fn is not None and a.attr in fn.localized:
                    continue        # locally shadowed name, not the global
                by_name.setdefault(a.attr, []).append(a)
        for name, alist in by_name.items():
            muts = [a for a in alist if a.mut]
            best, best_n = None, 0
            for lk in fp.module_locks:
                n = sum(1 for m in muts if self._holds_mod(m.held, lk))
                if n > best_n:
                    best, best_n = lk, n
            if best is None or 2 * best_n <= len(muts):
                continue
            hot = callgraph is not None and any(
                callgraph.spawned_roots_of(fp.module, f[0], f[1], f[2])
                for f in {a.func for a in alist})
            if not hot:
                continue
            swap_pub = all(a.rebind for a in muts)
            for a in alist:
                if self._holds_mod(a.held, best) or \
                        (not a.mut and swap_pub):
                    continue
                kind = "write to" if a.mut else "read of"
                findings.append(OwnerFinding(
                    "GUARDEDBY", fp.module, a.line,
                    f"unguarded {kind} module state {name} (owned by "
                    f"{best}: its other mutation sites hold the lock) — "
                    "take the lock around the access"))
            self._check_atomicity(fp, ("mod", None), name, best, findings)

    def _check_atomicity(self, fp, scope, attr, lock_attr, findings):
        seen = set()
        for a_scope, a_attr, ref, if_line, func in fp.atomicity:
            if a_scope != scope or a_attr != attr:
                continue
            kind, name, cls = ref
            if name != lock_attr or (if_line, a_attr) in seen:
                continue
            seen.add((if_line, a_attr))
            label = f"self.{lock_attr}" if scope[0] == "cls" else lock_attr
            findings.append(OwnerFinding(
                "ATOMICITY", fp.module, if_line,
                f"check-then-act on {attr}: the if-test reads it without "
                f"{label} but the body re-acquires the lock to act on it "
                "— the checked state can change before the lock arrives; "
                "take the lock around the whole check+act sequence"))

    def _check_blocking(self, fp, locked_ctx, findings):
        for held_refs, line, desc, path, func in fp.blocking:
            names = [n for n in (self._lock_name(fp, r)
                                 for r in reversed(held_refs)) if n]
            fcls, fname, _fline = func
            for lock_attr in sorted(
                    locked_ctx.get((fp.module, fcls, fname), ())):
                if fcls is not None and \
                        lock_attr in fp.class_locks.get(fcls, ()):
                    names.append(
                        f"{fp.module}:{fcls}.{lock_attr} (held through "
                        "every caller)")
            if not names:
                continue
            findings.append(OwnerFinding(
                "LOCKHELDBLOCK", fp.module, line,
                f"{desc} ({path}) while holding {names[0]}: every thread "
                "queued on the lock inherits the stall — move the "
                "blocking call outside the critical section or snapshot "
                "state under the lock and act after release"))


# -- runtime witness export ---------------------------------------------

_PKG_OWNERSHIP: dict | None = None


def package_ownership(refresh: bool = False) -> dict:
    """Inferred ownership map for the installed package tree, keyed
    ``"baikaldb_tpu/<mod>.py:Class" -> {attr: lock_attr}`` — the input the
    runtime lockset witness (analysis/runtime.py) arms its per-attribute
    assertions from.  Parsed once per process; ``refresh`` re-runs."""
    global _PKG_OWNERSHIP
    if _PKG_OWNERSHIP is not None and not refresh:
        return _PKG_OWNERSHIP
    import os
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(pkg_dir)
    from .callgraph import CallGraph
    graph, cg = OwnershipGraph(), CallGraph()
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", ".git"))
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except (OSError, SyntaxError):
                continue
            graph.add_file(rel, tree)
            cg.add_file(rel, tree)
    _, ownership = graph.check(cg)
    _PKG_OWNERSHIP = ownership
    return ownership
