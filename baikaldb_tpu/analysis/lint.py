"""tpulint driver: file discovery, rule execution, suppression handling.

Suppression channels (all explicit, all greppable):

- inline, same line:        ``x = int(flag)  # tpulint: disable=HOSTSYNC``
- inline, next line:        ``# tpulint: disable-next-line=HOSTSYNC,RETRACE``
- whole file:               ``# tpulint: disable-file=BAREEXC`` (top of file)
- suppression file:         one ``path RULE line-or-qualname-or-*`` entry
  per line (see tools/tpulint_suppressions.txt) — the reviewed registry of
  *intentional* sync points (egress materialization, host-side caches).

``run_lint`` returns the surviving violations; exit-code policy belongs to
the CLI (tools/tpulint.py).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from .callgraph import CallGraph
from .locks import LockGraph
from .ownership import OwnershipGraph
from .rules import lint_tree

_INLINE_RE = re.compile(
    r"#\s*tpulint:\s*(disable|disable-next-line|disable-file)="
    r"([A-Z]+(?:\s*,\s*[A-Z]+)*)")

RULES = ("HOSTSYNC", "RETRACE", "TRACERLEAK", "LOCKORDER", "BAREEXC",
         "SPANINJIT", "FAILPOINTHOT", "METRICINJIT", "PROGRESSINJIT",
         "DONATED", "GUARDEDBY", "LOCKHELDBLOCK", "ATOMICITY")


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str               # repo-relative, forward slashes
    line: int
    col: int
    msg: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.msg}"


@dataclass
class LintConfig:
    """``hot_paths``: package-relative prefixes of the jit-traced modules —
    functions there get the traced-scope rules without needing a decorator."""
    hot_paths: tuple = ("ops/", "parallel/", "column/", "exec/executor.py",
                        "expr/compile.py", "expr/builtins_ext.py",
                        "expr/builtins_ext2.py")
    package: str = "baikaldb_tpu"
    suppression_file: str | None = None
    rules: tuple = RULES

    def is_hot(self, relpath: str) -> bool:
        norm = relpath.replace(os.sep, "/")
        marker = f"{self.package}/"
        idx = norm.find(marker)
        sub = norm[idx + len(marker):] if idx >= 0 else norm
        return any(sub.startswith(h) for h in self.hot_paths)


@dataclass
class Suppressions:
    # (path, rule) -> list of scopes; scope is "*", an int line, or a name
    entries: dict = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "Suppressions":
        sup = cls()
        with open(path) as f:
            for raw in f:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split()
                if len(parts) not in (2, 3):
                    raise ValueError(
                        f"{path}: bad suppression line {line!r} "
                        "(want: <path> <RULE> [line|qualname|*])")
                fpath, rule = parts[0], parts[1]
                scope: object = parts[2] if len(parts) == 3 else "*"
                if isinstance(scope, str) and scope.isdigit():
                    scope = int(scope)
                sup.entries.setdefault(
                    (fpath.replace(os.sep, "/"), rule), []).append(scope)
        return sup

    def matches(self, v: Violation, func_at_line) -> bool:
        for scope in self.entries.get((v.path, v.rule), ()):
            if scope == "*":
                return True
            if isinstance(scope, int) and scope == v.line:
                return True
            if isinstance(scope, str) and func_at_line(v.line) == scope:
                return True
        return False


def _inline_suppressed(src_lines: list[str], v: Violation) -> bool:
    def rules_on(line_no: int, directives: tuple) -> set[str]:
        if not (1 <= line_no <= len(src_lines)):
            return set()
        m = _INLINE_RE.search(src_lines[line_no - 1])
        if m and m.group(1) in directives:
            return {r.strip() for r in m.group(2).split(",")}
        return set()

    if v.rule in rules_on(v.line, ("disable",)):
        return True
    if v.rule in rules_on(v.line - 1, ("disable-next-line",)):
        return True
    for ln in src_lines[:5]:
        m = _INLINE_RE.search(ln)
        if m and m.group(1) == "disable-file" and \
                v.rule in {r.strip() for r in m.group(2).split(",")}:
            return True
    return False


def _collect_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def _relpath(path: str, root: str | None) -> str:
    rel = os.path.relpath(path, root) if root else path
    return rel.replace(os.sep, "/").lstrip("./")


class _FuncIndex:
    """line -> enclosing function name (for qualname-scoped suppressions)."""

    def __init__(self, tree: ast.AST):
        self.spans: list[tuple[int, int, str]] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.spans.append((node.lineno,
                                   getattr(node, "end_lineno", node.lineno),
                                   node.name))

    def at(self, line: int) -> str | None:
        best = None
        for lo, hi, name in self.spans:
            if lo <= line <= hi and (best is None or lo > best[0]):
                best = (lo, name)
        return best[1] if best else None


def run_lint(paths: list[str], config: LintConfig | None = None,
             root: str | None = None) -> list[Violation]:
    """Lint ``paths`` (files/dirs); returns surviving violations sorted by
    (path, line).  ``root`` anchors the repo-relative paths used for
    reporting and suppression matching (defaults to cwd)."""
    config = config or LintConfig()
    sup = Suppressions.load(config.suppression_file) \
        if config.suppression_file else Suppressions()
    files = _collect_files(paths)
    graph = LockGraph()
    owners = OwnershipGraph()
    callgraph = CallGraph()
    raw: list[Violation] = []
    sources: dict[str, list[str]] = {}
    findex: dict[str, _FuncIndex] = {}
    sync_sites: dict[str, list[int]] = {}

    for path in files:
        rel = _relpath(path, root)
        with open(path, encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            raw.append(Violation("RETRACE", rel, e.lineno or 0, 0,
                                 f"file does not parse: {e.msg}"))
            continue
        sources[rel] = src.splitlines()
        findex[rel] = _FuncIndex(tree)
        seen: set[tuple] = set()

        def report(rule, node, msg, rel=rel):
            line = getattr(node, "lineno", 0)
            col = getattr(node, "col_offset", 0)
            key = (rule, line, col)
            if key in seen:
                return
            seen.add(key)
            raw.append(Violation(rule, rel, line, col, msg))
            if rule == "HOSTSYNC":
                sync_sites.setdefault(rel, []).append(line)

        lint_tree(tree, config.is_hot(rel), report)
        graph.add_file(rel, tree)
        owners.add_file(rel, tree)
        callgraph.add_file(rel, tree)

    lock_findings, lock_order, lock_edges = graph.check(sync_sites)
    for lf in lock_findings:
        raw.append(Violation("LOCKORDER", lf.module, lf.line, 0, lf.msg))
    owner_findings, ownership = owners.check(callgraph)
    for of in owner_findings:
        raw.append(Violation(of.rule, of.module, of.line, 0, of.msg))
    # introspection for tests/docs: the derived order + raw A->B edges +
    # the inferred guarded-by map the runtime witness arms from
    run_lint.last_lock_order = lock_order
    run_lint.last_lock_edges = lock_edges
    run_lint.last_ownership = ownership

    out = []
    for v in raw:
        if v.rule not in config.rules:
            continue
        lines = sources.get(v.path, [])
        if lines and _inline_suppressed(lines, v):
            continue
        fi = findex.get(v.path)
        if sup.matches(v, fi.at if fi else lambda _ln: None):
            continue
        out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


run_lint.last_lock_order = []
run_lint.last_lock_edges = []
run_lint.last_ownership = {}
