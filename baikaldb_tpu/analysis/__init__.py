"""tpulint — engine-specific static analysis for trace/transfer hygiene.

The TPU query engine lives or dies by three invariants a Python reader
cannot see locally (PAPERS.md: "Query Processing on Tensor Computation
Runtimes" — tensor-runtime engines keep data-dependent control flow and
host round-trips out of compiled paths):

- no silent host syncs on the hot path (HOSTSYNC),
- no trace-key churn / data-dependent shapes outside the sel-mask
  machinery (RETRACE, TRACERLEAK),
- a cycle-free, sync-free lock discipline (LOCKORDER),

plus BAREEXC for swallow-all exception handlers.  ``lint.run_lint`` drives
the per-file rules (rules.py, over the taint engine in taint.py) and the
package-wide lock-graph pass (locks.py); ``tools/tpulint.py`` is the CLI
and ``tests/test_lint.py`` pins the tree at zero violations.
"""

from .lint import LintConfig, Violation, run_lint  # noqa: F401
