"""Failpoint registry: programmable fault injection at the distributed seams.

The reference hardens its braft/brpc surface by injecting faults at seams
(the gofail/failpoint pattern: a named point compiled into the code, armed
at runtime with an action spec).  Here every distributed seam of the repro
carries a named point — the catalog below is the authoritative list — and
each site follows one discipline, enforced by tpulint's FAILPOINTHOT rule:

    if failpoint.ENABLED:
        if failpoint.hit("rpc.send", method=method):
            ...drop handling...

so a disabled build pays exactly one module-attribute bool read per site
(the ``tracing`` off-switch discipline), and no site may live inside
jit-traced scope (a host-side sleep/raise baked into an XLA program would
fire at trace time, not run time).

Actions (armed per point via ``SET failpoint.<name> = '<spec>'``, the
``chaos_enable``/``chaos_seed`` flag pair, or :func:`set_failpoint`):

- ``return(msg)`` — raise :class:`FailpointError` at the site (an injected
  typed failure the caller's error handling must absorb),
- ``delay(ms)``   — sleep ``ms`` milliseconds (latency injection),
- ``drop``        — ``hit()`` returns True; the SITE decides what a drop
  means (lose the frame, skip the append, defer the apply — the per-site
  semantics are the docs/CHAOS.md catalog),
- ``panic``       — raise :class:`FailpointPanic`, a BaseException, so the
  fault-isolation ``except Exception`` handlers cannot swallow it: the
  in-process daemon crashes (``utils.net.RpcServer`` turns it into its
  ``on_panic`` crash hook).

Spec grammar: ``[P%][N*]action[(arg)]`` — ``P%`` triggers with probability
P (default: always), ``N*`` fires at most N times, e.g. ``30%delay(20)``,
``1*panic``, ``return(no quorum)``, ``50%drop``.

Determinism contract: every armed point owns a ``random.Random`` seeded by
``(chaos_seed, point name)`` and consumes exactly one draw per ``hit()``,
so the trigger schedule of a point is a pure function of (seed, name,
hit index) — independent of which other points are armed or how their
evaluations interleave.  On the single-threaded LocalBus plane (raft fleet
mode) whole chaos runs replay bit-identically; on the threaded daemon plane
each point's schedule is still deterministic per hit sequence, but thread
interleaving owns the hit order.  Re-arming a point or changing
``chaos_seed`` resets the point's RNG (a fresh schedule from hit 0).

Trips land in ``metrics.failpoint_trips`` + a per-point
``failpoint.<name>`` counter and as ``failpoint`` trace events, so SHOW
PROFILE shows which injected faults a slow query paid for;
``information_schema.failpoints`` lists the full catalog with live specs
and hit/trip counts.
"""

from __future__ import annotations

import re
import threading
import time
import zlib
from random import Random
from typing import Optional

from ..utils import metrics
from ..utils.flags import FLAGS, define

define("chaos_enable", False,
       "master switch for failpoint evaluation; arming any failpoint also "
       "enables the sites (the flag alone lets the overhead of evaluated-"
       "but-unarmed sites be measured, bench.py line 5)")
define("chaos_seed", 0,
       "seed of the deterministic failpoint RNG: every armed point's "
       "trigger schedule is a pure function of (chaos_seed, point name, "
       "hit index), so a chaos run replays identically")


class FailpointError(RuntimeError):
    """An injected ``return(msg)`` failure at a failpoint site."""


class FailpointPanic(BaseException):
    """An injected ``panic``: derives from BaseException ON PURPOSE so the
    per-call fault-isolation handlers (``except Exception``) cannot swallow
    it — the in-process daemon genuinely crashes."""


# -- the catalog of wired seams (docs/CHAOS.md documents drop semantics) ----
CATALOG: dict[str, str] = {
    "rpc.send": "RpcClient.call before the request frame is sent "
                "(drop: lose the frame, transport-failure retry path)",
    "rpc.recv": "RpcClient.call between send and receive "
                "(drop: the server executed, the response is lost)",
    "store.handler": "RpcServer dispatch around the handler "
                     "(drop: no reply; panic: crash the daemon)",
    "raft.append": "RaftGroup.propose_cmd / store rpc_propose "
                   "(drop: the append never happens, caller sees failure)",
    "raft.commit": "ReplicatedRegion.apply_committed "
                   "(drop: defer applying committed entries this round)",
    "raft.leader_step": "leader resolution (drop: report leaderless / "
                        "not_leader, forcing election churn + retries)",
    "2pc.prepare": "two-phase commit prepare fan-out "
                   "(drop: a participant's prepare fails)",
    "2pc.decide": "two-phase commit decision propose "
                  "(drop: the decision propose fails, in-doubt window)",
    "binlog.append": "local WAL binlog append, before durability "
                     "(drop: the event is lost; panic: crash mid-append)",
    "binlog.dist_append": "distributed binlog prewrite/commit protocol "
                          "(drop: skip the CDC append, data still lands)",
    "coldfs.put": "cold-tier segment write (drop: the bytes never land)",
    "coldfs.get": "cold-tier segment read (drop: FileNotFoundError)",
    "dispatch.combine": "batched dispatcher combiner tick (delay: stall "
                        "the tick; drop/return: abandon it — every member "
                        "falls back to its own inline execution, exactly-"
                        "once preserved; panic: same fallback — the "
                        "frontend combiner has no daemon to crash)",
    "region.split_fence": "live split, before the fence/routing switch "
                          "(drop: the split aborts cleanly — child "
                          "retires, parent routing untouched)",
    "region.handoff": "live split bulk row handoff into the child region "
                      "(drop: the copy fails, split aborts; parent keeps "
                      "serving its whole range)",
    "migrate.snapshot": "live migration snapshot catch-up of the new "
                        "learner (drop: the learner is never added, "
                        "migration aborts with membership unchanged)",
    "migrate.promote": "live migration learner->voter promotion (drop: "
                       "promotion skipped, the learner is torn back down "
                       "— clean rollback)",
    "meta.balance_tick": "MetaService.tick control loop (drop: the tick "
                         "emits no orders — a stalled balancer; the data "
                         "plane must stay correct without it)",
    "fragment.dispatch": "pushed-fragment per-region dispatch, frontend "
                         "side before the spec leaves (drop: this "
                         "attempt is abandoned; the bounded retry loop "
                         "re-dispatches, then falls back to the pulled "
                         "image path)",
    "fragment.exec": "store-daemon fragment execution, after the spec "
                     "arrived but before any region rows are read "
                     "(drop: the handler fails; the pushed attempt "
                     "fails whole and the frontend falls back to the "
                     "pulled image path, partials stay exactly-once)",
    "tso.allocate": "TSO batched-range grant, after the propose returned "
                    "(drop: the grant response is lost in flight — the "
                    "range is burned and the client re-proposes; "
                    "monotonicity must survive because the source never "
                    "re-issues a granted range)",
    "mvcc.gc": "per-table MVCC history sweep (drop: this sweep is "
               "skipped — a wedged GC; version debt grows but pinned "
               "snapshots stay correct)",
    "snapshot.pin": "snapshot pin registration (drop: the pin is "
                    "refused — an automatic analytical pin degrades to "
                    "an unpinned read; explicit SET SNAPSHOT surfaces "
                    "the refusal to the client)",
    "cdc.fetch": "subscription fetch, before events are read off the "
                 "merged stream (drop: the fetch returns nothing this "
                 "round — delivery deferred, never lost; delay: a slow "
                 "consumer)",
    "cdc.apply": "subscription ack after a delivered batch (drop: the "
                 "ack is skipped — the batch redelivers; consumers "
                 "dedupe by commit_ts, so exactly-once application "
                 "must survive)",
    "view.fold": "matview delta fold over a fetched batch (drop: the "
                 "fold round is abandoned before any state change — "
                 "events stay unacked and staleness grows, state stays "
                 "consistent)",
}

_SPEC_RE = re.compile(
    r"^\s*(?:(?P<prob>\d+(?:\.\d+)?)%)?\s*(?:(?P<limit>\d+)\*)?\s*"
    r"(?P<action>return|delay|drop|panic)\s*(?:\((?P<arg>[^)]*)\))?\s*$")

_VALID_ARGS = {"return": True, "delay": True, "drop": False, "panic": False}


class _Point:
    """One armed failpoint: parsed spec + deterministic RNG + counters."""

    __slots__ = ("name", "spec", "action", "arg", "prob", "limit",
                 "rng", "hits", "trips", "fp_mu")

    def __init__(self, name: str, spec: str):
        m = _SPEC_RE.match(spec)
        if m is None:
            raise ValueError(
                f"failpoint {name!r}: bad spec {spec!r} "
                f"(want [P%][N*]return(msg)|delay(ms)|drop|panic)")
        self.name = name
        self.spec = spec
        self.action = m.group("action")
        self.arg = (m.group("arg") or "").strip()
        if self.arg and not _VALID_ARGS[self.action]:
            raise ValueError(f"failpoint {name!r}: {self.action} takes "
                             f"no argument")
        if self.action == "delay":
            try:
                float(self.arg or "0")
            except ValueError:
                raise ValueError(f"failpoint {name!r}: delay needs a "
                                 f"millisecond number, got {self.arg!r}") \
                    from None
        self.prob = float(m.group("prob")) / 100.0 if m.group("prob") \
            else 1.0
        self.limit = int(m.group("limit")) if m.group("limit") else -1
        self.rng = Random(_point_seed(name))
        self.hits = 0
        self.trips = 0
        self.fp_mu = threading.Lock()


def _point_seed(name: str) -> int:
    # crc32 is stdlib, stable across runs/platforms, and independent per
    # point name — exactly what the (seed, name) -> schedule contract needs
    return (int(FLAGS.chaos_seed) << 32) ^ zlib.crc32(name.encode())


_mu = threading.Lock()
_armed: dict[str, _Point] = {}
# retired points keep their lifetime counters so information_schema rows
# survive a clear() (the spec column goes empty)
_counts: dict[str, tuple[int, int]] = {}

# THE module-level enable check: reading this attribute is the entire cost
# of a disabled failpoint site.  True when chaos_enable is set OR any point
# is armed (arming via SET failpoint.x implies intent to fire).
ENABLED = False


def _refresh(_value=None) -> None:
    global ENABLED
    ENABLED = bool(FLAGS.chaos_enable) or bool(_armed)


def _reseed(_value=None) -> None:
    """chaos_seed changed: every armed point restarts its schedule."""
    with _mu:
        for p in _armed.values():
            with p.fp_mu:
                p.rng = Random(_point_seed(p.name))
    _refresh()


_refresh()
FLAGS.on_change("chaos_enable", _refresh)
FLAGS.on_change("chaos_seed", _reseed)


def register(name: str, doc: str) -> None:
    """Add a point to the catalog (tests/tools wiring ad-hoc seams)."""
    CATALOG.setdefault(name, doc)


def set_failpoint(name: str, spec: str) -> None:
    """Arm ``name`` with ``spec``; re-arming resets its RNG schedule.
    ``off``/empty spec clears.  Unknown names are rejected — a typo must
    not silently never fire."""
    name = name.strip().lower()
    if spec is None or str(spec).strip().lower() in ("", "off"):
        clear(name)
        return
    if name not in CATALOG:
        raise ValueError(
            f"unknown failpoint {name!r} (see information_schema.failpoints)")
    point = _Point(name, str(spec).strip())
    with _mu:
        old = _armed.get(name)
        if old is not None:
            point.hits, point.trips = old.hits, old.trips
        else:
            point.hits, point.trips = _counts.get(name, (0, 0))
        _armed[name] = point
    _refresh()


def clear(name: str) -> None:
    with _mu:
        p = _armed.pop(name.strip().lower(), None)
        if p is not None:
            _counts[p.name] = (p.hits, p.trips)
    _refresh()


def clear_all() -> None:
    with _mu:
        for p in _armed.values():
            _counts[p.name] = (p.hits, p.trips)
        _armed.clear()
    _refresh()


def get_spec(name: str) -> Optional[str]:
    with _mu:
        p = _armed.get(name)
        return p.spec if p is not None else None


def describe() -> list[tuple[str, str, str, int, int]]:
    """(name, doc, spec, hits, trips) for every cataloged point — the
    information_schema.failpoints source."""
    with _mu:
        out = []
        for name in sorted(CATALOG):
            p = _armed.get(name)
            if p is not None:
                out.append((name, CATALOG[name], p.spec, p.hits, p.trips))
            else:
                h, t = _counts.get(name, (0, 0))
                out.append((name, CATALOG[name], "", h, t))
        return out


def hit(name: str, **ctx) -> bool:
    """Evaluate the failpoint.  Returns True when a ``drop`` triggered
    (the site interprets it); sleeps for ``delay``; raises
    :class:`FailpointError` for ``return`` and :class:`FailpointPanic`
    for ``panic``.  Call sites MUST sit behind ``if failpoint.ENABLED:``
    (tpulint FAILPOINTHOT)."""
    p = _armed.get(name)
    if p is None:
        return False
    with p.fp_mu:
        p.hits += 1
        # one draw per hit, unconditionally: the schedule of a point is a
        # pure function of (seed, name, hit index), spec changes included
        r = p.rng.random()
        if p.limit == 0 or r >= p.prob:
            return False
        if p.limit > 0:
            p.limit -= 1
        p.trips += 1
        action, arg = p.action, p.arg
    metrics.failpoint_trips.add(1)
    metrics.REGISTRY.counter(f"failpoint.{name}").add(1)
    from ..obs import trace

    trace.event("failpoint", point=name, action=action, **ctx)
    if action == "delay":
        time.sleep(float(arg or "0") / 1e3)
        return False
    if action == "return":
        raise FailpointError(arg or f"failpoint {name}: injected failure")
    if action == "panic":
        raise FailpointPanic(f"failpoint {name}: injected panic")
    return True                                           # drop
