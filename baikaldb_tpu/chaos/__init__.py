"""Chaos engineering: failpoint-driven fault injection + seeded scenarios.

The reference survives node loss because its braft/brpc seams are exercised
under injected faults; this package is that discipline for the repro's
distributed surface.  ``failpoint`` is the registry (named points wired at
every distributed seam, programmable actions, deterministic seeded
triggering); ``scenarios`` is the seeded kill/partition/latency harness
driven by ``python -m tools.chaos_run``.
"""

from . import failpoint  # noqa: F401

__all__ = ["failpoint"]
