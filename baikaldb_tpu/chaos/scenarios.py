"""Seeded chaos scenarios: kill / partition / latency scripts with
exactly-once and convergence assertions.

Two planes, two guarantees:

- **fleet plane** (``kill_leader``, ``partition``): StoreFleet regions on
  the deterministic in-process LocalBus.  Everything — the fault schedule,
  raft elections, apply order, the final table AND binlog state — is a
  pure function of the seed, so a run replays **bit-identically**
  (``state_digest`` equality across runs is the acceptance check;
  wall-clock TSO timestamps are excluded from the digest by design).
- **daemon plane** (``rpc_chaos``): real in-process meta + store daemons
  over TCP sockets, seeded ``store.handler`` latency and ``rpc.recv``
  response drops from chaos/failpoint.py, plus a mid-run crash of the
  region leader's daemon.  Thread/socket timing is not replayable, but the
  OUTCOME contract is: every client write lands exactly once (RpcClient
  retry + idempotency-token dedupe at the daemons), and the final row
  state digest is seed-deterministic.

Every scenario returns a JSON-able dict: ``fault_schedule`` (the injected
faults, in order), ``state_digest`` (sha256 over the deterministic final
state), assertion results, and observed counters (retries, dedupe hits,
latency percentiles).  ``python -m tools.chaos_run --seed N`` drives them;
bench.py reuses ``rpc_chaos`` for its seeded latency-injection line.
"""

from __future__ import annotations

import hashlib
import json
import random
import time

from . import failpoint


def _digest(obj) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, default=str).encode()).hexdigest()[:16]


def _p(lat_ms: list, q: float) -> float:
    if not lat_ms:
        return 0.0
    s = sorted(lat_ms)
    return round(s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))], 3)


def _fleet_session(seed: int, stores: int = 3):
    from ..exec.session import Database, Session
    from ..meta.service import MetaService
    from ..raft.fleet import StoreFleet

    fleet = StoreFleet(MetaService(peer_count=3),
                       [f"c{i + 1}:1" for i in range(stores)], seed=7 + seed)
    db = Database(fleet=fleet)
    s = Session(db)
    s.execute("CREATE DATABASE chaos")
    s.execute("USE chaos")
    s.execute("CREATE TABLE ck (k BIGINT, v BIGINT, PRIMARY KEY (k))")
    return fleet, db, s


def _check_exactly_once(rows: list[dict], events, writes: int) -> list[str]:
    """Shared assertions: every acked write visible exactly once in the
    table AND in the binlog stream (no lost, no duplicated)."""
    problems = []
    got = {r["k"]: r["v"] for r in rows}
    want = {i: i * i for i in range(writes)}
    if len(rows) != len(got):
        problems.append("duplicate keys in final table state")
    if got != want:
        missing = sorted(set(want) - set(got))
        extra = sorted(set(got) - set(want))
        wrong = sorted(k for k in set(got) & set(want)
                       if got[k] != want[k])
        problems.append(f"table state diverged (missing={missing[:5]} "
                        f"extra={extra[:5]} wrong={wrong[:5]})")
    seen_keys: list[int] = []
    for e in events:
        for r in e.rows or []:
            seen_keys.append(int(r["k"]))
    if sorted(seen_keys) != sorted(want):
        problems.append(
            f"binlog events diverged: {len(seen_keys)} row images for "
            f"{writes} writes (lost="
            f"{sorted(set(want) - set(seen_keys))[:5]}, dup="
            f"{sorted(k for k in set(seen_keys) if seen_keys.count(k) > 1)[:5]})")
    return problems


def kill_leader(seed: int = 1, writes: int = 30) -> dict:
    """Seeded leader kill/revive churn on the fleet plane while SQL
    INSERTs flow.  The write path retries through elections
    (RaftGroup.propose_cmd); 2-of-3 quorum keeps committing.  Asserts
    exactly-once table rows and binlog events; fully deterministic."""
    rng = random.Random((seed << 8) ^ 0x6B696C)
    fleet, db, s = _fleet_session(seed)
    tier = fleet.row_tiers["chaos.ck"]
    g = tier.groups[0]
    schedule: list[list] = []
    killed = None
    for i in range(writes):
        if killed is not None and rng.random() < 0.5:
            g.bus.revive(killed)
            schedule.append([i, "revive", killed])
            killed = None
        if killed is None and rng.random() < 0.35:
            try:
                victim = g.leader()
            except RuntimeError:
                victim = None
            if victim is not None:
                g.bus.kill(victim)
                schedule.append([i, "kill_leader", victim])
                killed = victim
        s.execute(f"INSERT INTO ck VALUES ({i}, {i * i})")
    if killed is not None:
        g.bus.revive(killed)
        schedule.append([writes, "revive", killed])
    rows = s.query("SELECT k, v FROM ck ORDER BY k")
    events = [e for e in db.binlog.read(0, 1 << 20)
              if e.table == "ck" and e.event_type == "insert"]
    problems = _check_exactly_once(rows, events, writes)
    state = {"rows": rows,
             # commit_ts is wall-clock (TSO): excluded from the digest
             "binlog": [[e.event_type, e.rows] for e in events]}
    return {"writes": writes, "fault_schedule": schedule,
            "faults": len(schedule),
            "state_digest": _digest({"schedule": schedule, "state": state}),
            "problems": problems}


def partition(seed: int = 2, writes: int = 24) -> dict:
    """Seeded network partitions on the fleet plane: the current leader is
    repeatedly isolated from the majority, which elects around it; heals
    re-join it.  Asserts exactly-once plus full replica convergence after
    the final heal (every live replica holds identical rows)."""
    rng = random.Random((seed << 8) ^ 0x706172)
    fleet, db, s = _fleet_session(seed)
    tier = fleet.row_tiers["chaos.ck"]
    g = tier.groups[0]
    schedule: list[list] = []
    partitioned = False
    for i in range(writes):
        if partitioned and rng.random() < 0.5:
            g.bus.heal()
            schedule.append([i, "heal"])
            partitioned = False
        if not partitioned and rng.random() < 0.3:
            try:
                ldr = g.leader()
            except RuntimeError:
                ldr = None
            if ldr is not None:
                rest = [n for n in g.bus.nodes if n != ldr]
                g.bus.partition([ldr], rest)
                schedule.append([i, "partition_leader", ldr])
                partitioned = True
        s.execute(f"INSERT INTO ck VALUES ({i}, {i * i})")
    if partitioned:
        g.bus.heal()
        schedule.append([writes, "heal"])
    g.bus.advance(30)               # let the isolated replica catch up
    rows = s.query("SELECT k, v FROM ck ORDER BY k")
    events = [e for e in db.binlog.read(0, 1 << 20)
              if e.table == "ck" and e.event_type == "insert"]
    problems = _check_exactly_once(rows, events, writes)
    replica_states = []
    for nid in sorted(g.bus.nodes):
        node = g.bus.nodes[nid]
        node.apply_committed()
        replica_states.append(
            sorted((r["k"], r["v"]) for r in node.rows_in_range()))
    if any(st != replica_states[0] for st in replica_states[1:]):
        problems.append("replicas did not converge after heal")
    state = {"rows": rows,
             "binlog": [[e.event_type, e.rows] for e in events],
             "replicas": replica_states}
    return {"writes": writes, "fault_schedule": schedule,
            "faults": len(schedule),
            "state_digest": _digest({"schedule": schedule, "state": state}),
            "problems": problems}


def rpc_chaos(seed: int = 3, writes: int = 16, delay_ms: float = 10.0,
              delay_pct: int = 30, drop_pct: int = 15,
              crash_leader: bool = True) -> dict:
    """Daemon plane: 1 in-process meta + 3 in-process store daemons over
    real TCP, with seeded handler latency (``store.handler`` delay) and
    lost responses (``rpc.recv`` drop — the server executed, the reply
    died), plus a mid-run crash of the region leader's daemon.  Client
    writes ride RpcClient's backoff+jitter retries; lost-response resends
    dedupe at the daemons by idempotency token.  Asserts every write
    landed exactly once; reports retry/dedupe/timeout counters and write
    latency percentiles."""
    from ..server.meta_server import MetaServer
    from ..server.store_server import StoreServer
    from ..storage.remote_tier import ClusterClient, RemoteRowTier
    from ..storage.rowstore import KeyCodec
    from ..types import Field, LType, Schema
    from ..utils import metrics
    from ..utils.flags import FLAGS, set_flag

    prev_seed = int(FLAGS.chaos_seed)
    set_flag("chaos_seed", int(seed))
    meta = MetaServer("127.0.0.1:0")
    meta.start()
    stores: list[StoreServer] = []
    schedule: list[list] = []
    lat_ms: list[float] = []
    r0 = metrics.rpc_retries.value
    d0 = metrics.rpc_dedup_hits.value
    t0 = metrics.rpc_timeouts.value
    try:
        meta_addr = f"127.0.0.1:{meta.rpc.port}"
        for sid in (1, 2, 3):
            st = StoreServer(sid, "127.0.0.1:0", meta_addr,
                             tick_interval=0.02, seed=seed * 11 + sid)
            st.address = f"127.0.0.1:{st.rpc.port}"
            st.start()
            stores.append(st)
        schema = Schema((Field("k", LType.INT64, False),
                         Field("v", LType.INT64, True)))
        cluster = ClusterClient(meta_addr)
        tier = RemoteRowTier.get_or_create(
            cluster, f"chaos.rpc_s{seed}", schema, ["k"])
        kc = KeyCodec(schema, ["k"])
        crash_at = writes // 3
        try:
            failpoint.set_failpoint("store.handler",
                                    f"{delay_pct}%delay({delay_ms})")
            failpoint.set_failpoint("rpc.recv", f"{drop_pct}%drop")
            for i in range(writes):
                if crash_leader and i == crash_at:
                    victim_addr = tier.regions[0].leader_addr
                    for st in stores:
                        if st.address == victim_addr:
                            st.crash()  # SIGKILL analog: 2/3 quorum remains
                            schedule.append([i, "crash_store", st.store_id])
                row = {"k": i, "v": i * i}
                w0 = time.perf_counter()
                tier.write_ops([(0, kc.encode_one(row),
                                 tier.row_codec.encode(row))])
                lat_ms.append((time.perf_counter() - w0) * 1e3)
        finally:
            failpoint.clear("store.handler")
            failpoint.clear("rpc.recv")
            set_flag("chaos_seed", prev_seed)
        problems = []
        got = {r["k"]: r["v"] for r in tier.scan_rows()
               if not r.get("__del")}
        want = {i: i * i for i in range(writes)}
        if got != want:
            problems.append(
                f"writes lost or corrupted (missing="
                f"{sorted(set(want) - set(got))[:5]})")
    finally:
        # a failed write mid-run must NOT leak daemon tick threads and
        # ports into the process (bench / repeated runs share it)
        for st in stores:
            st.stop()
        meta.stop()
    return {"writes": writes, "fault_schedule": schedule,
            "faults": len(schedule),
            # rows only: WHICH store led at crash time is thread-timing,
            # so the schedule is informational here — the seed-stable
            # contract on the daemon plane is the final row state
            "state_digest": _digest({"rows": sorted(got.items())}),
            "problems": problems,
            "rpc_retries": metrics.rpc_retries.value - r0,
            "rpc_dedup_hits": metrics.rpc_dedup_hits.value - d0,
            "rpc_timeouts": metrics.rpc_timeouts.value - t0,
            "p50_ms": _p(lat_ms, 0.50), "p99_ms": _p(lat_ms, 0.99),
            "max_ms": round(max(lat_ms), 3) if lat_ms else 0.0}


def dispatch_overload(seed: int = 4, clients: int = 12, queries: int = 8,
                      writes: int | None = None, delay_ms: float = 8.0,
                      delay_pct: int = 60, queue_max: int = 4) -> dict:
    """Overload the cross-query batched dispatcher (exec/dispatch.py) while
    the combiner is stalled by a seeded ``dispatch.combine`` delay: many
    client threads hammer one statement group through the qos gate with the
    per-group queue bound cranked down.

    Outcome contract (thread timing owns the interleaving, so this is the
    rpc_chaos-style contract, not bit-identical replay): every query either
    returns ITS OWN correct row exactly once or raises a typed
    ``RejectedError`` (qos bucket / ``DispatchOverload`` queue bound) —
    never a wrong row, never a hang, never an untyped failure; the observed
    queue depth stays within the configured bound; combiner stalls degrade
    to inline fallback, not loss."""
    import threading

    from ..exec.session import Database, Session
    from ..utils import metrics
    from ..utils.flags import FLAGS, set_flag
    from ..utils.qos import QosManager, RejectedError

    if writes is not None:              # chaos_run --writes compatibility
        queries = max(1, int(writes) // clients)
    prev_seed = int(FLAGS.chaos_seed)
    prev_on = bool(FLAGS.batch_dispatch)
    prev_qmax = int(FLAGS.batch_dispatch_queue_max)
    prev_tick = float(FLAGS.batch_dispatch_tick_ms)
    set_flag("chaos_seed", int(seed))
    set_flag("batch_dispatch", True)    # the combiner IS the scenario
    set_flag("batch_dispatch_queue_max", int(queue_max))
    set_flag("batch_dispatch_tick_ms", 2.0)
    t0 = metrics.failpoint_trips.value
    f0 = metrics.dispatch_fallbacks.value
    g0 = metrics.batched_groups.value
    db = Database()
    boot = Session(db)
    boot.execute("CREATE TABLE dq (id BIGINT, v BIGINT)")
    boot.execute("INSERT INTO dq VALUES " + ", ".join(
        f"({i}, {i * 3})" for i in range(clients * queries)))
    boot.query("SELECT v FROM dq WHERE id = 0")        # settle the plan
    # generous user/sign rates, tight per-table bucket: the overload sheds
    # AT the hot table, which is the dimension this scenario drives
    db.qos = QosManager(table_rate=30.0, table_burst=float(
        clients * queries // 2))
    ok: list[tuple[int, int]] = []
    rejected: list[str] = []
    problems: list[str] = []
    mu = threading.Lock()
    depth_seen = [0]
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            depth_seen[0] = max(depth_seen[0], db.dispatcher.queue_depth())
            time.sleep(0.0005)

    def worker(tid: int):
        s = Session(db)
        for q in range(queries):
            i = tid * queries + q
            try:
                r = s.query(f"SELECT v FROM dq WHERE id = {i}")
            except RejectedError as e:
                with mu:
                    rejected.append(type(e).__name__)
                continue
            except Exception as e:      # noqa: BLE001 — the report IS the point
                with mu:
                    problems.append(
                        f"untyped failure for id {i}: "
                        f"{type(e).__name__}: {e}")
                continue
            if r != [{"v": i * 3}]:
                with mu:
                    problems.append(f"wrong result for id {i}: {r!r}")
            else:
                with mu:
                    ok.append((tid, i))
    try:
        failpoint.set_failpoint("dispatch.combine",
                                f"{delay_pct}%delay({delay_ms})")
        smp = threading.Thread(target=sampler, daemon=True)
        smp.start()
        ts = [threading.Thread(target=worker, args=(t,))
              for t in range(clients)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        stop.set()
        smp.join(timeout=1)
    finally:
        failpoint.clear("dispatch.combine")
        set_flag("chaos_seed", prev_seed)
        set_flag("batch_dispatch", prev_on)
        set_flag("batch_dispatch_queue_max", prev_qmax)
        set_flag("batch_dispatch_tick_ms", prev_tick)
    total = clients * queries
    if len(ok) + len(rejected) != total:
        problems.append(f"accounting hole: {len(ok)} ok + {len(rejected)} "
                        f"rejected != {total} issued")
    if metrics.batched_groups.value == g0:
        problems.append("combiner never engaged — the scenario exercised "
                        "nothing")
    if depth_seen[0] > queue_max:
        problems.append(f"queue depth {depth_seen[0]} exceeded the "
                        f"{queue_max} bound")
    return {"clients": clients, "queries": total,
            "succeeded": len(ok), "rejected": len(rejected),
            "faults": metrics.failpoint_trips.value - t0,
            "fault_schedule": [],     # thread timing owns hit order; the
            #                           per-hit trigger schedule is still a
            #                           pure fn of (seed, hit index)
            "combiner_fallbacks": metrics.dispatch_fallbacks.value - f0,
            "batched_groups": metrics.batched_groups.value - g0,
            "max_queue_depth": depth_seen[0],
            "state_digest": _digest(
                {"rows": [[i, i * 3] for i in range(total)]}),
            "problems": problems}


def _region_invariants(fleet, tier) -> list[str]:
    """Never-half-routed checks shared by the elastic-region scenarios:
    the tier's ranges tile the keyspace with no gap or overlap, every tier
    region is SERVING in meta, and the meta registry / fleet group table /
    tier routing lists agree exactly on which regions exist."""
    problems = []
    if tier._starts[0] != b"" or tier._ends[-1] != b"":
        problems.append("tier range endpoints no longer span the keyspace")
    for i in range(len(tier.metas) - 1):
        if tier._ends[i] != tier._starts[i + 1]:
            problems.append(
                f"range gap/overlap between regions "
                f"{tier.metas[i].region_id} and {tier.metas[i + 1].region_id}")
    tier_rids = {m.region_id for m in tier.metas}
    meta_rids = {rid for rid, r in fleet.meta.regions.items()
                 if r.table_id == tier.table_id}
    if tier_rids != meta_rids:
        problems.append(f"meta/tier region sets diverged "
                        f"(tier={sorted(tier_rids)} meta={sorted(meta_rids)})")
    for m in tier.metas:
        rm = fleet.meta.regions.get(m.region_id)
        if rm is not None and rm.state != "SERVING":
            problems.append(f"region {m.region_id} stuck {rm.state}")
        if m.region_id not in fleet.groups:
            problems.append(f"region {m.region_id} routed but its raft "
                            f"group left the fleet")
    for rid in fleet.groups:
        if fleet.meta.regions.get(rid) is None:
            problems.append(f"raft group {rid} leaked (no meta entry)")
    return problems


def _replica_convergence(tier) -> tuple[list, list[str]]:
    """Per-region replica states after a settle; diverged replicas are
    problems.  Returns (states for the digest, problems)."""
    problems = []
    states = []
    for m, g in zip(tier.metas, tier.groups):
        g.bus.advance(30)
        per = []
        for nid in sorted(g.bus.nodes):
            node = g.bus.nodes[nid]
            node.apply_committed()
            per.append(sorted((r["k"], r["v"])
                              for r in node.rows_in_range()))
        if any(st != per[0] for st in per[1:]):
            problems.append(f"replicas of region {m.region_id} did not "
                            f"converge after heal")
        states.append(per[0])
    return states, problems


def split_chaos(seed: int = 5, writes: int = 40) -> dict:
    """Partition the fleet mid-split (the tentpole contract): a live
    fenced split runs while SQL INSERTs keep flowing, and the seeded fault
    is one of — partition the leader's store away from the fleet at the
    bulk-copy or catch-up phase (the split must COMPLETE through elections
    on the majority side), or drop the ``region.handoff`` /
    ``region.split_fence`` seam (the split must ABORT cleanly and a retry
    must complete).  Ends with exactly-once rows, key-ordered binlog,
    converged replicas, and a fully-routed region table — then a lowered
    ``region_split_rows`` proves the meta-tick -> split-order -> online
    split path end to end.  Fleet plane: bit-identical replay."""
    from ..storage.replicated import SplitError
    from ..utils.flags import FLAGS, set_flag

    rng = random.Random((seed << 8) ^ 0x73706C)
    fleet, db, s = _fleet_session(seed)
    tier = fleet.row_tiers["chaos.ck"]
    schedule: list[list] = []
    problems: list[str] = []
    next_key = 0

    def put(n: int):
        nonlocal next_key
        for _ in range(min(n, writes - next_key)):
            s.execute(f"INSERT INTO ck VALUES ({next_key}, "
                      f"{next_key * next_key})")
            next_key += 1

    put(writes // 2)
    parent = tier.metas[0].region_id
    fault = rng.choice(["partition_begin", "partition_copied",
                        "handoff_drop", "fence_drop"])
    mid_writes = 3 + rng.randrange(4)
    schedule.append(["fault_plan", fault, mid_writes])

    def hook(phase: str):
        schedule.append(["phase", phase])
        put(mid_writes)             # writes continue during the live split
        if fault == f"partition_{phase}":
            ldr = fleet.meta.regions[parent].leader
            fleet.partition_store(ldr)
            schedule.append(["partition", ldr, phase])

    try:
        if fault == "handoff_drop":
            failpoint.set_failpoint("region.handoff", "1*drop")
        elif fault == "fence_drop":
            failpoint.set_failpoint("region.split_fence", "1*drop")
        try:
            child = tier.split_region_online(parent, chaos_hook=hook)
            schedule.append(["split_ok", parent, child.region_id])
        except SplitError:
            schedule.append(["split_abort", parent])
            fleet.heal_all()
            try:                    # aborted cleanly -> a retry completes
                child = tier.split_region_online(parent)
                schedule.append(["split_retry_ok", parent, child.region_id])
            except SplitError as e:
                problems.append(f"split retry failed: {e}")
    finally:
        failpoint.clear("region.handoff")
        failpoint.clear("region.split_fence")
        fleet.heal_all()
    put(writes - next_key)          # lands across BOTH sides of the split
    # tick-driven path: with the threshold lowered, heartbeats feed the
    # load gauges and meta's next tick emits split orders the fleet
    # executes as further online splits
    prev_rows = int(FLAGS.region_split_rows)
    set_flag("region_split_rows", max(4, writes // 4))
    try:
        fleet.heartbeat_all()
        fleet.heartbeat_all()
        orders = fleet.meta.tick()
        applied = fleet.apply_orders(orders)
        schedule.append(["tick", sorted([o.kind, o.region_id]
                                        for o in orders), applied])
        if not any(o.kind == "split" for o in orders):
            problems.append("meta tick emitted no split order despite "
                            "rows over threshold")
    finally:
        set_flag("region_split_rows", prev_rows)
    rows = s.query("SELECT k, v FROM ck ORDER BY k")
    events = [e for e in db.binlog.read(0, 1 << 20)
              if e.table == "ck" and e.event_type == "insert"]
    problems += _check_exactly_once(rows, events, writes)
    seen = [int(r["k"]) for e in events for r in (e.rows or [])]
    if seen != sorted(seen):
        problems.append("binlog order diverged from write order")
    if len(tier.metas) < 2:
        problems.append("no split happened")
    problems += _region_invariants(fleet, tier)
    replicas, conv = _replica_convergence(tier)
    problems += conv
    state = {"rows": rows,
             "binlog": [[e.event_type, e.rows] for e in events],
             "regions": [[m.region_id, tier._starts[i].hex(),
                          tier._ends[i].hex()]
                         for i, m in enumerate(tier.metas)],
             "replicas": replicas}
    return {"writes": writes, "fault_schedule": schedule,
            "faults": len(schedule),
            "regions": len(tier.metas),
            "state_digest": _digest({"schedule": schedule, "state": state}),
            "problems": problems}


def migrate_chaos(seed: int = 6, writes: int = 36) -> dict:
    """Kill the leader mid-migration (the tentpole contract): a learner-
    first live migration moves a replica off the region's current leader
    store to the fleet's idle fourth store while SQL INSERTs keep flowing.
    The seeded fault is one of — kill the leader's node at the start or
    at learner catch-up (the migration must COMPLETE through elections),
    or drop the ``migrate.snapshot`` / ``migrate.promote`` seam (clean
    rollback, then a retry completes).  Ends with exactly-once rows,
    key-ordered binlog, converged replicas, and meta's membership exactly
    equal to the raft group's — completed or rolled back, never half-
    moved.  Fleet plane: bit-identical replay."""
    from ..raft.fleet import MigrateError

    rng = random.Random((seed << 8) ^ 0x6D6967)
    fleet, db, s = _fleet_session(seed, stores=4)
    tier = fleet.row_tiers["chaos.ck"]
    rid = tier.metas[0].region_id
    g = tier.groups[0]
    schedule: list[list] = []
    problems: list[str] = []
    next_key = 0

    def put(n: int):
        nonlocal next_key
        for _ in range(min(n, writes - next_key)):
            s.execute(f"INSERT INTO ck VALUES ({next_key}, "
                      f"{next_key * next_key})")
            next_key += 1

    put(writes // 2)
    rm = fleet.meta.regions[rid]
    source = rm.leader              # move the LEADER's replica: the move
    #                                 must transfer leadership away first
    target = next(a for a in sorted(fleet.addresses) if a not in rm.peers)
    fault = rng.choice(["kill_leader_start", "kill_leader_learner",
                        "snapshot_drop", "promote_drop"])
    mid_writes = 3 + rng.randrange(4)
    schedule.append(["fault_plan", fault, source, target, mid_writes])
    killed: list[int] = []

    def hook(phase: str):
        schedule.append(["phase", phase])
        put(mid_writes)         # writes continue during the live migration
        if fault == f"kill_leader_{phase}":
            try:
                victim = g.leader()
            except RuntimeError:
                return
            g.bus.kill(victim)
            killed.append(victim)
            schedule.append(["kill_leader", victim, phase])

    try:
        if fault == "snapshot_drop":
            failpoint.set_failpoint("migrate.snapshot", "1*drop")
        elif fault == "promote_drop":
            failpoint.set_failpoint("migrate.promote", "1*drop")
        try:
            fleet.migrate_replica(rid, source, target, chaos_hook=hook)
            schedule.append(["migrate_ok", source, target])
        except MigrateError:
            schedule.append(["migrate_abort", source, target])
            for nid in killed:
                g.bus.revive(nid)
            killed.clear()
            try:                # rolled back cleanly -> a retry completes
                fleet.migrate_replica(rid, source, target)
                schedule.append(["migrate_retry_ok", source, target])
            except MigrateError as e:
                problems.append(f"migration retry failed: {e}")
    finally:
        failpoint.clear("migrate.snapshot")
        failpoint.clear("migrate.promote")
        for nid in killed:
            g.bus.revive(nid)
    put(writes - next_key)
    rows = s.query("SELECT k, v FROM ck ORDER BY k")
    events = [e for e in db.binlog.read(0, 1 << 20)
              if e.table == "ck" and e.event_type == "insert"]
    problems += _check_exactly_once(rows, events, writes)
    seen = [int(r["k"]) for e in events for r in (e.rows or [])]
    if seen != sorted(seen):
        problems.append("binlog order diverged from write order")
    # membership: completed-or-rolled-back, never half-moved — meta's
    # registry must equal the raft group's real voter set
    rm = fleet.meta.regions[rid]
    raft_peers = sorted(fleet._addr[n] for n in g.peers())
    if sorted(rm.peers) != raft_peers:
        problems.append(f"meta peers {sorted(rm.peers)} != raft voters "
                        f"{raft_peers}")
    if g.bus.nodes[g.leader()].core.learners():
        problems.append("migration left a dangling learner behind")
    if source in raft_peers:
        problems.append(f"replica never left {source} (migration neither "
                        f"completed nor cleanly retried)")
    if target not in raft_peers:
        problems.append(f"replica never reached {target}")
    if rm.state != "SERVING":
        problems.append(f"region stuck {rm.state}")
    problems += _region_invariants(fleet, tier)
    replicas, conv = _replica_convergence(tier)
    problems += conv
    state = {"rows": rows,
             "binlog": [[e.event_type, e.rows] for e in events],
             "membership": raft_peers,
             "replicas": replicas}
    return {"writes": writes, "fault_schedule": schedule,
            "faults": len(schedule),
            "membership": raft_peers,
            "state_digest": _digest({"schedule": schedule, "state": state}),
            "problems": problems}


def stream_chaos(seed: int = 7, rows: int = 384, chunk_rows: int = 64,
                 writes: int | None = None, drop_pct: int = 25,
                 delay_ms: float = 1.0) -> dict:
    """Out-of-core streaming scan under cold-tier faults: a streamed
    scan->filter->GROUP BY folds the table's Parquet chunk segments while
    the ``coldfs.get`` seam is armed — first with a hard ``2*drop`` (the
    first two segment reads fail, proving the bounded-backoff retry
    path), then with a seeded ``P%drop`` + a second pass of pure latency
    (``delay``).  The retry policy is PR 5's: doubling backoff with full
    jitter, ``stream_retry_max`` attempts, counted in ``stream_retries``.

    Invariants: every armed run returns BIT-IDENTICAL rows to the
    unfaulted resident path, and every chunk folds exactly once per scan
    (``stream_chunks`` moves by exactly the chunk count — a retried read
    re-stages bytes, never re-folds a chunk).  The fold is single-scan
    deterministic data, so the digest (rows + fault plan) pins per seed
    across runs."""
    import shutil
    import tempfile

    from ..exec.session import Database, Session
    from ..utils import metrics
    from ..utils.flags import FLAGS, set_flag

    if writes is not None:              # chaos_run --writes compatibility
        rows = max(chunk_rows, int(writes))
    prev = {k: getattr(FLAGS, k) for k in
            ("chaos_seed", "streaming_scan", "streaming_min_rows",
             "streaming_chunk_rows", "stream_backoff_ms")}
    set_flag("chaos_seed", int(seed))
    set_flag("streaming_scan", True)
    set_flag("streaming_min_rows", 1)
    set_flag("streaming_chunk_rows", int(chunk_rows))
    set_flag("stream_backoff_ms", 0.5)      # keep retry sleeps cheap
    cold = tempfile.mkdtemp(prefix="stream_chaos_")
    schedule: list[list] = []
    problems: list[str] = []
    sql = ("SELECT g, COUNT(*) n, SUM(v) s, AVG(v) a FROM sc "
           "WHERE id >= 0 GROUP BY g ORDER BY g")
    try:
        s = Session(Database(cold_dir=cold))
        s.execute("CREATE TABLE sc (id BIGINT, g BIGINT, v DOUBLE, "
                  "PRIMARY KEY (id))")
        for lo in range(0, rows, 128):
            vals = ", ".join(f"({i}, {i % 5}, {float(i % 97)})"
                             for i in range(lo, min(lo + 128, rows)))
            s.execute(f"INSERT INTO sc VALUES {vals}")
        set_flag("streaming_scan", False)
        want = s.query(sql)             # unfaulted resident ground truth
        set_flag("streaming_scan", True)
        n_chunks = -(-rows // chunk_rows)

        def streamed_run(tag: str, spec: str | None):
            c0 = metrics.stream_chunks.value
            r0 = metrics.stream_retries.value
            if spec is not None:
                failpoint.set_failpoint("coldfs.get", spec)
            try:
                got = s.query(sql)
            finally:
                if spec is not None:
                    failpoint.clear("coldfs.get")
            folded = metrics.stream_chunks.value - c0
            retried = metrics.stream_retries.value - r0
            schedule.append([tag, spec, folded, retried])
            if got != want:
                problems.append(f"{tag}: streamed rows diverged from the "
                                f"resident path")
            if folded != n_chunks:
                problems.append(f"{tag}: {folded} chunk folds for "
                                f"{n_chunks} chunks — not exactly-once")
            return retried

        # pass 1 (unfaulted): builds + persists the chunk segments, and
        # pins the fault-free fold
        streamed_run("clean", None)
        # pass 2: hard drop — the first two segment reads FAIL, retries
        # must recover mid-streamed-scan
        retried = streamed_run("hard_drop", "2*drop")
        if retried < 2:
            problems.append(f"hard_drop: only {retried} retries for a "
                            f"2*drop (the failpoint never bit)")
        # pass 3: seeded probabilistic drops — the schedule is a pure
        # function of (chaos_seed, hit index)
        streamed_run("seeded_drop", f"{drop_pct}%drop")
        # pass 4: pure latency — staging slows, results must not change
        streamed_run("latency", f"delay({delay_ms})")
    finally:
        failpoint.clear("coldfs.get")
        for k, v in prev.items():
            set_flag(k, v)
        shutil.rmtree(cold, ignore_errors=True)
    return {"rows": rows, "chunks": n_chunks,
            "fault_schedule": schedule, "faults": len(schedule) - 1,
            "state_digest": _digest({"schedule": schedule,
                                     "rows": [sorted(r.items())
                                              for r in want]}),
            "problems": problems}


def fragment_chaos(seed: int = 8, rows: int = 240,
                   writes: int | None = None, drop_pct: int = 35,
                   queries: int = 5) -> dict:
    """Pushed-down fragment dispatch (exec/fragments.py) under daemon
    faults and a forced mid-query split, on the daemon plane (in-process
    meta + 3 store daemons over real TCP).

    Passes, each compared against the frontend-pulled ground truth
    (``pushdown_reads`` off — the bit-identity the off-switch guarantees):

    1. ``clean`` — pushed dispatch, no faults.
    2. ``exec_drop`` × ``queries`` — ``fragment.exec`` armed with a seeded
       ``P%drop``: a tripped daemon dies before reading any region row,
       the pushed attempt fails, and the query falls back to the pulled
       image path (``fragment_fallbacks``).  Results never change.
    3. ``split_retarget`` — ANOTHER frontend live-splits the region, so
       this frontend's routing is stale when its dispatch is in flight:
       the range-validated read raises StaleRoutingError, the dispatcher
       throws the whole attempt away, refreshes routing and re-slices
       over both children (``fragment_retargets``).
    4. ``dispatch_drop`` — ``fragment.dispatch`` armed ``1*drop`` (the
       attempt is abandoned frontend-side, the bounded retry loop lands
       the next one), then ``drop`` (every attempt dies → image fallback).

    The exactly-once contract is audited on every successful dispatch via
    the per-daemon ``scanned`` counts riding the payloads: their sum must
    equal the table's row count — a retarget or retry that double-folded
    a region (or dropped one) cannot sum to it.  Thread/socket timing is
    not replayable, but the outcome schedule (which passes fell back,
    how many partials) is a pure function of the seed, so the digest
    pins per seed."""
    from ..exec.fragments import recent_dispatches
    from ..exec.session import Database, Session
    from ..server.meta_server import MetaServer
    from ..server.store_server import StoreServer
    from ..utils import metrics
    from ..utils.flags import FLAGS, set_flag

    if writes is not None:              # chaos_run --writes compatibility
        rows = max(40, int(writes))
    prev = {k: getattr(FLAGS, k) for k in
            ("chaos_seed", "pushdown_reads", "fragment_pushdown",
             "fragment_retry_max")}
    set_flag("chaos_seed", int(seed))
    set_flag("fragment_pushdown", True)
    meta = MetaServer("127.0.0.1:0")
    meta.start()
    stores: list = []
    schedule: list[list] = []
    problems: list[str] = []
    sql = ("SELECT g, COUNT(*) n, SUM(v) s, MIN(v) lo, MAX(v) hi "
           "FROM fc WHERE v >= 0 GROUP BY g ORDER BY g")
    ddl = ("CREATE TABLE fc (id BIGINT NOT NULL, g BIGINT, v BIGINT, "
           "PRIMARY KEY (id))")
    try:
        meta_addr = f"127.0.0.1:{meta.rpc.port}"
        for sid in (1, 2, 3):
            st = StoreServer(sid, "127.0.0.1:0", meta_addr,
                             tick_interval=0.02, seed=seed * 13 + sid)
            st.address = f"127.0.0.1:{st.rpc.port}"
            st.start()
            stores.append(st)
        writer = Session(Database(cluster=meta_addr))
        writer.db.telemetry.stop()
        writer.execute(ddl)
        for lo in range(0, rows, 120):
            vals = ", ".join(f"({i}, {i % 7}, {(i * 37) % 101})"
                             for i in range(lo, min(lo + 120, rows)))
            writer.execute(f"INSERT INTO fc VALUES {vals}")
        set_flag("pushdown_reads", "off")
        want = writer.query(sql)        # frontend-pulled ground truth
        set_flag("pushdown_reads", "always")
        reader = Session(Database(cluster=meta_addr))
        reader.db.telemetry.stop()
        reader.execute(ddl)

        def pushed_run(tag: str):
            f0 = metrics.fragment_fallbacks.value
            got = reader.query(sql)
            fell = metrics.fragment_fallbacks.value - f0
            ring = recent_dispatches()
            last = ring[-1] if ring else {}
            schedule.append([tag, last.get("status", "none"),
                             int(last.get("dispatched", 0)),
                             int(last.get("retargeted", 0)), int(fell)])
            if got != want:
                problems.append(f"{tag}: pushed rows diverged from the "
                                f"pulled ground truth")
            if last.get("status") == "ok" \
                    and int(last.get("scanned", 0)) != rows:
                problems.append(
                    f"{tag}: {last.get('scanned')} rows folded for {rows} "
                    f"live rows — partials not exactly-once")
            return last, fell

        # pass 1: clean pushed dispatch
        last, fell = pushed_run("clean")
        if fell or last.get("status") != "ok":
            problems.append("clean: pushed dispatch fell back unfaulted")
        # pass 2: seeded daemon-side execution drops -> image fallback
        failpoint.set_failpoint("fragment.exec", f"{drop_pct}%drop")
        try:
            for q in range(int(queries)):
                pushed_run(f"exec_drop{q}")
        finally:
            failpoint.clear("fragment.exec")
        # pass 3: live split by ANOTHER frontend mid-flight -> re-target
        writer.db.stores["default.fc"].replicated.split_region(0)
        last, fell = pushed_run("split_retarget")
        if not last.get("retargeted"):
            problems.append("split_retarget: dispatch never re-targeted "
                            "after the live split")
        if fell:
            problems.append("split_retarget: re-target fell back instead "
                            "of re-slicing")
        # pass 4: frontend-side dispatch drops — one abandoned attempt
        # (retry lands), then all attempts (image fallback)
        t0 = metrics.failpoint_trips.value
        failpoint.set_failpoint("fragment.dispatch", "1*drop")
        try:
            last, fell = pushed_run("dispatch_retry")
        finally:
            failpoint.clear("fragment.dispatch")
        if metrics.failpoint_trips.value - t0 < 1:
            problems.append("dispatch_retry: the failpoint never bit")
        if fell or last.get("status") != "ok":
            problems.append("dispatch_retry: bounded retry did not land "
                            "the second attempt")
        failpoint.set_failpoint("fragment.dispatch", "drop")
        try:
            last, fell = pushed_run("dispatch_exhaust")
        finally:
            failpoint.clear("fragment.dispatch")
        if not fell:
            problems.append("dispatch_exhaust: exhausted dispatch did "
                            "not fall back to the pulled path")
    finally:
        failpoint.clear("fragment.exec")
        failpoint.clear("fragment.dispatch")
        for k, v in prev.items():
            set_flag(k, v)
        for st in stores:
            st.stop()
        meta.stop()
    return {"rows": rows, "fault_schedule": schedule,
            "faults": len(schedule) - 2,
            "state_digest": _digest({"schedule": schedule,
                                     "rows": [sorted(r.items())
                                              for r in want]}),
            "problems": problems,
            "retargets": sum(s[3] for s in schedule),
            "fallbacks": sum(s[4] for s in schedule)}


def snapshot_chaos(seed: int = 7, writes: int = 48) -> dict:
    """Hammer writes + a forced live split during a pinned-snapshot
    aggregate (the tentpole contract): a session pins an explicit MVCC
    snapshot, records a GROUP BY aggregate, and that aggregate must stay
    BIT-IDENTICAL while seeded insert/update/delete traffic rewrites the
    table, a live region split runs mid-query (checked at every split
    phase via the chaos hook), a ``tso.allocate`` grant is lost (burned
    range — monotonicity must survive the re-propose), one GC sweep is
    failpoint-wedged and the next must still respect the oldest pin, and
    a fresh session re-pinning the RECORDED ts reproduces the aggregate
    (quiesced replay).  Also: an explicit pin refusal (``snapshot.pin``)
    must surface to the client, the ``mvcc=0`` off-switch must read
    bit-identically to the unpinned read, and TSO timestamps must stay
    strictly monotonic across a meta raft leader kill.  Fleet plane:
    bit-identical replay (wall-clock timestamps excluded from the
    digest by design)."""
    from ..exec.session import Session
    from ..meta.replicated_meta import ReplicatedMeta
    from ..storage.mvcc import TsoClient
    from ..utils.flags import FLAGS, set_flag

    rng = random.Random((seed << 8) ^ 0x736E70)
    fleet, db, s = _fleet_session(seed)
    s.execute("CREATE TABLE sv (k BIGINT, g BIGINT, v BIGINT, "
              "PRIMARY KEY (k))")
    tier = fleet.row_tiers["chaos.sv"]
    schedule: list[list] = []
    problems: list[str] = []
    next_key = 0

    def put(n: int):
        nonlocal next_key
        for _ in range(n):
            k = next_key
            s.execute(f"INSERT INTO sv VALUES ({k}, {k % 4}, {k * k})")
            next_key += 1

    def hammer(n: int):
        nonlocal next_key
        for _ in range(n):
            r = rng.random()
            if r < 0.5 or next_key < 4:
                put(1)
            elif r < 0.8:
                k = rng.randrange(next_key)
                s.execute(f"UPDATE sv SET v = v + 7 WHERE k = {k}")
                schedule.append(["update", k])
            else:
                k = rng.randrange(next_key)
                s.execute(f"DELETE FROM sv WHERE k = {k}")
                schedule.append(["delete", k])

    put(writes // 2)
    AGG = "SELECT g, COUNT(*), SUM(v) FROM sv GROUP BY g ORDER BY g"
    s.execute("SET SNAPSHOT = 'now'")
    snap_ts = s._snapshot[1]
    base = s.query(AGG)
    schedule.append(["pin", next_key])

    def check(tag: str):
        if s.query(AGG) != base:
            problems.append(f"{tag}: pinned aggregate diverged under "
                            f"writes")
        schedule.append(["agg", tag])

    parent = tier.metas[0].region_id

    def hook(phase: str):
        schedule.append(["phase", phase])
        hammer(4)                   # writes flow during the live split
        check(f"mid_split_{phase}")  # ... while the pinned agg re-runs

    failpoint.set_failpoint("tso.allocate", "1*drop")
    failpoint.set_failpoint("mvcc.gc", "1*drop")
    try:
        try:
            child = tier.split_region_online(parent, chaos_hook=hook)
            schedule.append(["split_ok", parent, child.region_id])
        except Exception as e:      # noqa: BLE001 — report, don't die
            problems.append(f"live split under pinned snapshot failed: "
                            f"{type(e).__name__}: {e}")
        hammer(max(writes - next_key, 8))
        check("after_split")
        # GC respects the pin: the watermark must not pass it, the first
        # sweep is failpoint-wedged (skipped), the second really sweeps —
        # and the pinned aggregate must still reproduce afterwards
        if db.mvcc.snapshots.watermark(db.mvcc.tso.last_ts()) > snap_ts:
            problems.append("gc watermark passed the oldest pin")
        db.mvcc.gc(db.stores.values())      # wedged by mvcc.gc 1*drop
        reclaimed = db.mvcc.gc(db.stores.values())
        schedule.append(["gc", reclaimed >= 0])
        check("after_gc")
        # quiesced replay: a FRESH session pins the RECORDED ts and must
        # read the exact aggregate the original pin saw
        s2 = Session(db, "chaos")
        s2.execute(f"SET SNAPSHOT = {snap_ts}")
        replay_ok = s2.query(AGG) == base
        if not replay_ok:
            problems.append("quiesced replay at the recorded ts diverged")
        s2.execute("SET SNAPSHOT = 0")
        schedule.append(["replay", replay_ok])
    finally:
        failpoint.clear("tso.allocate")
        failpoint.clear("mvcc.gc")
    # off-switch: mvcc=0 must read bit-identically to the unpinned read
    s.execute("SET SNAPSHOT = 0")
    live = s.query(AGG)
    prev_mvcc = bool(FLAGS.mvcc)
    set_flag("mvcc", 0)
    try:
        if s.query(AGG) != live:
            problems.append("mvcc=0 off-switch diverged from the "
                            "unpinned read")
    finally:
        set_flag("mvcc", 1 if prev_mvcc else 0)
    # explicit pin refusal surfaces; the next attempt lands
    failpoint.set_failpoint("snapshot.pin", "1*drop")
    try:
        refused = False
        try:
            s.execute("SET SNAPSHOT = 'now'")
        except Exception:           # noqa: BLE001 — the refusal IS the test
            refused = True
        if not refused:
            problems.append("refused explicit pin did not surface")
        s.execute("SET SNAPSHOT = 'now'")
        s.execute("SET SNAPSHOT = 0")
        schedule.append(["pin_refused", refused])
    finally:
        failpoint.clear("snapshot.pin")
    # TSO strict monotonicity across a meta raft leader kill: enough
    # allocations after the kill to force batched-range refills through
    # the NEW leader (the save-ahead lease covers the failover)
    rm = ReplicatedMeta(seed=5 + seed)
    cli = TsoClient(rm.tso_gen)
    seq = [cli.next_ts() for _ in range(5)]
    rm.kill_leader()
    seq += [cli.next_ts() for _ in range(3 * int(FLAGS.tso_batch_size))]
    if any(b <= a for a, b in zip(seq, seq[1:])):
        problems.append("TSO regressed across meta leader failover")
    schedule.append(["tso_failover", len(seq)])
    rows = s.query("SELECT k, g, v FROM sv ORDER BY k")
    state = {"rows": rows, "pinned": base,
             "regions": len(tier.metas)}
    return {"writes": next_key, "fault_schedule": schedule,
            "faults": len(schedule),
            "regions": len(tier.metas),
            "state_digest": _digest({"schedule": schedule, "state": state}),
            "problems": problems}


def cdc_chaos(seed: int = 9, writes: int = 60) -> dict:
    """CDC change streams + matview maintenance under seeded faults (the
    tentpole contract): INSERT/UPDATE/DELETE traffic flows while
    ``cdc.fetch`` drops/delays defer delivery, ``cdc.apply`` drops lose
    acks (forced redelivery), ``view.fold`` drops abandon maintenance
    rounds, and one store daemon is killed and revived mid-stream.

    Invariants checked:

    - **exactly-once**: an audit subscription applies every event with a
      commit_ts dedupe; replaying the applied row images reconstructs the
      final table EXACTLY (no lost event, no double-apply) even though
      lost acks redelivered batches (``redeliveries`` > 0 is the witness
      that the fault actually fired and was absorbed);
    - **view exactness at quiesce**: at failpoint-cleared checkpoints the
      materialized-view answer is BIT-IDENTICAL to the recompute
      (``matview_answer=0``) over the same data;
    - fleet plane: the run digest is a pure function of the seed
      (wall-clock commit_ts excluded by design)."""
    from ..cdc.streams import CursorLagging
    from ..utils.flags import FLAGS, set_flag

    rng = random.Random((seed << 8) ^ 0x636463)
    prev_seed = int(FLAGS.chaos_seed)
    set_flag("chaos_seed", seed)
    fleet, db, s = _fleet_session(seed)
    s.execute("CREATE TABLE cv (k BIGINT, g BIGINT, v BIGINT, "
              "PRIMARY KEY (k))")
    s.execute("CREATE MATERIALIZED VIEW cv_mv AS SELECT g, COUNT(*), "
              "SUM(v), MIN(v), MAX(v) FROM cv GROUP BY g")
    audit = db.cdc.create("audit", table_key="chaos.cv")
    AGG = ("SELECT g, COUNT(*), SUM(v), MIN(v), MAX(v) FROM cv "
           "GROUP BY g ORDER BY g")
    schedule: list[list] = []
    problems: list[str] = []
    applied: dict[int, bool] = {}       # commit_ts -> seen (the dedupe)
    replica: dict[int, tuple] = {}      # k -> (g, v) rebuilt from events
    redeliveries = 0
    lost_ranges = 0
    next_key = 0

    def consume(drain: bool = False):
        """The audit consumer: apply-then-ack with commit_ts dedupe."""
        nonlocal redeliveries, lost_ranges
        for _ in range(64 if drain else 2):
            try:
                evs = audit.fetch(32)
            except CursorLagging:
                lost_ranges += 1        # typed loss surfaced, never silent
                continue
            if not evs:
                if drain:
                    continue
                return
            for e in evs:
                if e.commit_ts in applied:
                    redeliveries += 1   # lost ack redelivered: absorbed
                    continue
                applied[e.commit_ts] = True
                if not e.rows:
                    problems.append(f"{e.event_type} event without row "
                                    f"images (capture fell back)")
                    continue
                if e.event_type == "insert":
                    for r in e.rows:
                        replica[int(r["k"])] = (r["g"], r["v"])
                elif e.event_type == "update":
                    for pair in e.rows:
                        n = pair["new"]
                        replica[int(n["k"])] = (n["g"], n["v"])
                elif e.event_type == "delete":
                    for r in e.rows:
                        replica.pop(int(r["k"]), None)
            audit.ack(evs[-1].commit_ts)    # cdc.apply may drop this

    def checkpoint(tag: str):
        """Quiesced: faults off, maintenance drains, view == recompute."""
        for n in ("cdc.fetch", "cdc.apply", "view.fold"):
            failpoint.clear(n)
        view = s.query(AGG)
        set_flag("matview_answer", 0)
        try:
            base = s.query(AGG)
        finally:
            set_flag("matview_answer", 1)
        if view != base:
            problems.append(f"{tag}: view answer diverged from recompute")
        schedule.append(["checkpoint", tag, view == base])
        return view

    tier = fleet.row_tiers["chaos.cv"]
    g0 = tier.groups[0]
    failpoint.set_failpoint("cdc.fetch", "25%drop")
    failpoint.set_failpoint("cdc.apply", "25%drop")
    failpoint.set_failpoint("view.fold", "20%drop")
    killed = None
    try:
        for i in range(writes):
            r = rng.random()
            if r < 0.55 or next_key < 4:
                s.execute(f"INSERT INTO cv VALUES ({next_key}, "
                          f"{next_key % 3}, {next_key * next_key})")
                next_key += 1
            elif r < 0.8:
                k = rng.randrange(next_key)
                s.execute(f"UPDATE cv SET v = v + 11 WHERE k = {k}")
                schedule.append(["update", k])
            else:
                k = rng.randrange(next_key)
                s.execute(f"DELETE FROM cv WHERE k = {k}")
                schedule.append(["delete", k])
            consume()
            if i % 5 == 4:
                s.query(AGG)            # exercise fold under the faults
            if killed is None and i == writes // 3:
                killed = g0.leader()
                g0.bus.kill(killed)
                schedule.append([i, "kill_daemon", killed])
            if killed is not None and i == (2 * writes) // 3:
                g0.bus.revive(killed)
                schedule.append([i, "revive", killed])
                killed = None
            if i == writes // 2:
                # switch fetch faults from drops to seeded delays (slow
                # consumer phase), then the checkpoint re-arms drops
                checkpoint("mid_run")
                failpoint.set_failpoint("cdc.fetch", "30%delay(1)")
                failpoint.set_failpoint("cdc.apply", "25%drop")
                failpoint.set_failpoint("view.fold", "20%drop")
        if killed is not None:
            g0.bus.revive(killed)
            schedule.append([writes, "revive", killed])
    finally:
        for n in ("cdc.fetch", "cdc.apply", "view.fold"):
            failpoint.clear(n)
        set_flag("chaos_seed", prev_seed)
    view = checkpoint("quiesce")
    consume(drain=True)
    rows = s.query("SELECT k, g, v FROM cv ORDER BY k")
    got = {int(r["k"]): (r["g"], r["v"]) for r in rows}
    if got != replica:
        missing = sorted(set(got) - set(replica))
        extra = sorted(set(replica) - set(got))
        wrong = sorted(k for k in set(got) & set(replica)
                       if got[k] != replica[k])
        problems.append(f"audit replay diverged from the table (lost="
                        f"{missing[:5]} extra={extra[:5]} "
                        f"wrong={wrong[:5]})")
    if redeliveries == 0:
        problems.append("no redelivery observed: the cdc.apply fault "
                        "never fired (chaos did not exercise the seam)")
    mv = db.matviews.get("chaos", "cv_mv")
    state = {"rows": rows, "view": view,
             "groups": len(mv.state or {})}
    return {"writes": writes, "fault_schedule": schedule,
            "faults": len(schedule),
            "events_applied": len(applied),
            "redeliveries": redeliveries,
            "lost_ranges": lost_ranges,
            "deltas_folded": mv.deltas_folded,
            "view_rescans": mv.rescans,
            "state_digest": _digest({"schedule": schedule, "state": state}),
            "problems": problems}


SCENARIOS = {
    "kill_leader": kill_leader,
    "partition": partition,
    "rpc_chaos": rpc_chaos,
    "dispatch_overload": dispatch_overload,
    "split_chaos": split_chaos,
    "migrate_chaos": migrate_chaos,
    "stream_chaos": stream_chaos,
    "fragment_chaos": fragment_chaos,
    "snapshot_chaos": snapshot_chaos,
    "cdc_chaos": cdc_chaos,
}


def run_scenario(name: str, seed: int, **kw) -> dict:
    """Run one scenario; assertion failures and crashes land in the result
    (``ok`` False + ``problems``/``error``), never as an unhandled raise —
    the harness must report a broken invariant, not die of it."""
    fn = SCENARIOS[name]
    try:
        out = fn(seed=seed, **kw)
    except Exception as e:          # noqa: BLE001 — the report IS the point
        out = {"fault_schedule": [], "problems": [],
               "error": f"{type(e).__name__}: {e}"}
    out["scenario"] = name
    out["seed"] = seed
    out["ok"] = not out.get("problems") and "error" not in out
    return out
