"""Raft consensus layer (reference: braft per-Region replication, SURVEY
§2.9).  The consensus core is native C++ (native/raft.cpp — a deterministic
state machine); this package owns what the reference delegates to brpc and
the OS: transport, timers, storage apply, and group management."""

from .core import RaftCore, raft_available
from .cluster import LocalBus, RaftGroup, ReplicatedRegion

__all__ = ["RaftCore", "raft_available", "LocalBus", "RaftGroup",
           "ReplicatedRegion"]
