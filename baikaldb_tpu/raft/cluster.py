"""Raft runtime: transport, replicated regions, group management.

The reference hosts one braft::StateMachine per Region inside baikalStore
processes connected by brpc (include/store/region.h:445).  Here the same
roles split differently: the native core (native/raft.cpp) decides, this
module moves bytes and applies commits.  ``LocalBus`` is an in-process
transport with deterministic delivery plus partition/kill controls — the
multi-node-without-a-cluster test pattern the reference uses by faking
topology into SchemaFactory (SURVEY §4), but covering election/failover
paths braft-based tests cannot drive deterministically.

A ``ReplicatedRegion`` applies committed write batches to its own MVCC row
table (native/engine.cpp), so each peer holds a real storage replica; a
snapshot is the serialized row table (install replaces the replica's state —
the reference's SST-streaming install_snapshot analog)."""

from __future__ import annotations

import struct
from typing import Callable, Optional

from ..chaos import failpoint
from ..storage.rowstore import RowTable
from ..types import Field, LType, Schema
from .core import CONFIG, DATA, LEADER, SNAPSHOT_KIND, Committed, RaftCore


# -- write-batch / snapshot codecs ------------------------------------------

# replicated command kinds (the store-side op_type dispatch, region.cpp:1680)
CMD_WRITE = 0        # apply ops immediately
CMD_PREPARE = 1      # buffer ops under txn_id (2PC phase 1)
CMD_COMMIT = 2       # apply buffered txn_id (2PC phase 2)
CMD_ROLLBACK = 3     # drop buffered txn_id
CMD_DECIDE = 4       # primary-region commit decision record
CMD_SET_RANGE = 5    # split/merge finalize: shrink/grow key range + version
CMD_TRIM = 6         # drop keys outside the region's range (post-split GC)
CMD_COLD = 7         # cold-tier manifest op + hot eviction (region_olap
#                      flush_to_cold analog: segment bytes live on the
#                      external FS, the manifest and the eviction watermark
#                      replicate here)


def encode_range(version: int, start: bytes, end: bytes) -> bytes:
    return struct.pack("<II", version, len(start)) + start + \
        struct.pack("<I", len(end)) + end


def decode_range(data: bytes) -> tuple[int, bytes, bytes]:
    version, slen = struct.unpack_from("<II", data, 0)
    pos = 8
    start = data[pos:pos + slen]
    pos += slen
    (elen,) = struct.unpack_from("<I", data, pos)
    pos += 4
    return version, start, data[pos:pos + elen]


def encode_cmd(cmd: int, txn_id: int, ops_bytes: bytes = b"") -> bytes:
    return struct.pack("<BQ", cmd, txn_id) + ops_bytes


def decode_cmd(data: bytes) -> tuple[int, int, bytes]:
    cmd, txn_id = struct.unpack_from("<BQ", data, 0)
    return cmd, txn_id, data[9:]


def encode_ops(ops: list[tuple[int, bytes, bytes]]) -> bytes:
    parts = [struct.pack("<I", len(ops))]
    for op, k, v in ops:
        parts.append(struct.pack("<BI", op, len(k)))
        parts.append(k)
        parts.append(struct.pack("<I", len(v)))
        parts.append(v)
    return b"".join(parts)


def decode_ops(data: bytes) -> list[tuple[int, bytes, bytes]]:
    (n,) = struct.unpack_from("<I", data, 0)
    pos = 4
    out = []
    for _ in range(n):
        op, klen = struct.unpack_from("<BI", data, pos)
        pos += 5
        k = data[pos:pos + klen]
        pos += klen
        (vlen,) = struct.unpack_from("<I", data, pos)
        pos += 4
        v = data[pos:pos + vlen]
        pos += vlen
        out.append((op, k, v))
    return out


def _ops_size(data: bytes) -> int:
    """Byte length of the leading encode_ops section."""
    (n,) = struct.unpack_from("<I", data, 0)
    pos = 4
    for _ in range(n):
        _, klen = struct.unpack_from("<BI", data, pos)
        pos += 5 + klen
        (vlen,) = struct.unpack_from("<I", data, pos)
        pos += 4 + vlen
    return pos


class ReplicatedRegion:
    """One peer's replica of one region: Raft core + MVCC row table."""

    def __init__(self, node_id: int, peers: list[int], seed: int = 1,
                 schema: Optional[Schema] = None,
                 key_columns: Optional[list[str]] = None):
        self.core = RaftCore(node_id, peers, seed=seed)
        self.node_id = node_id
        self.schema = schema or Schema((Field("k", LType.INT64, False),
                                        Field("v", LType.STRING, True)))
        self.key_columns = key_columns or [self.schema.fields[0].name]
        self.table = RowTable(self.schema, self.key_columns)
        self.applied_index = 0
        # 2PC replicated state: prepared-but-undecided txns and the primary
        # region's decision log (reference: prepared-txn recovery from
        # METAINFO_CF, transaction_pool.cpp)
        self.prepared: dict[int, bytes] = {}
        self.decisions: dict[int, int] = {}   # txn -> CMD_COMMIT|CMD_ROLLBACK
        # replica-local wall time a prepare was applied: in-doubt RECOVERY
        # only rolls back prepares older than a grace window, so it cannot
        # abort a live coordinator mid-2PC (the reference's txn timeout)
        self.prepared_at: dict[int, float] = {}
        # cold-tier manifest: ordered (seq, file, watermark) entries.  The
        # segment FILES live on the external FS; this list is the raft-
        # replicated truth about which segments exist and which rowid range
        # was evicted from the hot table (region_olap.cpp:727-882)
        self.cold_manifest: list[tuple[int, str, int]] = []
        # key-range ownership: [start_key, end_key) with b"" = unbounded;
        # range_version bumps at every split/merge finalize (the reference's
        # region version used to reject stale-routed requests,
        # region.cpp:4864 add_version)
        self.start_key: bytes = b""
        self.end_key: bytes = b""
        self.range_version: int = 1

    def apply_committed(self) -> list[Committed]:
        """Drain the core's committed entries into the row table (the
        braft on_apply analog, with the store's op_type dispatch)."""
        if failpoint.ENABLED:
            if failpoint.hit("raft.commit", node=self.node_id):
                return []       # drop: defer applying this round — commits
                #                 stay in the core and apply when cleared
        commits = self.core.drain_commits()
        for c in commits:
            if c.kind == DATA:
                cmd, txn_id, body = decode_cmd(c.data)
                if cmd == CMD_WRITE:
                    self.table.write_batch(self._in_range(decode_ops(body)))
                elif cmd == CMD_PREPARE:
                    self.prepared[txn_id] = body
                    import time as _time
                    self.prepared_at[txn_id] = _time.time()
                elif cmd == CMD_COMMIT:
                    ops = self.prepared.pop(txn_id, None)
                    self.prepared_at.pop(txn_id, None)
                    if ops is not None:
                        self.table.write_batch(self._in_range(decode_ops(ops)))
                elif cmd == CMD_ROLLBACK:
                    self.prepared.pop(txn_id, None)
                    self.prepared_at.pop(txn_id, None)
                elif cmd == CMD_DECIDE:
                    # first writer wins: a coordinator whose COMMIT decision
                    # propose timed out (but actually committed) may later
                    # replicate an ABORT decision — the abort must not
                    # overwrite a commit some region already resolved from
                    # (the torn-transaction window, ADVICE r03 medium)
                    self.decisions.setdefault(txn_id, body[0])
                elif cmd == CMD_SET_RANGE:
                    v, s, e = decode_range(body)
                    self.start_key, self.end_key = s, e
                    self.range_version = max(self.range_version, v)
                elif cmd == CMD_TRIM:
                    # drop rows that moved to another region at split
                    # finalize — deterministic on every replica (the
                    # split-aware compaction-filter analog)
                    dead = [(1, k, b"") for k, _ in self.table.scan_raw()
                            if not self._covers(k)]
                    if dead:
                        self.table.write_batch(dead)
                elif cmd == CMD_COLD:
                    self._apply_cold(body)
                self.applied_index = c.index
            elif c.kind == SNAPSHOT_KIND:
                self._install_snapshot(c.data)
                self.applied_index = c.index
            else:
                self.applied_index = c.index
        return commits

    def _apply_cold(self, body: bytes) -> None:
        """Cold-tier manifest op, deterministic on every replica.
        add:   record (seq, file, watermark) and EVICT the flushed hot rows
               (the bytes already sit immutably on the external FS —
               written by the flush coordinator BEFORE this committed).
               Eviction is not deletion: the rows live on in the segment
               and recovery replays cold-then-hot.  With a "keys" list
               ([hex key, value hash] pairs), eviction is per-key
               compare-and-swap — a row another frontend rewrote between
               the coordinator's scan and this apply keeps its NEWER hot
               version (the segment's stale copy is shadowed at replay).
               Without it (a coordinator that serializes flushes itself),
               everything at rowid <= watermark evicts.
        reset: replace this region's manifest (cold GC/merge); with
               "expect" (the file list the reset was computed from), a
               mismatch — a concurrent flush added a segment — makes the
               reset a deterministic no-op instead of orphaning it."""
        import json as _json

        m = _json.loads(body.decode())
        if m["op"] == "add":
            self.cold_manifest.append((int(m["seq"]), m["file"],
                                       int(m["watermark"])))
            if "keys" in m:
                from ..storage.replicated import _fnv64

                snap = dict(self.table.scan_raw())
                dead = []
                for khex, vh in m["keys"]:
                    k = bytes.fromhex(khex)
                    v = snap.get(k)
                    if v is not None and _fnv64(v) == int(vh):
                        dead.append((1, k, b""))
                if dead:
                    self.table.write_batch(dead)
                return
            wkey = self.table.key_codec.encode_one(
                {self.key_columns[0]: int(m["watermark"])})
            dead = [(1, k, b"") for k, _ in self.table.scan_raw()
                    if k <= wkey]
            if dead:
                self.table.write_batch(dead)
        elif m["op"] == "reset":
            if "expect" in m:
                current = sorted(f for _s, f, _w in self.cold_manifest)
                if current != sorted(m["expect"]):
                    return      # stale gc: a flush raced it — no-op
            self.cold_manifest = [(int(s), f, int(w))
                                  for s, f, w in m["entries"]]

    def _covers(self, key: bytes) -> bool:
        if self.start_key and key < self.start_key:
            return False
        if self.end_key and key >= self.end_key:
            return False
        return True

    def _in_range(self, ops: list[tuple[int, bytes, bytes]]):
        """After a split finalize, writes routed with a stale range must not
        land here (the reference rejects them with version_old; the router
        re-resolves and re-sends to the owning region)."""
        if not self.start_key and not self.end_key:
            return ops
        return [op for op in ops if self._covers(op[1])]

    # -- snapshots --------------------------------------------------------
    def snapshot_bytes(self) -> bytes:
        """Full replica state: rows + prepared txns + decisions + key range
        (install must not lose 2PC or ownership state)."""
        pairs = self.table.scan_raw()
        out = [encode_ops([(0, k, v) for k, v in pairs])]
        out.append(struct.pack("<I", len(self.prepared)))
        for txn, ops in sorted(self.prepared.items()):
            out.append(struct.pack("<QI", txn, len(ops)) + ops)
        out.append(struct.pack("<I", len(self.decisions)))
        for txn, d in sorted(self.decisions.items()):
            out.append(struct.pack("<QB", txn, d))
        rng = encode_range(self.range_version, self.start_key, self.end_key)
        out.append(struct.pack("<I", len(rng)) + rng)
        import json as _json

        cold = _json.dumps(self.cold_manifest).encode()
        out.append(struct.pack("<I", len(cold)) + cold)
        return b"".join(out)

    def _install_snapshot(self, data: bytes):
        self.table = RowTable(self.schema, self.key_columns)
        ops = decode_ops(data)
        self.table.write_batch(ops)
        pos = _ops_size(data)
        self.prepared = {}
        self.prepared_at = {}
        self.decisions = {}
        self.start_key = b""
        self.end_key = b""
        self.range_version = 1
        if pos >= len(data):
            return                      # pre-2PC snapshot format
        (np_,) = struct.unpack_from("<I", data, pos)
        pos += 4
        import time as _time

        now = _time.time()
        for _ in range(np_):
            txn, ln = struct.unpack_from("<QI", data, pos)
            pos += 12
            self.prepared[txn] = data[pos:pos + ln]
            pos += ln
            # prepare wall-times are replica-local and not in the snapshot;
            # stamp install time so the in-doubt grace window RESTARTS
            # instead of never starting (prepared_age would otherwise read
            # ~0 forever and recovery would defer the txn indefinitely —
            # ADVICE r03 low #1)
            self.prepared_at[txn] = now
        (nd,) = struct.unpack_from("<I", data, pos)
        pos += 4
        for _ in range(nd):
            txn, d = struct.unpack_from("<QB", data, pos)
            pos += 9
            self.decisions[txn] = d
        self.cold_manifest = []
        if pos < len(data):
            (rlen,) = struct.unpack_from("<I", data, pos)
            pos += 4
            v, s, e = decode_range(data[pos:pos + rlen])
            self.start_key, self.end_key, self.range_version = s, e, v
            pos += rlen
        if pos < len(data):
            import json as _json

            (clen,) = struct.unpack_from("<I", data, pos)
            pos += 4
            self.cold_manifest = [
                (int(sq), f, int(w))
                for sq, f, w in _json.loads(data[pos:pos + clen].decode())]

    def compact(self):
        """Snapshot own state into the core, truncating the log (the
        space-efficient snapshot scheme: state, not log history)."""
        self.core.compact(self.core.commit_index, self.snapshot_bytes())

    # -- reads ------------------------------------------------------------
    def rows(self) -> list[dict]:
        return self.table.scan_rows()

    def rows_in_range(self) -> list[dict]:
        """Rows this region OWNS.  During split/merge a replica can briefly
        hold keys outside its committed range (copied but not yet trimmed,
        or trimmed on another replica first); readers must never see them
        twice, so ownership — not possession — decides visibility."""
        return [self.table.row_codec.decode(v)
                for k, v in self.table.scan_raw() if self._covers(k)]


class LocalBus:
    """Deterministic in-process transport with fault injection."""

    def __init__(self):
        self.nodes: dict[int, ReplicatedRegion] = {}
        self.down: set[int] = set()
        self.blocked: set[tuple[int, int]] = set()   # (src, dst) pairs

    def add(self, region: ReplicatedRegion):
        self.nodes[region.node_id] = region

    def kill(self, node_id: int):
        self.down.add(node_id)

    def revive(self, node_id: int):
        self.down.discard(node_id)

    def partition(self, group_a: list[int], group_b: list[int]):
        for a in group_a:
            for b in group_b:
                self.blocked.add((a, b))
                self.blocked.add((b, a))

    def heal(self):
        self.blocked.clear()

    # -- drive ------------------------------------------------------------
    def pump(self, max_rounds: int = 200):
        """Deliver messages until quiescent; apply commits as they appear."""
        for _ in range(max_rounds):
            moved = False
            for nid, node in list(self.nodes.items()):
                if nid in self.down:
                    node.core.drain_messages()   # drop a dead node's output
                    continue
                for dest, msg in node.core.drain_messages():
                    moved = True
                    if dest in self.down or dest not in self.nodes:
                        continue
                    if (nid, dest) in self.blocked:
                        continue
                    self.nodes[dest].core.receive(msg)
            for nid, node in self.nodes.items():
                if nid not in self.down:
                    node.apply_committed()
            if not moved:
                return
        raise RuntimeError("bus did not quiesce")

    def advance(self, ticks: int = 1):
        """ticks x (tick every live node, then deliver to quiescence)."""
        for _ in range(ticks):
            for nid, node in self.nodes.items():
                if nid not in self.down:
                    node.core.tick()
            self.pump()

    def elect(self, max_ticks: int = 400) -> int:
        """Advance until some live node is leader; returns its id."""
        for _ in range(max_ticks):
            ldr = self.leader()
            if ldr is not None:
                return ldr
            self.advance(1)
        raise RuntimeError("no leader elected")

    def leader(self) -> Optional[int]:
        """The leader a quorum actually follows.  A leader partitioned from
        the majority still THINKS it leads (it cannot learn otherwise until
        healed); counting it would route writes into a black hole, so a
        candidate only qualifies when a quorum of its config is live and at
        its term following it."""
        if failpoint.ENABLED and failpoint.hit("raft.leader_step"):
            return None         # drop: report leaderless — election churn
        best = None
        for nid, node in self.nodes.items():
            if nid in self.down or node.core.role != LEADER:
                continue
            peers = node.core.peers()
            follows = 0
            for p in peers:
                if p == nid:
                    follows += 1
                    continue
                other = self.nodes.get(p)
                if other is None or p in self.down or \
                        (nid, p) in self.blocked or (p, nid) in self.blocked:
                    continue    # unreachable: cannot sustain this leader
                if other.core.term == node.core.term and \
                        other.core.leader == nid:
                    follows += 1
            if follows >= len(peers) // 2 + 1:
                if best is None or node.core.term > \
                        self.nodes[best].core.term:
                    best = nid
        return best


class RaftGroup:
    """One replicated region group — the store-fleet view the meta service
    balances (region -> peers, leader)."""

    def __init__(self, region_id: int, peer_ids: list[int], seed: int = 1,
                 schema: Optional[Schema] = None,
                 key_columns: Optional[list[str]] = None,
                 bus: Optional[LocalBus] = None):
        self.region_id = region_id
        self.schema = schema
        self.key_columns = key_columns
        self.seed = seed
        self.bus = bus or LocalBus()
        for pid in peer_ids:
            self.bus.add(ReplicatedRegion(pid, peer_ids, seed=seed,
                                          schema=schema,
                                          key_columns=key_columns))

    # -- client API -------------------------------------------------------
    def leader(self) -> int:
        ldr = self.bus.leader()
        if ldr is None:
            ldr = self.bus.elect()
        return ldr

    def write(self, ops: list[tuple[int, bytes, bytes]],
              max_ticks: int = 400) -> bool:
        """Propose a write batch; returns True once COMMITTED on the leader
        (the ack the reference gives after braft on_apply).  Retries through
        elections like FetcherStore's leader-redirect loop."""
        return self.propose_cmd(CMD_WRITE, 0, encode_ops(ops), max_ticks)

    def propose_cmd(self, cmd: int, txn_id: int, ops_bytes: bytes = b"",
                    max_ticks: int = 400) -> bool:
        """Propose a replicated command and wait for leader commit.  False
        when no quorum exists (the region is unavailable)."""
        from ..obs import trace

        with trace.span("raft.append", region=self.region_id, cmd=int(cmd)):
            if failpoint.ENABLED:
                if failpoint.hit("raft.append", region=self.region_id,
                                 cmd=int(cmd)):
                    return False    # drop: the append never happens —
                    #                 callers see it as quorum loss
            return self._propose_cmd(cmd, txn_id, ops_bytes, max_ticks)

    def _propose_cmd(self, cmd: int, txn_id: int, ops_bytes: bytes,
                     max_ticks: int) -> bool:
        payload = encode_cmd(cmd, txn_id, ops_bytes)
        for _ in range(max_ticks):
            try:
                ldr = self.leader()
            except RuntimeError:
                return False               # no electable quorum
            idx = self.bus.nodes[ldr].core.propose(payload)
            if idx < 0:
                self.bus.advance(1)
                continue
            for _ in range(max_ticks):
                self.bus.pump()
                if self.bus.nodes[ldr].core.commit_index >= idx:
                    return True
                if self.bus.nodes[ldr].core.role != LEADER:
                    break
                self.bus.advance(1)
            else:
                return False
        return False

    def set_range(self, version: int, start: bytes, end: bytes,
                  max_ticks: int = 400) -> bool:
        """Replicated range finalize (the add_version analog,
        region.cpp:4864): after commit, replicas reject out-of-range
        writes and TRIM drops moved rows."""
        return self.propose_cmd(CMD_SET_RANGE, 0,
                                encode_range(version, start, end), max_ticks)

    def trim(self, max_ticks: int = 400) -> bool:
        return self.propose_cmd(CMD_TRIM, 0, b"", max_ticks)

    def put_row(self, region: ReplicatedRegion, row: dict) -> bool:
        key = region.table.key_codec.encode_one(row)
        val = region.table.row_codec.encode(row)
        return self.write([(0, key, val)])

    # -- membership (meta balance orders execute through these) -----------
    def add_peer(self, new_id: int, max_ticks: int = 400) -> bool:
        """Single-server membership add (reference: raft_control add_peer).
        The config change is proposed FIRST; the replica only joins the bus
        once accepted (a rejected propose must not leave a ghost node whose
        election timeouts would depose real leaders forever)."""
        ldr = self.leader()
        if ldr is None:
            ldr = self.bus.elect()
        peers = self.bus.nodes[ldr].core.peers()
        payload = struct.pack("<Bq", 0, new_id)
        idx = self.bus.nodes[ldr].core.propose(payload, kind=CONFIG)
        if idx < 0:
            return False
        replica = ReplicatedRegion(new_id, peers + [new_id], seed=self.seed,
                                   schema=self.schema,
                                   key_columns=self.key_columns)
        self.bus.add(replica)
        for _ in range(max_ticks):
            self.bus.pump()
            if replica.core.commit_index >= idx:
                return True
            self.bus.advance(1)
        self.bus.nodes.pop(new_id, None)    # never caught up: no ghost
        return False

    def add_learner(self, new_id: int, max_ticks: int = 400) -> bool:
        """Add a NON-VOTING learner replica (reference: learner replicas,
        region.h:261-267): it receives full log replication and applies
        commits — a read-serving replica — but never counts toward quorum
        and never elects."""
        ldr = self.leader()
        if ldr is None:
            ldr = self.bus.elect()
        peers = self.bus.nodes[ldr].core.peers()
        payload = struct.pack("<Bq", 2, new_id)
        idx = self.bus.nodes[ldr].core.propose(payload, kind=CONFIG)
        if idx < 0:
            return False
        replica = ReplicatedRegion(new_id, peers, seed=self.seed,
                                   schema=self.schema,
                                   key_columns=self.key_columns)
        self.bus.add(replica)
        for _ in range(max_ticks):
            self.bus.pump()
            if replica.core.commit_index >= idx:
                return True
            self.bus.advance(1)
        self.bus.nodes.pop(new_id, None)
        return False

    def promote_learner(self, learner_id: int, max_ticks: int = 400) -> bool:
        """Promote an EXISTING caught-up learner replica to voter (the
        learner-first migration finalize).  The native core treats an
        add-voter config entry for a known learner as a promotion (it
        leaves the learner set and joins the voter set on every replica);
        unlike ``add_peer`` no new replica is created — the learner already
        holds the replicated state."""
        if learner_id not in self.bus.nodes:
            return False
        ldr = self.leader()
        if learner_id not in self.bus.nodes[ldr].core.learners():
            return False
        payload = struct.pack("<Bq", 0, learner_id)
        idx = self.bus.nodes[ldr].core.propose(payload, kind=CONFIG)
        if idx < 0:
            return False
        for _ in range(max_ticks):
            self.bus.pump()
            if self.bus.nodes[ldr].core.commit_index >= idx:
                return True
            self.bus.advance(1)
        return False

    def remove_learner(self, learner_id: int, max_ticks: int = 400) -> bool:
        ldr = self.leader()
        payload = struct.pack("<Bq", 3, learner_id)
        idx = self.bus.nodes[ldr].core.propose(payload, kind=CONFIG)
        if idx < 0:
            return False
        for _ in range(max_ticks):
            self.bus.pump()
            if self.bus.nodes[ldr].core.commit_index >= idx:
                self.bus.nodes.pop(learner_id, None)
                return True
            self.bus.advance(1)
        return False

    def remove_peer(self, dead_id: int, max_ticks: int = 400) -> bool:
        ldr = self.leader()
        if ldr == dead_id:
            raise ValueError("transfer leadership before removing the leader")
        payload = struct.pack("<Bq", 1, dead_id)
        idx = self.bus.nodes[ldr].core.propose(payload, kind=CONFIG)
        if idx < 0:
            return False
        for _ in range(max_ticks):
            self.bus.pump()
            if self.bus.nodes[ldr].core.commit_index >= idx:
                self.bus.nodes.pop(dead_id, None)
                return True
            self.bus.advance(1)
        return False

    def peers(self) -> list[int]:
        return sorted(self.bus.nodes[self.leader()].core.peers())
