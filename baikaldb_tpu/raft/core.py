"""ctypes binding over the native Raft core (native/raft.cpp).

The core is a pure deterministic state machine: the host calls tick() on its
own clock, feeds inbound messages to receive(), and drains three output
channels — outbound messages, committed entries, and snapshot-install
events.  Determinism (seeded election timeouts, no internal clocks/threads)
is what makes elections and partitions unit-testable, which the reference's
braft cannot do without real time and sockets."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from dataclasses import dataclass
from typing import Optional

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SO = os.path.join(_HERE, "native", "build", "libbkraft.so")
_SRC = os.path.join(_HERE, "native", "raft.cpp")

_lock = threading.Lock()
_lib = None
_err: Optional[str] = None

NOOP, DATA, CONFIG = 0, 1, 2
SNAPSHOT_KIND = 255
FOLLOWER, CANDIDATE, LEADER = 0, 1, 2


def _build() -> Optional[str]:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return None
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", _SRC, "-o", _SO]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except Exception as e:  # pragma: no cover
        return f"{type(e).__name__}: {e}"
    return None if r.returncode == 0 else r.stderr[-2000:]


def _sig(lib):
    c = ctypes
    P8 = c.POINTER(c.c_uint8)
    P64 = c.POINTER(c.c_int64)
    lib.rf_new.restype = c.c_void_p
    lib.rf_new.argtypes = [c.c_int64, P64, c.c_int, c.c_uint64, c.c_int,
                           c.c_int, c.c_int]
    lib.rf_free.argtypes = [c.c_void_p]
    lib.rf_tick.argtypes = [c.c_void_p]
    lib.rf_receive.argtypes = [c.c_void_p, P8, c.c_int64]
    lib.rf_propose.restype = c.c_int64
    lib.rf_propose.argtypes = [c.c_void_p, c.c_uint8, P8, c.c_int64]
    for name in ("rf_role", "rf_peer_count", "rf_learner_count",
                 "rf_committed_current_term"):
        getattr(lib, name).restype = c.c_int
        getattr(lib, name).argtypes = [c.c_void_p]
    lib.rf_learners.argtypes = [c.c_void_p, P64]
    for name in ("rf_term", "rf_commit_index", "rf_last_index",
                 "rf_first_index"):
        getattr(lib, name).restype = c.c_uint64
        getattr(lib, name).argtypes = [c.c_void_p]
    lib.rf_leader.restype = c.c_int64
    lib.rf_leader.argtypes = [c.c_void_p]
    lib.rf_peers.argtypes = [c.c_void_p, P64]
    lib.rf_out_count.restype = c.c_int64
    lib.rf_out_count.argtypes = [c.c_void_p]
    lib.rf_out_dest.restype = c.c_int64
    lib.rf_out_dest.argtypes = [c.c_void_p, c.c_int64]
    lib.rf_out_size.restype = c.c_int64
    lib.rf_out_size.argtypes = [c.c_void_p, c.c_int64]
    lib.rf_out_copy.argtypes = [c.c_void_p, c.c_int64, P8]
    lib.rf_out_clear.argtypes = [c.c_void_p]
    lib.rf_commit_count.restype = c.c_int64
    lib.rf_commit_count.argtypes = [c.c_void_p]
    lib.rf_commit_index_at.restype = c.c_uint64
    lib.rf_commit_index_at.argtypes = [c.c_void_p, c.c_int64]
    lib.rf_commit_kind.restype = c.c_int
    lib.rf_commit_kind.argtypes = [c.c_void_p, c.c_int64]
    lib.rf_commit_size.restype = c.c_int64
    lib.rf_commit_size.argtypes = [c.c_void_p, c.c_int64]
    lib.rf_commit_copy.argtypes = [c.c_void_p, c.c_int64, P8]
    lib.rf_commit_clear.argtypes = [c.c_void_p]
    lib.rf_compact.argtypes = [c.c_void_p, c.c_uint64, P8, c.c_int64]
    lib.rf_transfer.restype = c.c_int
    lib.rf_transfer.argtypes = [c.c_void_p, c.c_int64]
    return lib


def get_lib():
    global _lib, _err
    with _lock:
        if _lib is not None or _err is not None:
            return _lib
        err = _build()
        if err is not None:
            _err = err
            return None
        try:
            _lib = _sig(ctypes.CDLL(_SO))
        except OSError as e:  # pragma: no cover
            _err = str(e)
            return None
        return _lib


def raft_available() -> bool:
    return get_lib() is not None


@dataclass
class Committed:
    index: int
    kind: int          # DATA / NOOP / CONFIG / SNAPSHOT_KIND
    data: bytes


class RaftCore:
    """One consensus participant (no IO — see cluster.LocalBus)."""

    def __init__(self, node_id: int, peers: list[int], seed: int = 1,
                 election_min: int = 10, election_max: int = 20,
                 hb_interval: int = 3):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError("native raft core unavailable")
        arr = (ctypes.c_int64 * len(peers))(*peers)
        self._h = self._lib.rf_new(node_id, arr, len(peers), seed,
                                   election_min, election_max, hb_interval)
        self.node_id = node_id

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.rf_free(h)
            self._h = None

    # -- drive ------------------------------------------------------------
    def tick(self):
        self._lib.rf_tick(self._h)

    def receive(self, msg: bytes):
        buf = (ctypes.c_uint8 * len(msg)).from_buffer_copy(msg)
        self._lib.rf_receive(self._h, buf, len(msg))

    def propose(self, data: bytes, kind: int = DATA) -> int:
        buf = (ctypes.c_uint8 * max(1, len(data))).from_buffer_copy(
            data or b"\0")
        return int(self._lib.rf_propose(self._h, kind, buf, len(data)))

    def transfer_leader(self, target: int) -> bool:
        return int(self._lib.rf_transfer(self._h, target)) == 0

    def compact(self, upto: int, snapshot: bytes):
        buf = (ctypes.c_uint8 * max(1, len(snapshot))).from_buffer_copy(
            snapshot or b"\0")
        self._lib.rf_compact(self._h, upto, buf, len(snapshot))

    # -- outputs ----------------------------------------------------------
    def drain_messages(self) -> list[tuple[int, bytes]]:
        lib, h = self._lib, self._h
        n = lib.rf_out_count(h)
        out = []
        for i in range(n):
            size = lib.rf_out_size(h, i)
            buf = (ctypes.c_uint8 * max(1, size))()
            lib.rf_out_copy(h, i, buf)
            out.append((int(lib.rf_out_dest(h, i)), bytes(buf[:size])))
        lib.rf_out_clear(h)
        return out

    def drain_commits(self) -> list[Committed]:
        lib, h = self._lib, self._h
        n = lib.rf_commit_count(h)
        out = []
        for i in range(n):
            size = lib.rf_commit_size(h, i)
            buf = (ctypes.c_uint8 * max(1, size))()
            lib.rf_commit_copy(h, i, buf)
            out.append(Committed(int(lib.rf_commit_index_at(h, i)),
                                 int(lib.rf_commit_kind(h, i)),
                                 bytes(buf[:size])))
        lib.rf_commit_clear(h)
        return out

    # -- state ------------------------------------------------------------
    @property
    def role(self) -> int:
        return int(self._lib.rf_role(self._h))

    @property
    def term(self) -> int:
        return int(self._lib.rf_term(self._h))

    @property
    def leader(self) -> int:
        return int(self._lib.rf_leader(self._h))

    @property
    def commit_index(self) -> int:
        return int(self._lib.rf_commit_index(self._h))

    @property
    def read_safe(self) -> bool:
        """Raft §8 read barrier: True once an entry of the CURRENT term is
        committed.  A fresh leader must not serve reads before this — it
        cannot yet have applied entries the old leader committed."""
        return bool(self._lib.rf_committed_current_term(self._h))

    @property
    def last_index(self) -> int:
        return int(self._lib.rf_last_index(self._h))

    @property
    def first_index(self) -> int:
        return int(self._lib.rf_first_index(self._h))

    def peers(self) -> list[int]:
        n = self._lib.rf_peer_count(self._h)
        arr = (ctypes.c_int64 * max(1, n))()
        self._lib.rf_peers(self._h, arr)
        return [int(arr[i]) for i in range(n)]

    def learners(self) -> list[int]:
        """Non-voting replicated members (reference: learner replicas,
        include/store/region.h:261-267)."""
        n = self._lib.rf_learner_count(self._h)
        arr = (ctypes.c_int64 * max(1, n))()
        self._lib.rf_learners(self._h, arr)
        return [int(arr[i]) for i in range(n)]
