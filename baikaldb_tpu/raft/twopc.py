"""Two-phase commit across raft region groups.

The reference commits a multi-region DML by PREPARE fan-out, then COMMIT on
the PRIMARY region first, then the secondaries; a secondary crashing with a
prepared txn recovers by asking the primary whether the decision landed
(src/exec/fetcher_store.cpp:1848-1904 primary-first commit,
src/store/region.cpp:684 exec_txn_query_primary_region, transaction_pool.cpp
prepared-txn recovery).

Here each participant is a RaftGroup (raft-replicated itself, so "a region
crashed" means its quorum is gone or its coordinator died): PREPARE/COMMIT/
ROLLBACK are replicated commands in each group's log, and the commit
DECISION is a replicated record on the primary group — the single source of
truth for in-doubt resolution."""

from __future__ import annotations

import itertools
import threading

from ..chaos import failpoint
from .cluster import (CMD_COMMIT, CMD_DECIDE, CMD_PREPARE, CMD_ROLLBACK,
                      RaftGroup, encode_ops)

_txn_ids = itertools.count(1)
_txn_lock = threading.Lock()


def next_txn_id() -> int:
    with _txn_lock:
        return next(_txn_ids)


class TwoPhaseError(RuntimeError):
    pass


class TwoPhaseCoordinator:
    """Coordinates one multi-region write (the DML manager node analog).

    ``crash_after`` (test hook): "prepare" kills the coordinator after the
    prepare fan-out, "primary" after the primary commit — the two windows
    the reference's recovery protocol must cover."""

    def __init__(self, groups: list[RaftGroup]):
        if not groups:
            raise ValueError("need at least one participant")
        self.primary = groups[0]
        self.secondaries = groups[1:]
        self.groups = groups

    def write(self, per_group_ops: dict[int, list], crash_after: str = "",
              txn_id: int | None = None, commit_ts: int = 0) -> int:
        """ops per region_id; returns the txn id.  Raises TwoPhaseError on a
        failed prepare (everything rolled back).

        ``commit_ts``: the transaction's MVCC commit timestamp, stamped at
        DECIDE time — it rides the decision record's raft log entry as a
        trailing 8-byte field, so the one instant every region's versions
        become visible at is itself quorum-persisted.  Replica apply reads
        only ``body[0]`` (the outcome byte), so old snapshots/replicas
        decode the extended record unchanged."""
        from ..obs import trace

        txn = txn_id or next_txn_id()
        decide_commit = bytes([CMD_COMMIT])
        if commit_ts:
            import struct
            decide_commit += struct.pack("<Q", int(commit_ts))
        by_region = {g.region_id: g for g in self.groups}
        # phase 1: PREPARE everywhere (each is itself raft-committed)
        prepared = []
        with trace.span("2pc.prepare", txn=txn,
                        regions=len(per_group_ops)):
            for rid, ops in per_group_ops.items():
                g = by_region[rid]
                injected = False
                if failpoint.ENABLED:
                    # drop: this participant's prepare fails (rollback fan-
                    # out); return/panic raise mid-fan-out, leaving earlier
                    # prepares in doubt — the recovery protocol's window
                    injected = failpoint.hit("2pc.prepare", txn=txn,
                                             region=rid)
                if injected or \
                        not g.propose_cmd(CMD_PREPARE, txn, encode_ops(ops)):
                    for p in prepared:
                        p.propose_cmd(CMD_ROLLBACK, txn)
                    raise TwoPhaseError(f"prepare failed on region {rid}")
                prepared.append(g)
        if crash_after == "prepare":
            return txn                    # coordinator dies here
        # decision record + commit on the PRIMARY first: once this is in the
        # primary's log the txn is globally COMMITTED.  The decision propose
        # MUST be verified — acking a txn whose decision never reached
        # quorum would lose it (recovery would roll the prepares back).
        with trace.span("2pc.decide", txn=txn):
            dropped = False
            if failpoint.ENABLED:
                dropped = failpoint.hit("2pc.decide", txn=txn)
            decided = (not dropped) and \
                self.primary.propose_cmd(CMD_DECIDE, txn, decide_commit)
        if not decided:
            # A failed propose does NOT mean the decision failed to commit —
            # a timeout can lose the ack, not the entry.  Rolling prepares
            # back here could tear the txn (recovery commits a surviving
            # prepare from the landed decision while others rolled back —
            # ADVICE r03 medium).  Replicate an explicit ABORT decision
            # instead; the apply is first-writer-wins, so reading back the
            # WINNING decision tells us which outcome is authoritative.
            if not self.primary.propose_cmd(CMD_DECIDE, txn,
                                            bytes([CMD_ROLLBACK])):
                # can't even record the abort: leave every prepare in doubt
                # for recovery to resolve from whatever decision exists
                raise TwoPhaseError(
                    f"commit decision in doubt on primary region "
                    f"{self.primary.region_id}; prepares left for recovery")
            winner = self.primary.bus.nodes[
                self.primary.leader()].decisions.get(txn)
            if winner != CMD_COMMIT:
                # abort decision won: rollbacks are now safe (best-effort —
                # failures leave in-doubt prepares that recovery rolls back
                # from the abort record)
                for p in prepared:
                    p.propose_cmd(CMD_ROLLBACK, txn)
                raise TwoPhaseError(
                    f"commit decision failed on primary region "
                    f"{self.primary.region_id}")
            # the original commit decision actually landed: fall through —
            # the txn IS committed
        # past the decision point the txn is committed; the remaining
        # proposals are completion, not consensus — a failure here leaves an
        # in-doubt prepare that resolve_in_doubt finishes from the decision
        with trace.span("2pc.commit", txn=txn):
            self.primary.propose_cmd(CMD_COMMIT, txn)
            if crash_after == "primary":
                return txn                # coordinator dies here
            for g in self.secondaries:
                if g.region_id in per_group_ops:
                    g.propose_cmd(CMD_COMMIT, txn)
        return txn


def resolve_in_doubt(group: RaftGroup, primary: RaftGroup, txn_id: int) -> str:
    """Recovery for a prepared-but-undecided txn on ``group``: ask the
    primary (reference: region.cpp:598/684 — in-doubt secondaries query the
    primary region's txn state).  -> "committed" | "rolled_back"."""
    ldr = primary.bus.nodes[primary.leader()]
    decision = ldr.decisions.get(txn_id)
    if decision == CMD_COMMIT:
        group.propose_cmd(CMD_COMMIT, txn_id)
        return "committed"
    # explicit abort decision, or no decision at all (the coordinator died
    # before the commit point): the txn must abort everywhere (the
    # primary's own prepare, if any, rolls back too)
    for g in (group, primary):
        if txn_id in g.bus.nodes[g.leader()].prepared:
            g.propose_cmd(CMD_ROLLBACK, txn_id)
    return "rolled_back"


def recover_all(groups: list[RaftGroup], primary: RaftGroup) -> dict[int, str]:
    """Resolve every in-doubt txn across ``groups`` against the primary."""
    out: dict[int, str] = {}
    for g in groups:
        ldr = g.bus.nodes[g.leader()]
        for txn in sorted(list(ldr.prepared)):
            out[txn] = resolve_in_doubt(g, primary, txn)
    return out
