"""Store fleet: named store nodes hosting Raft-replicated regions, wired to
the meta service's control loop.

The reference's loop (SURVEY §3.5): stores heartbeat instance + region state
to meta; meta's health checks and balancers answer with add_peer /
remove_peer / trans_leader orders; stores execute them through braft
(region_manager.cpp:159-197, raft_control.cpp).  This module closes the same
loop in-process: ``StoreFleet`` reports REAL raft state (leaders, versions,
row counts) in heartbeats and executes meta's orders as REAL membership
changes / leadership transfers on the underlying RaftGroups — the round-1
gap where balance orders commanded nothing."""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..chaos import failpoint
from ..meta.service import (MIGRATING, SERVING, BalanceOrder,
                            HeartbeatRequest, MetaService)
from ..types import Schema
from ..utils import metrics
from .cluster import RaftGroup, ReplicatedRegion
from .core import LEADER


class MigrateError(RuntimeError):
    """A live replica migration failed and was rolled back (membership
    unchanged — learner torn down, meta registry restored)."""


class StoreFleet:
    """All store nodes of one deployment (addresses are the instance names
    registered with meta; raft node ids are derived stably from them)."""

    def __init__(self, meta: MetaService, addresses: list[str],
                 schema: Optional[Schema] = None,
                 key_columns: Optional[list[str]] = None, seed: int = 7):
        self.meta = meta
        self.schema = schema
        self.key_columns = key_columns
        self.seed = seed
        self.addresses = list(addresses)
        self._ids = {a: i + 1 for i, a in enumerate(addresses)}
        self._addr = {i: a for a, i in self._ids.items()}
        self.groups: dict[int, RaftGroup] = {}     # region_id -> group
        # table_key -> storage.replicated.ReplicatedRowTier: SQL-visible
        # replicated tables survive Database restarts through this registry;
        # tier_lock serializes check-then-create so two frontends creating
        # the same table never mint duplicate region sets
        self.row_tiers: dict = {}
        import threading
        self.tier_lock = threading.Lock()
        for a in addresses:
            meta.add_instance(a)

    def _id_of(self, address: str) -> int:
        if address not in self._ids:
            nid = max(self._addr) + 1 if self._addr else 1
            self._ids[address] = nid
            self._addr[nid] = address
            # late-joining stores (e.g. OLAP learner hosts) must heartbeat
            # like everyone else, or meta's health check marks them DEAD
            self.addresses.append(address)
        return self._ids[address]

    # -- region lifecycle -------------------------------------------------
    def create_table_regions(self, table_id: int, n_regions: int = 1,
                             schema: Optional[Schema] = None,
                             key_columns: Optional[list[str]] = None):
        """Meta assigns placement; the fleet materializes raft groups on the
        chosen peers (init_region fan-out, store.interface.proto:425).
        ``schema``/``key_columns`` override the fleet defaults so each SQL
        table's regions replicate rows in that table's own row encoding."""
        metas = self.meta.create_regions(table_id, n_regions)
        for rm in metas:
            peer_ids = [self._id_of(a) for a in rm.peers]
            g = RaftGroup(rm.region_id, peer_ids, seed=self.seed,
                          schema=schema or self.schema,
                          key_columns=key_columns or self.key_columns)
            self.groups[rm.region_id] = g
            ldr = g.leader()
            rm.leader = self._addr[ldr]
        return metas

    def group(self, region_id: int) -> RaftGroup:
        return self.groups[region_id]

    def materialize_region(self, rm, schema: Optional[Schema] = None,
                           key_columns: Optional[list[str]] = None) -> RaftGroup:
        """Instantiate a raft group for an already-registered RegionMeta —
        the split path: meta registered the child region on the parent's
        peers; the stores now host it (region.cpp:4472 init of the new
        region on the same instances)."""
        peer_ids = [self._id_of(a) for a in rm.peers]
        g = RaftGroup(rm.region_id, peer_ids, seed=self.seed,
                      schema=schema or self.schema,
                      key_columns=key_columns or self.key_columns)
        self.groups[rm.region_id] = g
        rm.leader = self._addr[g.leader()]
        return g

    def replica(self, region_id: int, address: str) -> ReplicatedRegion:
        return self.groups[region_id].bus.nodes[self._ids[address]]

    # -- control loop -----------------------------------------------------
    def heartbeat_all(self):
        """Every live store reports its REAL raft state to meta — version,
        rows, and the PR 8 per-region load gauges (apply lag, proposal
        backlog) meta's load-driven split trigger consumes."""
        for a in self.addresses:
            nid = self._ids[a]
            regions: dict[int, tuple] = {}
            leader_ids = []
            dead = False
            for rid, g in sorted(self.groups.items()):
                node = g.bus.nodes.get(nid)
                if node is None:
                    continue
                if nid in g.bus.down:
                    dead = True
                    continue
                regions[rid] = (1, len(node.rows()),
                                max(0, node.core.commit_index
                                    - node.applied_index),
                                max(0, node.core.last_index
                                    - node.core.commit_index))
                if node.core.role == LEADER:
                    leader_ids.append(rid)
            if not dead:
                resp = self.meta.heartbeat(
                    HeartbeatRequest(a, regions, leader_ids))
                self._apply_params(resp.param_overrides)

    def _apply_params(self, overrides: dict):
        """Apply meta-pushed dynamic config (reference: stores applying
        update_instance_param from heartbeat responses).  Unknown names are
        ignored — meta may be newer than this store."""
        from ..utils.flags import FLAGS, FlagError
        for name, value in overrides.items():
            try:
                FLAGS.set_flag(name, value)   # no-op (no listeners) when
            except FlagError:                  # the value is unchanged
                pass

    def kill_store(self, address: str):
        """Hard-fail one store node across every region it hosts."""
        nid = self._ids[address]
        for g in self.groups.values():
            if nid in g.bus.nodes:
                g.bus.kill(nid)

    def revive_store(self, address: str):
        """Bring a killed store back across every region it hosts."""
        nid = self._ids[address]
        for g in self.groups.values():
            if nid in g.bus.nodes:
                g.bus.revive(nid)

    def partition_store(self, address: str):
        """Partition one store away from the rest of the fleet on EVERY
        region bus it participates in (a split/migration spans multiple
        raft groups — parent + child — so a fleet partition must cover
        them all, not just one group)."""
        nid = self._ids[address]
        for g in self.groups.values():
            if nid in g.bus.nodes:
                rest = [n for n in g.bus.nodes if n != nid]
                if rest:
                    g.bus.partition([nid], rest)

    def heal_all(self):
        """Heal every region bus in the fleet."""
        for g in self.groups.values():
            g.bus.heal()

    # -- elastic regions ---------------------------------------------------
    def tier_of_region(self, region_id: int):
        """The SQL row tier hosting a region, if any (bare test regions
        created straight through create_table_regions have none)."""
        with self.tier_lock:
            tiers = list(self.row_tiers.values())
        for tier in tiers:
            if any(m.region_id == region_id for m in tier.metas):
                return tier
        return None

    def retire_region(self, region_id: int) -> None:
        """Tear one region fully down: raft group out of the fleet, meta
        entry out of routing.  The single teardown seam — split aborts,
        merges and tier release all funnel here so neither registry can
        leak a dead group the other still routes to."""
        self.groups.pop(region_id, None)
        try:
            self.meta.drop_regions([region_id])
        except Exception:       # meta itself quorumless: group is gone,
            metrics.count_swallowed("fleet.retire_region")  # routing entry
            #                         dies with the next meta recovery

    def migrate_replica(self, region_id: int, source: str, target: str,
                        chaos_hook: Optional[Callable[[str], None]] = None
                        ) -> bool:
        """Move one replica ``source`` -> ``target`` LIVE, learner-first
        (reference: peer balance through braft learner catch-up;
        region_manager.cpp:189 + raft_control):

        1. the leader compacts, so the new learner bootstraps from ONE
           snapshot install (the PR 10 artifact-replication bulk-copy
           shape) instead of replaying the whole log,
        2. add learner on ``target`` -> snapshot + log catch-up
           (``migrate.snapshot`` failpoint),
        3. promote the caught-up learner to voter (``migrate.promote``),
        4. transfer leadership away from ``source`` if it leads,
        5. remove the ``source`` peer; meta records the real membership.

        Writes flow throughout — the group keeps a quorum at every step
        (3 voters -> 3 voters + learner -> 4 voters -> 3 voters).  On any
        failure before promotion the learner is torn down and membership
        is restored unchanged (MigrateError); ``chaos_hook(phase)`` lets
        scenarios inject kills/writes between phases deterministically.
        """
        rm = self.meta.regions.get(region_id)
        g = self.groups.get(region_id)
        if rm is None or g is None:
            raise ValueError(f"unknown region {region_id}")
        src_id, tgt_id = self._ids.get(source), self._id_of(target)
        if src_id is None or src_id not in g.bus.nodes:
            raise ValueError(f"{source!r} hosts no replica of "
                             f"region {region_id}")
        if tgt_id in g.bus.nodes:
            raise ValueError(f"{target!r} already hosts a replica of "
                             f"region {region_id}")
        t0 = time.perf_counter()
        self.meta.set_region_state(region_id, MIGRATING)
        learner_added = promoted = False
        try:
            if chaos_hook is not None:
                chaos_hook("start")
            # bulk copy: one snapshot install, not a log replay from 1
            ldr = g.bus.nodes[g.leader()]
            ldr.compact()
            if failpoint.ENABLED:
                if failpoint.hit("migrate.snapshot", region=region_id,
                                 target=target):
                    raise MigrateError(
                        f"region {region_id}: snapshot transfer to "
                        f"{target} failed (injected)")
            if not g.add_learner(tgt_id):
                raise MigrateError(f"region {region_id}: add_learner "
                                   f"{target} did not commit")
            learner_added = True
            if chaos_hook is not None:
                chaos_hook("learner")
            # catch-up gate: the learner must have applied everything the
            # leader has committed before it may count toward quorum
            learner = g.bus.nodes[tgt_id]
            for _ in range(400):
                learner.apply_committed()
                if learner.applied_index >= \
                        g.bus.nodes[g.leader()].core.commit_index:
                    break
                g.bus.pump()
                g.bus.advance(1)
            else:
                raise MigrateError(f"region {region_id}: learner {target} "
                                   f"never caught up")
            if failpoint.ENABLED:
                if failpoint.hit("migrate.promote", region=region_id,
                                 target=target):
                    raise MigrateError(
                        f"region {region_id}: promotion of {target} "
                        f"failed (injected)")
            if not g.promote_learner(tgt_id):
                raise MigrateError(f"region {region_id}: promote "
                                   f"{target} did not commit")
            promoted = True
            if chaos_hook is not None:
                chaos_hook("promoted")
            # leadership must leave the outgoing peer BEFORE removal
            if g.leader() == src_id:
                if g.bus.nodes[src_id].core.transfer_leader(tgt_id):
                    g.bus.pump()
                    g.bus.elect()
                if g.leader() == src_id:
                    raise MigrateError(
                        f"region {region_id}: could not transfer "
                        f"leadership off {source}")
            if not g.remove_peer(src_id):
                raise MigrateError(f"region {region_id}: remove_peer "
                                   f"{source} did not commit")
            if chaos_hook is not None:
                chaos_hook("removed")
        except MigrateError:
            # pre-promotion failure: tear the learner down — membership is
            # exactly what it was.  Post-promotion failure (remove_peer of
            # the source did not commit): the target IS a raft voter now;
            # tearing it down would fight the committed config, so the
            # region stays at 4 voters and meta records that real state —
            # a consistent (if temporarily wide) membership, never a
            # half-routed one.
            if learner_added and not promoted and tgt_id in g.bus.nodes:
                g.remove_learner(tgt_id)
            self._record_membership(region_id, g)
            metrics.region_migrate_aborts.add(1)
            raise
        finally:
            self.meta.set_region_state(region_id, SERVING)
        self._record_membership(region_id, g)
        metrics.region_migrations.add(1)
        metrics.region_handoff_ms.observe((time.perf_counter() - t0) * 1e3)
        return True

    def _record_membership(self, region_id: int, g: RaftGroup) -> None:
        """Write the raft group's REAL membership back into meta's registry
        (the one owner of routing state)."""
        try:
            ldr = g.leader()
        except RuntimeError:
            return                      # quorumless: nothing to record
        peers = sorted(self._addr[n] for n in g.bus.nodes[ldr].core.peers()
                       if n in self._addr)
        learners = sorted(self._addr[n]
                          for n in g.bus.nodes[ldr].core.learners()
                          if n in self._addr)
        self.meta.update_region_membership(
            region_id, peers=peers, leader=self._addr.get(ldr, ""),
            learners=learners)

    def apply_orders(self, orders: list[BalanceOrder]) -> int:
        """Execute meta's balance orders as real raft operations
        (reference: store applying heartbeat-response orders,
        region.h:654-665)."""
        done = 0
        for o in orders:
            g = self.groups.get(o.region_id)
            if g is None:
                continue
            if o.kind == "split":
                tier = self.tier_of_region(o.region_id)
                if tier is None:
                    # bare (tierless) region: nothing can execute a split —
                    # clear the SPLITTING mark so balancing resumes
                    self.meta.set_region_state(o.region_id, SERVING)
                    continue
                from ..storage.replicated import SplitError
                try:
                    tier.split_region_online(o.region_id)
                    done += 1
                except SplitError:
                    pass           # aborted cleanly; next tick retries
            elif o.kind == "migrate":
                try:
                    if self.migrate_replica(o.region_id, o.source,
                                            o.target):
                        done += 1
                except (MigrateError, ValueError):
                    # rolled back (or stale order): meta re-learns real
                    # membership from heartbeats and may retry
                    self._record_membership(o.region_id, g)
            elif o.kind == "add_peer":
                if g.add_peer(self._id_of(o.target)):
                    done += 1
            elif o.kind == "remove_peer":
                nid = self._ids.get(o.source)
                if nid is None or nid not in g.bus.nodes:
                    continue
                if g.bus.leader() == nid:
                    continue       # meta must transfer leadership first
                if g.remove_peer(nid):
                    done += 1
            elif o.kind == "trans_leader":
                src, tgt = self._ids.get(o.source), self._ids.get(o.target)
                if src is None or tgt is None or src not in g.bus.nodes:
                    continue
                if not g.bus.nodes[src].core.transfer_leader(tgt):
                    continue       # source no longer leads: stale order
                g.bus.pump()
                if g.bus.elect() == tgt:
                    done += 1      # count only a transfer that took effect
        return done

    def operator_order(self, kind: str, region_id: int,
                       address: str) -> None:
        """Operator membership op (reference: raft_control add/remove/
        transfer-leader RPCs): validates against meta, executes on the raft
        group, and records the result in meta's region registry — so
        routing and balancing never drift from real membership.  Raises
        ValueError on bad input, RuntimeError when the raft op fails."""
        rm = self.meta.regions.get(region_id)
        g = self.groups.get(region_id)
        if rm is None or g is None:
            raise ValueError(f"unknown region {region_id}")
        if address not in self.meta.instances:
            raise ValueError(f"unknown store {address!r}")
        if kind == "add_peer":
            if address in rm.peers:
                raise ValueError(f"{address} is already a peer")
            if not g.add_peer(self._id_of(address)):
                raise RuntimeError(f"add_peer {address} did not commit")
            self.meta.update_region_membership(
                region_id, peers=list(rm.peers) + [address])
        elif kind == "remove_peer":
            if address not in rm.peers:
                raise ValueError(f"{address} is not a peer")
            nid = self._ids.get(address)
            if nid is not None and g.bus.leader() == nid:
                raise ValueError("transfer leadership away first")
            if not g.remove_peer(nid):
                raise RuntimeError(f"remove_peer {address} did not commit")
            self.meta.update_region_membership(
                region_id, peers=[p for p in rm.peers if p != address])
        elif kind == "add_learner":
            if address in rm.peers or address in rm.learners:
                raise ValueError(f"{address} already hosts a replica")
            if not g.add_learner(self._id_of(address)):
                raise RuntimeError(f"add_learner {address} did not commit")
            self.meta.update_region_membership(
                region_id, learners=list(rm.learners) + [address])
        elif kind == "remove_learner":
            if address not in rm.learners:
                raise ValueError(f"{address} is not a learner")
            if not g.remove_learner(self._ids.get(address)):
                raise RuntimeError(f"remove_learner {address} did not "
                                   f"commit")
            self.meta.update_region_membership(
                region_id,
                learners=[a for a in rm.learners if a != address])
        elif kind == "trans_leader":
            src = g.leader()
            tgt = self._ids.get(address)
            if tgt is None or tgt not in g.bus.nodes:
                raise ValueError(f"{address} hosts no replica of "
                                 f"region {region_id}")
            if src == tgt:
                return
            if not g.bus.nodes[src].core.transfer_leader(tgt):
                raise RuntimeError("current leader rejected the transfer")
            g.bus.pump()
            if g.bus.elect() != tgt:
                raise RuntimeError("leadership transfer did not take effect")
            self.meta.update_region_membership(region_id, leader=address)
        else:
            raise ValueError(f"unknown operator order {kind!r}")

    def control_tick(self) -> int:
        """One full control-loop turn: heartbeats in, orders out, orders
        executed.  Returns how many orders were applied."""
        self.heartbeat_all()
        orders = self.meta.tick()
        return self.apply_orders(orders)
