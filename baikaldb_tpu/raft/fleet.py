"""Store fleet: named store nodes hosting Raft-replicated regions, wired to
the meta service's control loop.

The reference's loop (SURVEY §3.5): stores heartbeat instance + region state
to meta; meta's health checks and balancers answer with add_peer /
remove_peer / trans_leader orders; stores execute them through braft
(region_manager.cpp:159-197, raft_control.cpp).  This module closes the same
loop in-process: ``StoreFleet`` reports REAL raft state (leaders, versions,
row counts) in heartbeats and executes meta's orders as REAL membership
changes / leadership transfers on the underlying RaftGroups — the round-1
gap where balance orders commanded nothing."""

from __future__ import annotations

from typing import Optional

from ..meta.service import BalanceOrder, HeartbeatRequest, MetaService
from ..types import Schema
from .cluster import RaftGroup, ReplicatedRegion
from .core import LEADER


class StoreFleet:
    """All store nodes of one deployment (addresses are the instance names
    registered with meta; raft node ids are derived stably from them)."""

    def __init__(self, meta: MetaService, addresses: list[str],
                 schema: Optional[Schema] = None,
                 key_columns: Optional[list[str]] = None, seed: int = 7):
        self.meta = meta
        self.schema = schema
        self.key_columns = key_columns
        self.seed = seed
        self.addresses = list(addresses)
        self._ids = {a: i + 1 for i, a in enumerate(addresses)}
        self._addr = {i: a for a, i in self._ids.items()}
        self.groups: dict[int, RaftGroup] = {}     # region_id -> group
        # table_key -> storage.replicated.ReplicatedRowTier: SQL-visible
        # replicated tables survive Database restarts through this registry;
        # tier_lock serializes check-then-create so two frontends creating
        # the same table never mint duplicate region sets
        self.row_tiers: dict = {}
        import threading
        self.tier_lock = threading.Lock()
        for a in addresses:
            meta.add_instance(a)

    def _id_of(self, address: str) -> int:
        if address not in self._ids:
            nid = max(self._addr) + 1 if self._addr else 1
            self._ids[address] = nid
            self._addr[nid] = address
            # late-joining stores (e.g. OLAP learner hosts) must heartbeat
            # like everyone else, or meta's health check marks them DEAD
            self.addresses.append(address)
        return self._ids[address]

    # -- region lifecycle -------------------------------------------------
    def create_table_regions(self, table_id: int, n_regions: int = 1,
                             schema: Optional[Schema] = None,
                             key_columns: Optional[list[str]] = None):
        """Meta assigns placement; the fleet materializes raft groups on the
        chosen peers (init_region fan-out, store.interface.proto:425).
        ``schema``/``key_columns`` override the fleet defaults so each SQL
        table's regions replicate rows in that table's own row encoding."""
        metas = self.meta.create_regions(table_id, n_regions)
        for rm in metas:
            peer_ids = [self._id_of(a) for a in rm.peers]
            g = RaftGroup(rm.region_id, peer_ids, seed=self.seed,
                          schema=schema or self.schema,
                          key_columns=key_columns or self.key_columns)
            self.groups[rm.region_id] = g
            ldr = g.leader()
            rm.leader = self._addr[ldr]
        return metas

    def group(self, region_id: int) -> RaftGroup:
        return self.groups[region_id]

    def materialize_region(self, rm, schema: Optional[Schema] = None,
                           key_columns: Optional[list[str]] = None) -> RaftGroup:
        """Instantiate a raft group for an already-registered RegionMeta —
        the split path: meta registered the child region on the parent's
        peers; the stores now host it (region.cpp:4472 init of the new
        region on the same instances)."""
        peer_ids = [self._id_of(a) for a in rm.peers]
        g = RaftGroup(rm.region_id, peer_ids, seed=self.seed,
                      schema=schema or self.schema,
                      key_columns=key_columns or self.key_columns)
        self.groups[rm.region_id] = g
        rm.leader = self._addr[g.leader()]
        return g

    def replica(self, region_id: int, address: str) -> ReplicatedRegion:
        return self.groups[region_id].bus.nodes[self._ids[address]]

    # -- control loop -----------------------------------------------------
    def heartbeat_all(self):
        """Every live store reports its REAL raft state to meta."""
        for a in self.addresses:
            nid = self._ids[a]
            regions: dict[int, tuple[int, int]] = {}
            leader_ids = []
            dead = False
            for rid, g in self.groups.items():
                node = g.bus.nodes.get(nid)
                if node is None:
                    continue
                if nid in g.bus.down:
                    dead = True
                    continue
                regions[rid] = (1, len(node.rows()))
                if node.core.role == LEADER:
                    leader_ids.append(rid)
            if not dead:
                resp = self.meta.heartbeat(
                    HeartbeatRequest(a, regions, leader_ids))
                self._apply_params(resp.param_overrides)

    def _apply_params(self, overrides: dict):
        """Apply meta-pushed dynamic config (reference: stores applying
        update_instance_param from heartbeat responses).  Unknown names are
        ignored — meta may be newer than this store."""
        from ..utils.flags import FLAGS, FlagError
        for name, value in overrides.items():
            try:
                FLAGS.set_flag(name, value)   # no-op (no listeners) when
            except FlagError:                  # the value is unchanged
                pass

    def kill_store(self, address: str):
        """Hard-fail one store node across every region it hosts."""
        nid = self._ids[address]
        for g in self.groups.values():
            if nid in g.bus.nodes:
                g.bus.kill(nid)

    def apply_orders(self, orders: list[BalanceOrder]) -> int:
        """Execute meta's balance orders as real raft operations
        (reference: store applying heartbeat-response orders,
        region.h:654-665)."""
        done = 0
        for o in orders:
            g = self.groups.get(o.region_id)
            if g is None:
                continue
            if o.kind == "add_peer":
                if g.add_peer(self._id_of(o.target)):
                    done += 1
            elif o.kind == "remove_peer":
                nid = self._ids.get(o.source)
                if nid is None or nid not in g.bus.nodes:
                    continue
                if g.bus.leader() == nid:
                    continue       # meta must transfer leadership first
                if g.remove_peer(nid):
                    done += 1
            elif o.kind == "trans_leader":
                src, tgt = self._ids.get(o.source), self._ids.get(o.target)
                if src is None or tgt is None or src not in g.bus.nodes:
                    continue
                if not g.bus.nodes[src].core.transfer_leader(tgt):
                    continue       # source no longer leads: stale order
                g.bus.pump()
                if g.bus.elect() == tgt:
                    done += 1      # count only a transfer that took effect
        return done

    def operator_order(self, kind: str, region_id: int,
                       address: str) -> None:
        """Operator membership op (reference: raft_control add/remove/
        transfer-leader RPCs): validates against meta, executes on the raft
        group, and records the result in meta's region registry — so
        routing and balancing never drift from real membership.  Raises
        ValueError on bad input, RuntimeError when the raft op fails."""
        rm = self.meta.regions.get(region_id)
        g = self.groups.get(region_id)
        if rm is None or g is None:
            raise ValueError(f"unknown region {region_id}")
        if address not in self.meta.instances:
            raise ValueError(f"unknown store {address!r}")
        if kind == "add_peer":
            if address in rm.peers:
                raise ValueError(f"{address} is already a peer")
            if not g.add_peer(self._id_of(address)):
                raise RuntimeError(f"add_peer {address} did not commit")
            self.meta.update_region_membership(
                region_id, peers=list(rm.peers) + [address])
        elif kind == "remove_peer":
            if address not in rm.peers:
                raise ValueError(f"{address} is not a peer")
            nid = self._ids.get(address)
            if nid is not None and g.bus.leader() == nid:
                raise ValueError("transfer leadership away first")
            if not g.remove_peer(nid):
                raise RuntimeError(f"remove_peer {address} did not commit")
            self.meta.update_region_membership(
                region_id, peers=[p for p in rm.peers if p != address])
        elif kind == "add_learner":
            if address in rm.peers or address in rm.learners:
                raise ValueError(f"{address} already hosts a replica")
            if not g.add_learner(self._id_of(address)):
                raise RuntimeError(f"add_learner {address} did not commit")
            self.meta.update_region_membership(
                region_id, learners=list(rm.learners) + [address])
        elif kind == "remove_learner":
            if address not in rm.learners:
                raise ValueError(f"{address} is not a learner")
            if not g.remove_learner(self._ids.get(address)):
                raise RuntimeError(f"remove_learner {address} did not "
                                   f"commit")
            self.meta.update_region_membership(
                region_id,
                learners=[a for a in rm.learners if a != address])
        elif kind == "trans_leader":
            src = g.leader()
            tgt = self._ids.get(address)
            if tgt is None or tgt not in g.bus.nodes:
                raise ValueError(f"{address} hosts no replica of "
                                 f"region {region_id}")
            if src == tgt:
                return
            if not g.bus.nodes[src].core.transfer_leader(tgt):
                raise RuntimeError("current leader rejected the transfer")
            g.bus.pump()
            if g.bus.elect() != tgt:
                raise RuntimeError("leadership transfer did not take effect")
            self.meta.update_region_membership(region_id, leader=address)
        else:
            raise ValueError(f"unknown operator order {kind!r}")

    def control_tick(self) -> int:
        """One full control-loop turn: heartbeats in, orders out, orders
        executed.  Returns how many orders were applied."""
        self.heartbeat_all()
        orders = self.meta.tick()
        return self.apply_orders(orders)
