"""Device-resident columnar batches — the TPU analog of Arrow RecordBatch.

The reference's execution unit is a row batch (``include/runtime/row_batch.h``)
with a columnar sibling built by ``Chunk`` (``include/runtime/chunk.h:27``:
tuples -> arrow::ArrayBuilders -> RecordBatch).  Here the execution unit is a
:class:`ColumnBatch`: a pytree of fixed-width jax arrays (one per column, plus
optional validity masks and an optional row-selection mask) that flows through
jit-compiled kernels.

Key deviations from the Arrow model, driven by XLA:

- **Static shapes**: a batch's row count is a compile-time constant.  Filters do
  NOT shrink batches; they refine the ``sel`` mask (late materialization).  The
  ``compact`` kernel (ops/compact.py) materializes a dense prefix when an op
  needs one.
- **Strings are int32 codes** into host-side sorted dictionaries
  (column/dictionary.py).
- **Validity is a bool array**, not a bitmask — XLA vectorizes bool ops fine and
  bit-twiddling would fight the VPU.  ``validity=None`` means all-valid.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..types import Field, LType, Schema
from .dictionary import NULL_CODE, Dictionary


@jax.tree_util.register_pytree_node_class
@dataclass
class Column:
    """One column: device data + optional validity + static metadata."""

    data: Any                       # jnp array [N]
    validity: Optional[Any] = None  # jnp bool [N] or None (all valid)
    ltype: LType = LType.INT64      # static
    dictionary: Optional[Dictionary] = None  # static, host-side (strings only)

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.validity), (self.ltype, self.dictionary)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, validity = children
        ltype, dictionary = aux
        return cls(data=data, validity=validity, ltype=ltype, dictionary=dictionary)

    def __len__(self) -> int:
        return self.data.shape[0]

    def valid_mask(self) -> Any:
        if self.validity is None:
            return jnp.ones(jnp.shape(self.data), dtype=bool)
        return self.validity

    def with_data(self, data, validity="keep") -> "Column":
        if validity == "keep":
            validity = self.validity
        return replace(self, data=data, validity=validity)

    # -- host conversion ------------------------------------------------
    @staticmethod
    def from_numpy(arr: np.ndarray, ltype: LType, validity: np.ndarray | None = None,
                   dictionary: Dictionary | None = None) -> "Column":
        return Column(jnp.asarray(arr), None if validity is None else jnp.asarray(validity),
                      ltype, dictionary)

    def to_numpy(self):
        """-> (np data, np validity-or-None); strings stay as codes."""
        v = None if self.validity is None else np.asarray(self.validity)
        return np.asarray(self.data), v


@jax.tree_util.register_pytree_node_class
@dataclass
class ColumnBatch:
    """An ordered set of equal-length columns plus an optional selection mask.

    ``sel`` (bool [N] or None) marks live rows — the late-materialization analog
    of the reference's filtered RowBatch.  ``num_rows`` when set is a *traced
    scalar* giving the count of live rows among the leading prefix (set by
    ``compact``); None means sel/all rows are authoritative.

    ``live_prefix`` (static) is the capacity-bucketing promise: every live row
    sits in a leading prefix and ``sel`` equals ``arange(capacity) < live``
    (set by ``pad_batch`` on bucketed store batches).  Consumers may then skip
    the stable-partition gather that ``compact`` otherwise needs.
    """

    names: tuple  # static
    columns: list  # list[Column]
    sel: Optional[Any] = None
    num_rows: Optional[Any] = None
    live_prefix: bool = False  # static

    def tree_flatten(self):
        return (self.columns, self.sel, self.num_rows), \
            (self.names, self.live_prefix)

    @classmethod
    def tree_unflatten(cls, aux, children):
        columns, sel, num_rows = children
        return cls(names=aux[0], columns=list(columns), sel=sel,
                   num_rows=num_rows, live_prefix=aux[1])

    # -- accessors ------------------------------------------------------
    def __len__(self) -> int:
        return 0 if not self.columns else self.columns[0].data.shape[0]

    @property
    def capacity(self) -> int:
        return len(self)

    def column(self, name: str) -> Column:
        return self.columns[self.names.index(name)]

    def __contains__(self, name: str) -> bool:
        return name in self.names

    def sel_mask(self) -> Any:
        if self.sel is None:
            return jnp.ones(len(self), dtype=bool)
        return self.sel

    def live_count(self):
        """Traced count of live rows."""
        if self.num_rows is not None:
            return self.num_rows
        if self.sel is None:
            return jnp.int32(len(self))
        return jnp.sum(self.sel).astype(jnp.int32)

    # -- functional updates --------------------------------------------
    def with_sel(self, sel) -> "ColumnBatch":
        return ColumnBatch(self.names, self.columns, sel, None)

    def and_sel(self, mask) -> "ColumnBatch":
        sel = mask if self.sel is None else jnp.logical_and(self.sel, mask)
        return ColumnBatch(self.names, self.columns, sel, None)

    def select(self, names: list[str]) -> "ColumnBatch":
        cols = [self.column(n) for n in names]
        return ColumnBatch(tuple(names), cols, self.sel, self.num_rows,
                           live_prefix=self.live_prefix)

    def append_column(self, name: str, col: Column) -> "ColumnBatch":
        return ColumnBatch(self.names + (name,), self.columns + [col],
                           self.sel, self.num_rows,
                           live_prefix=self.live_prefix)

    def rename(self, names: list[str]) -> "ColumnBatch":
        return ColumnBatch(tuple(names), self.columns, self.sel,
                           self.num_rows, live_prefix=self.live_prefix)

    def gather(self, idx, valid=None) -> "ColumnBatch":
        """Row gather; idx traced int array, valid optional mask for out rows."""
        cols = []
        for c in self.columns:
            data = jnp.take(c.data, idx, axis=0, mode="clip")
            if c.validity is not None:
                v = jnp.take(c.validity, idx, mode="clip")
                if valid is not None:
                    v = jnp.logical_and(v, valid)
            else:
                v = valid
            cols.append(replace(c, data=data, validity=v))
        return ColumnBatch(self.names, cols, None, None)

    def schema(self) -> Schema:
        return Schema(tuple(Field(n, c.ltype) for n, c in zip(self.names, self.columns)))

    # -- host <-> device ------------------------------------------------
    @staticmethod
    def from_arrow(table) -> "ColumnBatch":
        """Build from a pyarrow Table/RecordBatch (host->device ingest).

        The analog of the reference's row->column conversion
        (src/store/row2column, include/runtime/chunk.h), with string columns
        dictionary-encoded (see column/dictionary.py).
        """
        import pyarrow as pa

        names, cols = [], []
        for fld in table.schema:
            arr = table.column(fld.name)
            if hasattr(arr, "combine_chunks"):
                arr = arr.combine_chunks()
            names.append(fld.name)
            cols.append(_arrow_to_column(arr, fld.type))
        return ColumnBatch(tuple(names), cols)

    def to_arrow(self):
        """Densify + decode back to a pyarrow Table (device->host egress).

        Used by the result-packet layer (the reference renders MySQL packets in
        src/exec/packet_node.cpp from Arrow tables on the vectorized path)."""
        import pyarrow as pa

        sel = None if self.sel is None else np.asarray(self.sel)
        n = None
        if self.num_rows is not None:
            n = int(self.num_rows)
        arrays, fields = [], []
        for name, c in zip(self.names, self.columns):
            data, valid = c.to_numpy()
            if n is not None:
                data = data[:n]
                valid = None if valid is None else valid[:n]
            elif sel is not None:
                data = data[sel]
                valid = None if valid is None else valid[sel]
            arrays.append(_column_to_arrow(c, data, valid))
            fields.append(pa.field(name, arrays[-1].type))
        return pa.table(arrays, schema=pa.schema(fields))

    def to_pylist(self) -> list[dict]:
        return self.to_arrow().to_pylist()


# ----------------------------------------------------------------------
_ARROW_LTYPE = None


def _arrow_ltype_map():
    global _ARROW_LTYPE
    if _ARROW_LTYPE is None:
        import pyarrow as pa

        _ARROW_LTYPE = {
            pa.bool_(): LType.BOOL,
            pa.int8(): LType.INT8,
            pa.int16(): LType.INT16,
            pa.int32(): LType.INT32,
            pa.int64(): LType.INT64,
            pa.uint32(): LType.UINT32,
            pa.uint64(): LType.UINT64,
            pa.float32(): LType.FLOAT32,
            pa.float64(): LType.FLOAT64,
            pa.date32(): LType.DATE,
            pa.timestamp("us"): LType.DATETIME,
        }
    return _ARROW_LTYPE


def _arrow_to_numpy(arr, typ):
    """Host half of the Arrow->device codec: -> (np data, np validity-or-
    None, ltype, dictionary-or-None).  The streaming chunk layer
    (storage/streamchunks.py) encodes a whole snapshot through this once —
    table-wide string dictionaries, the null-fill discipline — and slices
    chunks host-side; resident ingest wraps the same arrays in jnp below."""
    import pyarrow as pa
    import pyarrow.compute as pc

    if pa.types.is_string(typ) or pa.types.is_large_string(typ) or pa.types.is_dictionary(typ):
        d, codes = Dictionary.from_arrow(arr)
        validity = codes != NULL_CODE if arr.null_count else None
        return codes, validity, LType.STRING, d
    if pa.types.is_decimal(typ):
        arr = pc.cast(arr, pa.float64())
        typ = pa.float64()
    if pa.types.is_date32(typ):
        ltype = LType.DATE
        work = arr.cast(pa.int32())
    elif pa.types.is_timestamp(typ):
        ltype = LType.DATETIME
        work = arr.cast(pa.timestamp("us")).cast(pa.int64())
    else:
        ltype = _arrow_ltype_map().get(typ)
        if ltype is None:
            raise TypeError(f"unsupported arrow type {typ}")
        work = arr
    if arr.null_count:
        validity = ~np.asarray(arr.is_null())
        if not pa.types.is_floating(work.type):
            # fill nulls at the Arrow level: pyarrow's to_numpy renders a
            # null-bearing int array as float64+NaN, which corrupts 64-bit
            # integers beyond 2^53 (caught in round-2 regression)
            fill = False if pa.types.is_boolean(work.type) else 0
            work = pc.fill_null(work, fill)
        np_data = work.to_numpy(zero_copy_only=False)
        if np_data.dtype.kind == "f":
            np_data = np.nan_to_num(np_data)
        return np_data.astype(ltype.np_dtype, copy=False), validity, ltype, None
    np_data = work.to_numpy(zero_copy_only=False)
    return np_data.astype(ltype.np_dtype, copy=False), None, ltype, None


def _arrow_to_column(arr, typ) -> Column:
    data, validity, ltype, d = _arrow_to_numpy(arr, typ)
    return Column(jnp.asarray(data),
                  None if validity is None else jnp.asarray(validity),
                  ltype, d)


def _column_to_arrow(c: Column, data: np.ndarray, valid: np.ndarray | None):
    import pyarrow as pa

    if c.ltype is LType.STRING:
        if c.dictionary is None:
            return pa.array(data.astype(np.int32), type=pa.int32())
        strings = c.dictionary.decode(data.astype(np.int32))
        if valid is not None:
            strings[~valid] = None
        return pa.array(strings, type=pa.string())
    mask = None if valid is None else ~valid
    if c.ltype is LType.DATE:
        return pa.array(data.astype("int32"), type=pa.date32(), mask=mask)
    if c.ltype in (LType.DATETIME, LType.TIMESTAMP):
        return pa.array(data.astype("int64"), type=pa.timestamp("us"), mask=mask)
    return pa.array(data, mask=mask)


def bucket_capacity(n: int, minimum: int = 1) -> int:
    """Smallest power-of-two >= max(n, minimum, 1): the capacity bucket a
    batch of ``n`` rows pads into.  A table growing inside one bucket keeps
    its device shape, so every executable compiled against it stays valid;
    only a bucket crossing (or shrink below the previous bucket) retraces."""
    return 1 << (max(int(n), int(minimum), 1) - 1).bit_length()


def pad_batch(batch: ColumnBatch, capacity: int) -> ColumnBatch:
    """Pad to ``capacity`` rows with dead rows (``sel=False`` tail).

    The fill is NULL-safe per dtype — zeros / False / code 0 — the same
    "real-looking but dead" payload filtered-out rows already carry, so any
    kernel correct under sel masks is correct over the padded tail.  When the
    input had no sel (all rows live) the result is marked ``live_prefix``:
    live rows form a leading prefix, which lets ``compact`` skip its gather.
    """
    n = len(batch)
    if capacity < n:
        raise ValueError(f"pad_batch: capacity {capacity} < {n} rows")
    prefix = batch.sel is None
    if capacity == n:
        if batch.sel is None:
            # attach an explicit all-live mask: the pytree structure must not
            # flip between sel=None and sel=array as the row count moves
            # through an exact power of two (that flip alone would retrace)
            return ColumnBatch(batch.names, batch.columns,
                               jnp.ones(n, dtype=bool), batch.num_rows,
                               live_prefix=True)
        return batch
    pad = capacity - n
    cols = []
    for c in batch.columns:
        data = jnp.concatenate(
            [c.data, jnp.zeros((pad,) + c.data.shape[1:], c.data.dtype)])
        validity = None
        if c.validity is not None:
            validity = jnp.concatenate([c.validity, jnp.zeros((pad,), bool)])
        cols.append(Column(data, validity, c.ltype, c.dictionary))
    sel = jnp.concatenate([batch.sel_mask(), jnp.zeros((pad,), bool)])
    return ColumnBatch(batch.names, cols, sel, None, live_prefix=prefix)


def concat_batches(batches: list[ColumnBatch]) -> ColumnBatch:
    """Concatenate same-schema batches (densified) along rows."""
    assert batches
    first = batches[0]
    cols = []
    for i, name in enumerate(first.names):
        parts_d, parts_v, any_v = [], [], False
        for b in batches:
            c = b.columns[i]
            parts_d.append(c.data)
            v = c.valid_mask() if c.validity is not None else None
            parts_v.append(v)
            any_v = any_v or v is not None
        data = jnp.concatenate(parts_d)
        validity = None
        if any_v:
            validity = jnp.concatenate([
                v if v is not None else jnp.ones(d.shape[0], dtype=bool)
                for v, d in zip(parts_v, parts_d)
            ])
        cols.append(replace(first.columns[i], data=data, validity=validity))
    sels = [b.sel_mask() if b.sel is not None else None for b in batches]
    sel = None
    if any(s is not None for s in sels):
        sel = jnp.concatenate([
            s if s is not None else jnp.ones(len(b), dtype=bool)
            for s, b in zip(sels, batches)
        ])
    return ColumnBatch(first.names, cols, sel, None)
