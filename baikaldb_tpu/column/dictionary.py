"""Host-side sorted string dictionaries.

TPUs cannot chase variable-length string bytes; the reference's columnar path
keeps strings as Arrow utf8 arrays and runs string kernels on CPU
(``src/expr/arrow_string_function.cpp``).  The TPU-native design instead
dictionary-encodes every string column at ingest:

- the *codes* (int32) live on device and flow through every kernel;
- the *dictionary* (a sorted, de-duplicated numpy array of strings) stays on
  the host, attached to the column metadata.

Because the dictionary is sorted:
- ``=  <  <= >  >=`` against a literal compile to integer comparisons on codes
  (via host-side binary search for the literal's code / insertion point);
- ``LIKE 'prefix%'`` compiles to a code-range test;
- arbitrary string functions (LENGTH, UPPER, SUBSTR, regexp) are evaluated once
  per *distinct* value on the host, producing a lookup table gathered by code on
  device — O(|dict|) host work instead of O(N) row work.

Cross-column string ops (joins, group-bys spanning two tables) remap one side's
codes through a host-computed translation table (`translate_codes`).
"""

from __future__ import annotations

import numpy as np

NULL_CODE = np.int32(-1)


class Dictionary:
    """An immutable sorted dictionary for one string column."""

    __slots__ = ("values", "_id", "_ft_index", "_ft_state", "_hash_cache",
                 "_fp")

    def __init__(self, values: np.ndarray):
        # values must be sorted unique unicode/objects
        self.values = values
        self._id = id(values)
        self._ft_index = None   # lazily-built fulltext index (index/fulltext)
        self._ft_state = None   # per-dictionary BM25 state (fulltext)
        self._hash_cache = None
        self._fp = None         # lazy content fingerprint (see __eq__)

    # -- value equality ---------------------------------------------------
    # Dictionaries ride pytree aux data (column/batch.Column), so jax.jit
    # keys compiled executables on them.  Identity semantics would retrace
    # every query on a string column after ANY table mutation (each rebuild
    # allocates a fresh Dictionary even when the distinct values are
    # unchanged) — the recompile storm capacity bucketing exists to end.
    # Content equality via a cached digest keeps aux comparison O(1) after
    # the first hash, and a changed value set (which really does invalidate
    # traced code constants) still misses.
    def _fingerprint(self) -> bytes:
        if self._fp is None:
            import hashlib

            h = hashlib.blake2b(digest_size=16)
            arr = self.values
            h.update(str(len(arr)).encode())
            if arr.dtype.kind == "U":
                h.update(str(arr.dtype).encode())
                h.update(arr.tobytes())
            else:
                for v in arr:
                    b = str(v).encode("utf-8")
                    h.update(len(b).to_bytes(4, "little"))
                    h.update(b)
            self._fp = h.digest()
        return self._fp

    def __eq__(self, other):
        if self is other:
            return True
        if not isinstance(other, Dictionary):
            return NotImplemented
        return self._fingerprint() == other._fingerprint()

    def __hash__(self):
        return hash(self._fingerprint())

    # -- construction ---------------------------------------------------
    @staticmethod
    def encode(strings) -> tuple["Dictionary", np.ndarray]:
        """Encode an iterable of python strings (None allowed) -> (dict, codes)."""
        arr = np.asarray(["" if s is None else s for s in strings], dtype=object)
        mask = np.asarray([s is None for s in strings], dtype=bool)
        uniq, inv = np.unique(arr.astype(str), return_inverse=True)
        codes = inv.astype(np.int32)
        codes[mask] = NULL_CODE
        return Dictionary(uniq), codes

    @staticmethod
    def from_arrow(arr) -> tuple["Dictionary", np.ndarray]:
        """Encode a pyarrow string/dictionary Array -> (dict, codes)."""
        import pyarrow.compute as pc

        d = pc.dictionary_encode(arr.combine_chunks() if hasattr(arr, "combine_chunks") else arr)
        if hasattr(d, "chunks"):
            d = d.combine_chunks()
        values = np.asarray(d.dictionary.to_pylist(), dtype=str)
        null_mask = np.asarray(d.indices.is_null())
        if len(values) == 0:
            # all-NULL column: empty dictionary, every code NULL
            return Dictionary(values), np.full(len(arr), NULL_CODE, np.int32)
        codes = d.indices.fill_null(0).to_numpy(zero_copy_only=False).astype(np.int32)
        order = np.argsort(values, kind="stable")
        rank = np.empty_like(order)
        rank[order] = np.arange(len(order))
        codes = np.where(null_mask, NULL_CODE, rank[np.clip(codes, 0, None)]).astype(np.int32)
        return Dictionary(values[order]), codes

    # -- lookups --------------------------------------------------------
    def __len__(self) -> int:
        return len(self.values)

    def code_of(self, s: str) -> int | None:
        """Exact code of s, or None if absent."""
        i = int(np.searchsorted(self.values, s))
        if i < len(self.values) and self.values[i] == s:
            return i
        return None

    def lower_bound(self, s: str) -> int:
        return int(np.searchsorted(self.values, s, side="left"))

    def upper_bound(self, s: str) -> int:
        return int(np.searchsorted(self.values, s, side="right"))

    def prefix_range(self, prefix: str) -> tuple[int, int]:
        """[lo, hi) code range of values starting with prefix (LIKE 'p%')."""
        lo = self.lower_bound(prefix)
        # upper sentinel: max code point so astral-plane chars stay in range
        hi = int(np.searchsorted(self.values, prefix + "\U0010FFFF", side="right"))
        return lo, hi

    def map_values(self, fn, out_dtype) -> np.ndarray:
        """Host-evaluate fn over distinct values -> device gather table."""
        return np.asarray([fn(v) for v in self.values], dtype=out_dtype)

    def match_mask(self, pred) -> np.ndarray:
        """Boolean per-code table for an arbitrary string predicate."""
        return np.asarray([bool(pred(v)) for v in self.values], dtype=bool)

    def value_hashes(self) -> np.ndarray:
        """Per-code uint32 hash of the VALUE (not the code).  Equal strings
        hash equal across different dictionaries, so shuffle partitioning of
        string keys (parallel/shuffle.py) co-locates matches from two tables
        without a host-side dictionary merge; collisions only affect load
        balance, never correctness."""
        if self._hash_cache is None:
            import zlib

            self._hash_cache = np.asarray(
                [zlib.crc32(v.encode("utf-8")) for v in self.values],
                dtype=np.uint32)
        return self._hash_cache

    def decode(self, codes: np.ndarray) -> np.ndarray:
        out = np.empty(len(codes), dtype=object)
        valid = codes >= 0
        out[valid] = self.values[codes[valid]]
        out[~valid] = None
        return out


def merge(a: Dictionary, b: Dictionary) -> tuple[Dictionary, np.ndarray, np.ndarray]:
    """Merge two dictionaries -> (merged, remap_a, remap_b).

    remap_x maps old codes of x into the merged dictionary; used to align two
    string columns before a device-side join/compare (the TPU analog of the
    reference comparing raw bytes in hash-join keys, src/exec/joiner.cpp).
    """
    values = np.union1d(a.values, b.values)
    remap_a = np.searchsorted(values, a.values).astype(np.int32)
    remap_b = np.searchsorted(values, b.values).astype(np.int32)
    return Dictionary(values), remap_a, remap_b


def translate_codes(codes: np.ndarray, remap: np.ndarray) -> np.ndarray:
    out = np.where(codes >= 0, remap[np.clip(codes, 0, None)], NULL_CODE)
    return out.astype(np.int32)
