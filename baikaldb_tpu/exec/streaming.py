"""Out-of-core streaming scans: double-buffered prefetch + chunk folding.

``device_table_batch`` bounds a scan by what fits in device memory at once.
This module removes that bound for the plan shape the bound hurts most —
scan -> filter -> aggregate — by running it as a FOLD over the table's
chunked segments (storage/streamchunks.py):

- eligibility (``eligible``): the whole plan must be one Project/Filter
  chain under the root down to a single AggNode, then a Project/Filter
  chain down to exactly one ScanNode.  The aggregate must be expressible
  as mergeable partials (ops/hashagg.partial_specs): no DISTINCT, no
  row-set aggregates, and no scalar (keyless) stddev/variance — the
  keyless kernel uses a mean-centered formula the sumsq partial form is
  not bit-identical to;
- the fold step is ONE jitted program: evaluate the below-agg chain over
  a chunk, partial-aggregate it, merge into the accumulator under the
  MERGE_OP protocol.  Carry and chunk are passed with
  ``donate_argnums=(0, 1)`` so the device recycles the accumulator
  in place and frees each chunk the moment it folds — steady-state
  device residency is two chunks (the one folding + the one prefetched);
- a daemon thread stages chunk i+1 (coldfs read -> decode -> device put)
  through a Queue(maxsize=1) while chunk i folds, so host I/O overlaps
  device compute.  ``stream_prefetch_wait_ms`` vs per-chunk stage time is
  the overlap measurement;
- sorted-strategy accumulators carry an overflow bit folded into the
  carry (read ONCE on host, after the loop); overflow restarts the whole
  fold with a doubled accumulator, bounded by the table's row count;
- the finalize step (partials -> user aggregates, then the remainder of
  the plan above the aggregate) runs as one more jitted program over a
  plan copy whose agg subtree is replaced by a StreamResultNode leaf.

With ``streaming_scan`` off (or any gate failing) the session takes the
resident path unchanged — the off-switch is bit-identical by construction
for everything streaming accepts.
"""

from __future__ import annotations

import copy
import time
import warnings

import jax
import jax.numpy as jnp

from ..column.batch import ColumnBatch, bucket_capacity, concat_batches
from ..expr.params import PARAMS_KEY, bind_params
from ..obs import trace
from ..ops.hashagg import (ROW_AGGS, group_aggregate_dense,
                           group_aggregate_sorted, partial_specs,
                           scalar_aggregate)
from ..parallel.agg import merge_partial_agg_specs, rewrap_partial
from ..plan.nodes import (AggNode, FilterNode, LimitNode, PlanNode,
                          ProjectNode, ScanNode, SortNode, StreamResultNode)
from ..storage.streamchunks import ChunkSource
from ..utils import metrics
from ..utils.flags import FLAGS, define
from ..utils.prefetch import staged
from . import executor

define("streaming_scan", True,
       "stream eligible scan->filter->aggregate plans over chunked "
       "segments instead of materializing the whole table on device "
       "(off-switch: the resident path, bit-identical)")
define("streaming_min_rows", 1 << 18,
       "tables below this row count always take the resident path — "
       "chunking a table that fits comfortably only adds staging cost")

# the batches-dict slot the remainder plan's StreamResultNode leaf reads
STREAM_KEY = "__stream__"

# keyless stddev/variance use hashagg's mean-centered formula; the sumsq
# partial finalize is a different float expression — not bit-identical
_SCALAR_NO_PARTIAL = ("stddev", "stddev_samp", "variance", "var_samp")

# the chain nodes a fold can leave for the finalize program (above the
# agg) / evaluate per chunk (below it) — anything else (joins, windows,
# distinct, unions, subquery sources) needs cross-chunk row visibility
_ABOVE_OK = (ProjectNode, FilterNode, SortNode, LimitNode)
_BELOW_OK = (ProjectNode, FilterNode)


def eligible(plan: PlanNode, scan_node=None):
    """-> (above_chain, agg, below_root, scan) when ``plan`` is a
    chunk-foldable single-scan aggregate, else None.  ``scan_node`` (when
    given) must be the one ScanNode the walk lands on — the session calls
    this per scan it is about to stage."""
    above: list = []
    node = plan
    while not isinstance(node, AggNode):
        if isinstance(node, _ABOVE_OK) and len(node.children) == 1:
            above.append(node)
            node = node.children[0]
        else:
            return None
    agg = node
    if agg.merge or getattr(agg, "agg_dist", ""):
        return None
    if len(agg.children) != 1:
        return None
    try:
        parts, _fin = partial_specs(agg.specs)
    except ValueError:          # ROW_AGGS have no scalar partial form
        return None
    if any(p.distinct for p in parts) or any(s.distinct for s in agg.specs):
        return None
    if not agg.key_names and any(s.op in _SCALAR_NO_PARTIAL
                                 for s in agg.specs):
        return None
    below = agg.children[0]
    node = below
    while not isinstance(node, ScanNode):
        if isinstance(node, _BELOW_OK) and len(node.children) == 1:
            node = node.children[0]
        else:
            return None
    scan = node
    if scan.children or getattr(scan, "ann", None) is not None:
        return None
    if scan_node is not None and scan is not scan_node:
        return None
    return above, agg, below, scan


def stream_source(batches: dict):
    """The (table_key, ChunkSource) riding this execution's batches, or
    None — how _run_plan recognizes a streamed execution."""
    for k, v in batches.items():
        if isinstance(v, ChunkSource):
            return k, v
    return None


def _dead_zeros(struct):
    """A concrete carry matching ``struct`` with every leaf zeroed — the
    fold identity: sel all-False (no live groups), validity all-False,
    data all-identity-zero (harmless: dead lanes never merge)."""
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), struct)


def _resize_rows(struct, cap: int):
    """Rewrite leading dimension of every leaf to ``cap`` (partial tables
    are [chunk_capacity]; the accumulator is [acc_cap])."""
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct((cap,) + tuple(s.shape[1:]), s.dtype),
        struct)


def _same_struct(a, b) -> bool:
    la, ta = jax.tree.flatten(a)
    lb, tb = jax.tree.flatten(b)
    # ShapeDtypeStruct metadata, never tracers
    # tpulint: disable-next-line=RETRACE
    return ta == tb and len(la) == len(lb) and all(
        x.shape == y.shape and x.dtype == y.dtype for x, y in zip(la, lb))


def _shift_keys(batch: ColumnBatch, shift: dict, sign: int) -> ColumnBatch:
    """The dense-strategy key rebasing the resident executor applies
    around group_aggregate_dense (-1 going in, +1 coming out)."""
    if not shift:
        return batch
    cols = list(batch.columns)
    for kn, mn in shift.items():
        i = batch.names.index(kn)
        c = cols[i]
        off = jnp.asarray(mn, c.data.dtype)
        cols[i] = c.with_data(c.data - off if sign < 0 else c.data + off)
    return ColumnBatch(batch.names, cols, batch.sel, batch.num_rows)


class StreamOverflow(RuntimeError):
    """Sorted accumulator hit capacity mid-fold; restart with more."""


class StreamRunner:
    """One plan entry's streaming executor: the jitted fold step, the
    settled accumulator shape, and the finalize/remainder program —
    cached on the entry so steady-state re-runs never re-trace."""

    def __init__(self, plan: PlanNode, table_key: str):
        parsed = eligible(plan)
        if parsed is None:          # the session gated on this already
            raise executor.ExecError("plan is not streaming-eligible")
        self.plan = plan
        self.table_key = table_key
        self.above, self.agg, self.below, self.scan = parsed
        self.parts, self.finalize = partial_specs(self.agg.specs)
        self.merge_specs = merge_partial_agg_specs(self.parts)
        self.keys = list(self.agg.key_names)
        self.shift = dict(getattr(self.agg, "key_shift", {}) or {})
        self.acc_cap = 0            # sorted strategy only; set per chunk set
        self.cap_limit = 0
        self._skey = None
        self._jit_step = None
        self._acc_struct = None
        self._fin_jit = None
        # the finalize program runs the plan ABOVE the aggregate against
        # the folded result: shallow node copies, so join caps / presort
        # state on the live plan never alias the remainder's
        rem: PlanNode = StreamResultNode(key=STREAM_KEY)
        rem.schema = getattr(self.agg, "schema", None)
        for anc in reversed(self.above):
            c = copy.copy(anc)
            c.children = [rem]
            rem = c
        self.remainder = rem

    # -- the fold step (pure/traceable) ---------------------------------
    def _partial(self, chunk: ColumnBatch, params) -> ColumnBatch:
        with bind_params(params):
            child = executor._eval(self.below, {self.table_key: chunk}, [])
        if not self.keys:
            return rewrap_partial(scalar_aggregate(child, self.parts))
        if self.agg.strategy == "dense":
            work = _shift_keys(child, self.shift, -1)
            return rewrap_partial(group_aggregate_dense(
                work, self.keys, self.agg.domains, self.parts))
        # per-chunk cap = chunk capacity: a chunk cannot carry more groups
        # than rows, so the PARTIAL can never overflow — only the merge
        # into the accumulator needs the overflow bit
        return rewrap_partial(group_aggregate_sorted(
            child, self.keys, self.parts, len(chunk)))

    def _merge(self, acc: ColumnBatch, part: ColumnBatch):
        both = concat_batches([acc, part])
        if not self.keys:
            return (rewrap_partial(scalar_aggregate(both, self.merge_specs)),
                    jnp.asarray(False))
        if self.agg.strategy == "dense":
            return (rewrap_partial(group_aggregate_dense(
                both, self.keys, self.agg.domains, self.merge_specs)),
                jnp.asarray(False))
        out, ovf = group_aggregate_sorted(both, self.keys, self.merge_specs,
                                          self.acc_cap, with_overflow=True)
        return rewrap_partial(out), ovf

    def _step(self, carry, chunk, params):
        acc, ovf = carry
        acc2, movf = self._merge(acc, self._partial(chunk, params))
        return acc2, ovf | movf

    def _finalize_batch(self, acc: ColumnBatch) -> ColumnBatch:
        from ..ops.hashagg import finalize_partials
        out = acc
        if self.keys and self.agg.strategy == "dense":
            out = _shift_keys(out, self.shift, +1)
        return finalize_partials(out, self.finalize, self.keys)

    # -- compilation bootstrap ------------------------------------------
    def _ensure_step(self, source: ChunkSource, params) -> None:
        cs = source.chunks
        if not self.cap_limit:
            self.cap_limit = bucket_capacity(max(1, cs.total_rows))
            if self.agg.strategy == "sorted" and self.keys:
                want = self.agg.max_groups or 1024
                self.acc_cap = min(bucket_capacity(want), self.cap_limit)
        skey = (cs.capacity, cs.names,
                tuple(str(cs._dtypes[n]) for n in cs.names),
                tuple(bool(cs._has_validity[n]) for n in cs.names),
                self.acc_cap)
        if self._jit_step is not None and self._skey == skey:
            return
        chunk_struct = cs.device_struct()
        # the accumulator's pytree is the FIXPOINT of the step: partial
        # columns can gain validity after one merge (count: None -> ct>0)
        # — iterate abstractly (eval_shape; nothing runs on device) until
        # the carry structure maps to itself, so the jitted fold compiles
        # exactly once
        acc_struct = jax.eval_shape(self._partial, chunk_struct, params)
        if self.keys and self.agg.strategy == "sorted":
            acc_struct = _resize_rows(acc_struct, self.acc_cap)
        ovf_struct = jax.ShapeDtypeStruct((), jnp.bool_)
        for _ in range(4):
            nxt, _o = jax.eval_shape(self._step, (acc_struct, ovf_struct),
                                     chunk_struct, params)
            if _same_struct(nxt, acc_struct):
                break
            acc_struct = nxt
        else:
            raise executor.ExecError(
                "streaming accumulator structure did not settle")
        self._acc_struct = acc_struct
        self._jit_step = jax.jit(self._step, donate_argnums=(0, 1))
        self._fin_jit = None        # acc structure moved: re-trace finalize
        self._skey = skey

    # -- the drive loop --------------------------------------------------
    def run(self, source: ChunkSource, batches: dict, qp) -> ColumnBatch:
        params = batches.get(PARAMS_KEY, ())
        cs = source.chunks
        nlive = sum(1 for l in cs.live if l)
        skipped = nlive - len(source.keep)
        if skipped:
            metrics.stream_chunks_skipped.add(skipped)
        stats = {"chunks": 0, "chunks_total": cs.n_chunks,
                 "skipped": skipped, "bytes_h2d": 0,
                 "prefetch_wait_ms": 0.0, "stage_ms": 0.0, "restarts": 0}
        with warnings.catch_warnings():
            # CPU backends decline buffer donation with a warning per
            # compile; the fold is donation-correct either way
            warnings.filterwarnings("ignore",
                                    message=".*donated buffers.*")
            while True:
                self._ensure_step(source, params)
                acc, ovf = self._fold(source, params, qp, stats)
                if not bool(jax.device_get(ovf)):
                    break
                # sorted accumulator overflowed: the only carry-dependent
                # capacity.  Grow (bounded by the table's row count — the
                # true group count can never exceed it) and re-fold
                if self.acc_cap >= self.cap_limit:
                    raise executor.ExecError(
                        "stream aggregate overflow at table row capacity")
                self.acc_cap = min(self.acc_cap * 2, self.cap_limit)
                self._jit_step = None
                metrics.stream_restarts.add(1)
                stats["restarts"] += 1
            out = self._run_finalize(acc, params)
        trace.event("stream", **{k: (round(v, 3)
                                     if isinstance(v, float) else v)
                                 for k, v in stats.items()})
        return out

    def _fold(self, source: ChunkSource, params, qp, stats):
        cs = source.chunks
        # zero chunks survived pruning: fold chunk 0 with an all-False sel
        # so the aggregate still sees its (empty) input shape — COUNT
        # renders 0, not a missing row
        dead = not source.keep
        ids = source.keep or [0]

        def load(i):
            t0 = time.perf_counter()
            dev, nbytes = cs.load_chunk(i, dead=dead)
            return dev, nbytes, (time.perf_counter() - t0) * 1e3

        # the shared double-buffer discipline (utils/prefetch.staged):
        # chunk i+1 stages on a daemon thread while chunk i folds — the
        # same staging the store daemons use for cold-segment fragment
        # folds, so both planes keep one prefetch truth
        it = staged(ids, load, name="stream-prefetch")
        carry = (_dead_zeros(self._acc_struct), jnp.asarray(False))
        try:
            for m, i in enumerate(ids):
                if qp is not None:
                    qp.beat(operator=f"StreamScan({self.table_key})",
                            chunk_no=m, chunks_total=len(ids))
                with trace.span("stream.prefetch", chunk=i) as sp:
                    t0 = time.perf_counter()
                    _i, (dev, nbytes, stage_ms) = next(it)
                    wait = (time.perf_counter() - t0) * 1e3
                    sp.set(wait_ms=round(wait, 3))
                metrics.stream_prefetch_wait_ms.observe(wait)
                metrics.stream_bytes_h2d.add(nbytes)
                stats["prefetch_wait_ms"] += wait
                stats["stage_ms"] += stage_ms
                stats["bytes_h2d"] += nbytes
                with trace.span("stream.fold", chunk=i):
                    carry = self._jit_step(carry, dev, params)
                if not dead:
                    metrics.stream_chunks.add(1)
                    stats["chunks"] += 1
            if qp is not None:
                qp.beat(chunk_no=len(ids), chunks_total=len(ids))
        finally:
            it.close()      # stops the stager and drains on early exit
        return carry

    def _run_finalize(self, acc: ColumnBatch, params) -> ColumnBatch:
        if self._fin_jit is None:
            raw = executor.compile_plan(self.remainder)

            def fin(a, ps):
                out, _flags = raw({STREAM_KEY: self._finalize_batch(a),
                                   PARAMS_KEY: ps})
                return out

            self._fin_jit = jax.jit(fin)
        with trace.span("stream.finalize"):
            return self._fin_jit(acc, params)


def run_streamed(session, entry: dict, batches: dict, qp) -> ColumnBatch:
    """Entry point from the session's _run_plan: fold the ChunkSource in
    ``batches`` and return the (padded) result batch for egress."""
    src = stream_source(batches)
    if src is None:
        raise executor.ExecError("no chunk source in batches")
    table_key, source = src
    plan = entry["plan"]
    runner = entry.get("stream_runner")
    if runner is None or runner.plan is not plan:
        runner = entry["stream_runner"] = StreamRunner(plan, table_key)
    return runner.run(source, batches, qp)
