"""Egress-stage evaluation of data-dependent string builtins.

DATE_FORMAT / FORMAT / HEX / BIN / OCT over numeric inputs cannot lower to
the one-jit device program (a device string column needs a static dictionary
at trace time).  The reference evaluates them row-wise wherever they appear
(src/expr/internal_functions.cpp); here each position gets the strongest
host-stage treatment that preserves the compiled query pipeline
(VERDICT r04 missing #4):

- SELECT list: the statement is rewritten so the kernel computes every
  numeric/temporal subexpression as hidden outputs, and the string skeleton
  is evaluated host-side over the (final-sized) result via expr/roweval.
- WHERE: comparisons are INVERTED into native predicates — monotone
  DATE_FORMAT outputs ('%Y', '%Y-%m', '%Y-%m-%d', ...) become range
  predicates on the underlying temporal value, HEX/BIN/OCT over integers
  become integer equalities — so filtering stays in the kernel at full
  selectivity.
- GROUP BY: monotone DATE_FORMAT keys become numeric bucket keys
  (year(d), year*100+month, to_days(d), unix_timestamp(d)) with a MIN()
  representative for display, so aggregation runs on the MXU.
- ORDER BY touching an egress output falls back to a host sort over the
  final result (LIMIT/OFFSET applied after it).

The daemon pushdown plane needs none of this: expr/roweval executes these
functions directly inside store fragments.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..expr.ast import AggCall, Call, ColRef, Expr, Lit, Subquery, WindowCall
from ..expr.strfmt import (boundary_bucket_start, bucket_range,
                           monotone_granularity, parse_radix_literal)
from ..plan.planner import PlanError
from ..sql.stmt import OrderItem, SelectItem, SelectStmt
from ..types import LType

EGRESS_OPS = frozenset({"date_format", "format", "hex_str", "bin", "oct"})
_RADIX = {"hex_str": 16, "bin": 2, "oct": 8}


def has_egress(e: Optional[Expr]) -> bool:
    if e is None:
        return False
    if isinstance(e, Call) and e.op in EGRESS_OPS:
        return True
    return any(has_egress(a) for a in getattr(e, "args", ())) or \
        any(has_egress(a) for a in getattr(e, "partition_by", ())) or \
        any(has_egress(a) for a, _ in getattr(e, "order_by", ()))


@dataclass
class EgressSpec:
    names: list = field(default_factory=list)        # display names
    # per original item: ("col", inner_alias) | ("expr", skeleton)
    out: list = field(default_factory=list)
    # [] = inner ORDER BY kept; else host sort over the final env
    order: list = field(default_factory=list)        # (skeleton|alias ref, asc)
    limit: Optional[int] = None
    offset: int = 0


class _Rewriter:
    def __init__(self, stmt: SelectStmt, session):
        self.stmt = stmt
        self.session = session
        self.hidden: list[Expr] = []       # inner select item exprs
        self.hidden_keys: dict = {}
        self.col_types = self._collect_types()

    def _collect_types(self) -> dict:
        """(table_label_or_None, col) -> LType over the FROM tables; used
        only to type HEX/BIN/OCT inversion targets.  Ambiguous bare names
        map to None."""
        out: dict = {}
        refs = []
        if self.stmt.table is not None and self.stmt.table.subquery is None:
            refs.append(self.stmt.table)
        for j in self.stmt.joins:
            if j.table.subquery is None:
                refs.append(j.table)
        for r in refs:
            db = r.database or self.session.current_db
            try:
                info = self.session.db.catalog.get_table(db, r.name)
            except ValueError:      # unknown table — planner reports it
                continue
            for f in info.schema.fields:
                out[(r.label, f.name)] = f.ltype
                bare = (None, f.name)
                out[bare] = None if bare in out else f.ltype
        return out

    def _type_of(self, e: Expr) -> Optional[LType]:
        if isinstance(e, ColRef):
            return self.col_types.get((e.table, e.name))
        return None

    def _hide(self, e: Expr) -> ColRef:
        k = e.key()
        idx = self.hidden_keys.get(k)
        if idx is None:
            idx = len(self.hidden)
            self.hidden.append(e)
            self.hidden_keys[k] = idx
        return ColRef(f"__c{idx}")

    def skeletonize(self, e: Expr) -> Expr:
        """Kernel-computable subtrees become hidden inner outputs; the
        remaining skeleton (egress calls + their ancestors) evaluates
        host-side via expr/roweval over the inner result."""
        if not has_egress(e):
            return self._hide(e)
        if isinstance(e, Call):
            return Call(e.op, tuple(self.skeletonize(a) for a in e.args))
        if isinstance(e, (AggCall, WindowCall)):
            raise PlanError(
                f"{e.op} over a formatted string is not supported; "
                f"aggregate the underlying value instead")
        raise PlanError(f"cannot evaluate {e!r} at result egress")

    # -- WHERE inversion --------------------------------------------------
    def invert_conjunct(self, c: Expr) -> Expr:
        """Rewrite one WHERE conjunct containing an egress call into a
        native predicate, or raise PlanError."""
        if isinstance(c, Call) and c.op == "between" and \
                has_egress(c.args[0]) and not has_egress(c.args[1]) and \
                not has_egress(c.args[2]):
            return Call("and",
                        (self.invert_conjunct(Call("ge", (c.args[0],
                                                          c.args[1]))),
                         self.invert_conjunct(Call("le", (c.args[0],
                                                          c.args[2])))))
        if isinstance(c, Call) and c.op in ("in", "not_in") and \
                has_egress(c.args[0]) and \
                not any(has_egress(a) for a in c.args[1:]):
            parts = [self.invert_conjunct(Call("eq", (c.args[0], a)))
                     for a in c.args[1:]]
            pred = parts[0]
            for p in parts[1:]:
                pred = Call("or", (pred, p))
            return Call("not", (pred,)) if c.op == "not_in" else pred
        if not (isinstance(c, Call)
                and c.op in ("eq", "ne", "lt", "le", "gt", "ge")):
            raise PlanError(
                f"{self._fn_name(c)} in WHERE is only supported as a "
                f"direct comparison with a literal")
        a, b = c.args
        op = c.op
        if has_egress(b) and not has_egress(a):
            a, b = b, a
            op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(op, op)
        if has_egress(b) or not isinstance(b, Lit) or \
                not isinstance(a, Call) or a.op not in EGRESS_OPS or \
                any(has_egress(x) for x in a.args):
            raise PlanError(
                f"{self._fn_name(c)} in WHERE is only supported as a "
                f"direct comparison with a literal")
        if a.op == "date_format":
            return self._invert_date_format(a, op, b)
        if a.op in _RADIX:
            return self._invert_radix(a, op, b)
        raise PlanError(f"{a.op.upper()} results cannot be filtered in "
                        f"WHERE; compare the underlying value")

    @staticmethod
    def _fn_name(c: Expr) -> str:
        for x in _walk(c):
            if isinstance(x, Call) and x.op in EGRESS_OPS:
                return {"hex_str": "HEX"}.get(x.op, x.op.upper())
        return "formatted output"

    @staticmethod
    def _never() -> Expr:
        return Call("eq", (Lit(0), Lit(1)))

    @staticmethod
    def _always() -> Expr:
        return Call("eq", (Lit(0), Lit(0)))

    def _invert_date_format(self, a: Call, op: str, lit: Lit) -> Expr:
        if len(a.args) != 2 or not isinstance(a.args[1], Lit):
            raise PlanError("DATE_FORMAT in WHERE needs a literal format")
        fmt = str(a.args[1].value)
        if monotone_granularity(fmt) is None:
            raise PlanError(
                f"DATE_FORMAT({fmt!r}) is not monotone in the date — "
                f"filter on the underlying value or use %Y / %Y-%m / "
                f"%Y-%m-%d style formats")
        d = a.args[0]
        s = str(lit.value)
        if op in ("eq", "ne"):
            rng = bucket_range(fmt, s)
            if rng is None:
                # not a canonical output: the binary-collation equality
                # can never hold; <> holds for every non-NULL value
                if op == "ne":
                    return Call("is_not_null", (d,))
                return self._never()
            lo, hi = rng
            if op == "eq":
                return Call("and", (Call("ge", (d, Lit(lo))),
                                    Call("lt", (d, Lit(hi)))))
            return Call("or", (Call("lt", (d, Lit(lo))),
                               Call("ge", (d, Lit(hi)))))
        # ordering against an ARBITRARY literal: find the first bucket
        # whose formatted output sorts above it (lexicographic order ==
        # chronological order for monotone formats), host-side
        strict = op in ("le", "gt")      # boundary: first output > lit
        b = boundary_bucket_start(fmt, s, strict)
        want_ge = op in ("gt", "ge")     # fmt(d) > / >= lit <=> d >= b
        if b is None:                    # every output sorts above lit
            return Call("is_not_null", (d,)) if want_ge else self._never()
        if b == "":                      # no output sorts above lit
            return self._never() if want_ge else \
                Call("is_not_null", (d,))
        return Call("ge" if want_ge else "lt", (d, Lit(b)))

    def _invert_radix(self, a: Call, op: str, lit: Lit) -> Expr:
        x = a.args[0]
        t = self._type_of(x)
        if a.op == "hex_str" and t is not None and t.is_string:
            # HEX over a string column hexes bytes — the kernel's
            # dictionary transform handles that comparison natively
            return Call(op, (a, lit))
        if t is None or not t.is_integer:
            raise PlanError(
                f"{self._fn_name(a)} in WHERE needs an integer column")
        if op not in ("eq", "ne"):
            raise PlanError(
                f"{self._fn_name(a)} output is not ordered numerically; "
                f"only = and <> comparisons are supported in WHERE")
        from ..expr.strfmt import mysql_bin, mysql_hex, mysql_oct

        s = str(lit.value)
        v = parse_radix_literal(s, _RADIX[a.op])
        canon = {"hex_str": mysql_hex, "bin": mysql_bin,
                 "oct": mysql_oct}[a.op]
        if v is None or canon(v) != s:
            # not the formatter's canonical output ('0xFF', '+ff', 'ff'):
            # binary-collation equality can never hold
            return Call("is_not_null", (x,)) if op == "ne" \
                else self._never()
        return Call(op, (x, Lit(v)))


def _walk(e: Expr):
    yield e
    for a in getattr(e, "args", ()):
        yield from _walk(a)


from ..plan.eqclasses import conjuncts as _conjuncts  # noqa: E402


def _and_all(parts: list[Expr]) -> Optional[Expr]:
    if not parts:
        return None
    out = parts[0]
    for p in parts[1:]:
        out = Call("and", (out, p))
    return out


def _display_name(e: Expr) -> str:
    if isinstance(e, ColRef):
        return e.name.split(".")[-1] if e.table is None else e.name
    return repr(e)


_BUCKETS = {
    "year": lambda d: Call("year", (d,)),
    "month": lambda d: Call("add", (Call("mul", (Call("year", (d,)),
                                                 Lit(100))),
                                    Call("month", (d,)))),
    "day": lambda d: Call("to_days", (d,)),
    "second": lambda d: Call("unix_timestamp", (d,)),
}


def extract(stmt: SelectStmt, session):
    """None when the statement uses no egress builtins; otherwise
    (inner_stmt, EgressSpec) — or PlanError when a position cannot be
    given exact semantics host-side."""
    if getattr(stmt, "_egress_done", False):
        # already rewritten: any egress call still present is one the
        # kernel lowers natively (HEX over a string column)
        return None
    exprs = ([it.expr for it in stmt.items if it.expr is not None]
             + [stmt.where, stmt.having] + list(stmt.group_by)
             + [o.expr for o in stmt.order_by])
    if not any(has_egress(e) for e in exprs):
        return None
    if stmt.distinct or stmt.union is not None:
        raise PlanError("formatted-string outputs are not supported with "
                        "DISTINCT/UNION; format in an outer query")
    rw = _Rewriter(stmt, session)

    # resolve ordinals and item-alias references so every position holds
    # the real expression (the planner does the same substitution)
    alias_expr = {}
    for it in stmt.items:
        if it.expr is not None and it.alias:
            alias_expr.setdefault(it.alias, it.expr)

    def resolve(e: Expr) -> Expr:
        if isinstance(e, Lit) and isinstance(e.value, int) \
                and not isinstance(e.value, bool) \
                and 1 <= e.value <= len(stmt.items):
            it = stmt.items[e.value - 1]
            if it.expr is not None:
                return it.expr
        if isinstance(e, ColRef) and e.table is None \
                and e.name in alias_expr:
            return alias_expr[e.name]
        return e

    group_by = [resolve(g) for g in stmt.group_by]
    order_by = [OrderItem(resolve(o.expr), o.asc) for o in stmt.order_by]

    # GROUP BY on monotone DATE_FORMAT: numeric bucket key + a MIN()
    # representative so the formatted key is printable per group
    subst: dict = {}
    new_group = []
    for g in group_by:
        if not has_egress(g):
            new_group.append(g)
            continue
        if not (isinstance(g, Call) and g.op == "date_format"
                and len(g.args) == 2 and isinstance(g.args[1], Lit)):
            raise PlanError(
                "only DATE_FORMAT group keys are supported for formatted "
                "strings; group on the underlying value instead")
        fmt = str(g.args[1].value)
        gran = monotone_granularity(fmt)
        if gran is None:
            raise PlanError(
                f"GROUP BY DATE_FORMAT({fmt!r}) is not monotone; use "
                f"%Y / %Y-%m / %Y-%m-%d style formats")
        d = g.args[0]
        if has_egress(d):
            raise PlanError("nested formatted strings in GROUP BY")
        new_group.append(_BUCKETS[gran](d))
        subst[g.key()] = Call("date_format",
                              (AggCall("min", (d,)), g.args[1]))

    def apply_subst(e: Expr) -> Expr:
        r = subst.get(e.key())
        if r is not None:
            return r
        if isinstance(e, Call):
            return Call(e.op, tuple(apply_subst(a) for a in e.args))
        if isinstance(e, AggCall):
            return AggCall(e.op, tuple(apply_subst(a) for a in e.args),
                           e.distinct)
        return e

    # WHERE: keep egress-free conjuncts, invert the rest
    parts = []
    for cj in _conjuncts(stmt.where):
        parts.append(rw.invert_conjunct(cj) if has_egress(cj) else cj)
    where = _and_all(parts)

    having = stmt.having
    if having is not None:
        having = apply_subst(having)
        if has_egress(having):
            raise PlanError("formatted strings in HAVING are not "
                            "supported; compare the underlying value")

    # SELECT list -> inner hidden items + skeletons
    spec = EgressSpec(limit=stmt.limit, offset=stmt.offset)
    for it in stmt.items:
        if it.expr is None or it.star_table is not None:
            raise PlanError("SELECT * cannot combine with formatted-"
                            "string outputs in this position")
        e = apply_subst(it.expr)
        spec.names.append(it.alias or _display_name(it.expr))
        if has_egress(e):
            spec.out.append(("expr", rw.skeletonize(e)))
        else:
            spec.out.append(("col", rw._hide(e).name))

    # ORDER BY: host sort when any key needs egress output
    host_sort = any(has_egress(apply_subst(o.expr)) for o in order_by)
    inner_order = []
    if host_sort:
        for o in order_by:
            e = apply_subst(o.expr)
            spec.order.append((rw.skeletonize(e) if has_egress(e)
                               else rw._hide(e), o.asc))
    else:
        for o in order_by:
            e = apply_subst(o.expr)
            if has_egress(e):       # unreachable, kept for clarity
                raise PlanError("formatted strings in ORDER BY")
            inner_order.append(OrderItem(rw._hide(e), o.asc))

    inner_items = [SelectItem(e, f"__c{i}")
                   for i, e in enumerate(rw.hidden)]
    inner = SelectStmt(
        items=inner_items, table=stmt.table, joins=stmt.joins,
        where=where, group_by=new_group, having=having,
        order_by=inner_order,
        limit=None if host_sort else stmt.limit,
        offset=0 if host_sort else stmt.offset,
        distinct=False, union=None, ctes=stmt.ctes)
    if not host_sort:
        spec.limit = None
        spec.offset = 0
    inner._egress_done = True
    return inner, spec


# -- batched-dispatch scatter-back (exec/dispatch.py) ----------------------
#
# The per-query egress densify (Session._egress_compact: cumsum +
# searchsorted + gather, a chain of eager device ops) is the single largest
# per-query host cost on the point-read path.  The batched dispatcher
# amortizes it across the whole group by doing the SAME compact per lane
# INSIDE the one jitted batched executable (gather_live, traced once per
# shape) and shipping every lane's dense rows in ONE fused device->host
# transfer; rebuild_clients then slices per-client host batches out of it
# with plain numpy.  A per-client eager compact here would hand the whole
# win straight back.

def gather_live(batch, cap: int):
    """Traced per-lane compact: the first ``cap`` live rows of ``batch`` in
    row order, exactly the rows ``Session._egress_compact`` would surface.
    Returns ``(datas, valids, n)`` — per-column gathered data/validity plus
    the lane's true live count (a lane with ``n > cap`` overflowed the
    static scatter budget; the dispatcher re-runs it inline)."""
    import jax.numpy as jnp

    capacity = len(batch)
    k = min(max(1, int(cap)), capacity)
    if capacity == 0:
        idx = jnp.zeros((0,), jnp.int32)
        n = jnp.int32(0)
    elif batch.sel is None or batch.live_prefix:
        # all-live (or live-prefix promise): the leading rows ARE the rows
        idx = jnp.arange(k)
        n = batch.live_count()
    else:
        cs = jnp.cumsum(batch.sel.astype(jnp.int32))
        n = cs[-1]
        idx = jnp.clip(
            jnp.searchsorted(cs, jnp.arange(1, k + 1, dtype=jnp.int32)),
            0, capacity - 1)
    datas = tuple(jnp.take(c.data, idx, axis=0, mode="clip")
                  for c in batch.columns)
    valids = tuple(None if c.validity is None
                   else jnp.take(c.validity, idx, mode="clip")
                   for c in batch.columns)
    return datas, valids, jnp.asarray(n, jnp.int32)


def column_meta(batch) -> tuple:
    """Static column metadata captured at trace time (names + per-column
    ltype/dictionary), enough for rebuild_clients to reconstitute host
    batches from the transferred leaves."""
    return (batch.names,
            tuple((c.ltype, c.dictionary) for c in batch.columns))


def rebuild_clients(meta, hdatas, hvalids, ns, n_clients: int) -> list:
    """Host side of the scatter: per-client ColumnBatches over numpy views
    of the one fused transfer.  Bit-identical to serial execution — Arrow
    conversion slices the same first ``n`` gathered rows either way.
    Returns None for lanes whose live count overflowed the scatter budget
    (``ns[i] > cap``); the dispatcher re-runs those inline."""
    import numpy as np

    from ..column.batch import Column, ColumnBatch

    names, colmeta = meta
    cap = int(hdatas[0].shape[1]) if hdatas else 0
    outs = []
    for i in range(n_clients):
        n = int(ns[i])
        if n > cap:
            outs.append(None)
            continue
        cols = [Column(hd[i], None if hv is None else hv[i], lt, d)
                for hd, hv, (lt, d) in zip(hdatas, hvalids, colmeta)]
        outs.append(ColumnBatch(names, cols, np.arange(cap) < n, n))
    return outs


def finish(spec: EgressSpec, inner_result):
    """Evaluate the skeletons over the inner result and produce the final
    (names, row tuples)."""
    from ..obs import trace

    with trace.span("egress.host_eval",
                    rows=0 if inner_result.arrow is None
                    else inner_result.arrow.num_rows):
        return _finish(spec, inner_result)


def _finish(spec: EgressSpec, inner_result):
    from ..expr.roweval import eval_row
    from ..plan.fragment import host_sort_rows

    table = inner_result.arrow
    envs = table.to_pylist() if table is not None else []
    rows = []
    for env in envs:
        vals = []
        for kind, ref in spec.out:
            vals.append(env[ref] if kind == "col" else eval_row(ref, env))
        rows.append((tuple(vals), env))
    if spec.order:
        rows = host_sort_rows(rows, spec.order)
    out = [v for v, _ in rows]
    if spec.offset:
        out = out[spec.offset:]
    if spec.limit is not None:
        out = out[:spec.limit]
    return spec.names, out
