"""Cross-query batched dispatch: coalesce concurrent point queries into one
device batch per tick.

PR 3's auto-parameterized plan cache means thousands of concurrent point
queries of the same statement shape share ONE compiled executable — but each
still paid its own device dispatch, egress densify, and GIL round-trip.
*Tailwind* (PAPERS.md) frames the fix: admit concurrent queries into a
combiner that batches them onto the accelerator; *Query Processing on Tensor
Computation Runtimes* motivates keeping the hot path a handful of LARGE
tensor-runtime launches instead of per-client small ones.

The dispatcher sits between the session layer and the jitted plan executor:

- **Group key**: queries coalesce when they hit the same plan-cache entry
  (the paramize lookup key: canonical statement structure + pinned values),
  the same scan shapes (table, version, capacity bucket — PR 1's buckets),
  and the same plan signature.  Members differ ONLY in their bound param
  feeds, so one program serves the whole group.
- **Inline bypass**: a query whose group is idle (nothing queued, nothing
  in flight) executes inline on its own thread — single-in-flight queries
  pay zero added latency.  Only genuine concurrency queues.
- **Combiner tick**: the first queued waiter becomes the group's leader and
  sleeps for ``batch_dispatch_tick_ms`` (or until the group fills to
  ``batch_dispatch_max_group``), then stacks the pending param feeds along
  a new leading client axis, pads the group to a power-of-two size (so
  group-size variation forks O(log max_group) executables, not O(sizes)),
  and runs ONE ``jax.vmap``-batched executable: every lane evaluates the
  same plan against the same table batches with its own params.
- **Scatter-back**: the per-lane egress compact is FUSED into the batched
  program (``exec.egress.gather_live``), so a tick costs one jit call plus
  ONE fused device->host transfer; ``exec.egress.rebuild_clients`` then
  slices per-client host batches out of it with plain numpy — bit-identical
  to what a serial run's ``_egress_compact`` would produce.
- **Admission**: the per-group queue is bounded (``batch_dispatch_queue_max``;
  overflow raises the typed :class:`DispatchOverload`), and the session
  layer's qos gate (utils/qos.py, now per-user/per-table token buckets)
  sheds load BEFORE anything enqueues — overload degrades to bounded
  queueing + typed rejection, never collapse.
- **Fallback valve**: any combiner failure (a plan the vmap lowering cannot
  express, an injected ``dispatch.combine`` fault) lands every member —
  leader included — back on its own inline execution path, preserving
  exactly-once results per client.

Trace seams: ``batch.enqueue`` (waiter-side, duration = queue wait),
``batch.combine`` (leader, group/padded/compiled attrs), ``batch.scatter``.
All ride obs/trace.py's no-op singleton when tracing is off.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

import numpy as np

from ..obs import progress, trace
from ..utils import metrics
from ..utils.flags import FLAGS, define
from ..utils.qos import RejectedError

define("batch_dispatch", True,
       "cross-query batched dispatch: concurrent point queries hitting the "
       "same plan-cache group run as ONE vmapped device batch per combiner "
       "tick (param feeds stacked along a leading client axis); single-in-"
       "flight queries bypass the queue entirely.  0 restores per-query "
       "dispatch")
define("batch_dispatch_tick_ms", 1.5,
       "combiner latency budget: how long a group leader waits for more "
       "members before running the batch (the admission tick)")
define("batch_dispatch_max_group", 256,
       "combine at most this many queries per tick; a full group fires "
       "immediately without waiting out the tick")
define("batch_dispatch_queue_max", 1024,
       "bounded per-group queue: arrivals beyond this many waiting queries "
       "get a typed DispatchOverload rejection instead of queueing "
       "unboundedly")
define("batch_dispatch_wait_s", 120.0,
       "waiter safety net: a member falls back to inline execution if its "
       "combine result does not arrive within this window (covers a leader "
       "paying a multi-second first compile)")
define("batch_dispatch_cache", 64,
       "batched executables kept by the dispatcher (distinct (statement "
       "group, shapes, padded group size) triples)")
define("batch_dispatch_scatter_rows", 128,
       "static per-lane scatter budget: the batched executable returns up "
       "to this many live rows per client (the egress compact fused into "
       "the program); a lane returning more re-runs inline")


class DispatchOverload(RejectedError):
    """The group's queue is full: typed admission rejection (the reference's
    reject strategy under overload — the client sees a MySQL error, the
    server never queues unboundedly)."""


class CombineFallback(Exception):
    """Internal control flow: this member must execute inline (combiner
    failed / timed out / an injected fault abandoned the tick).  The session
    catches it and runs its own ``_run_plan``."""


# cached master switch (the per-SELECT eligibility check must not take the
# flag-registry lock; the ``tracing`` off-switch discipline)
_ON = bool(FLAGS.batch_dispatch)


def _refresh(value=None) -> None:
    global _ON
    _ON = bool(FLAGS.batch_dispatch if value is None else value)


FLAGS.on_change("batch_dispatch", _refresh)


def enabled() -> bool:
    return _ON


class _Waiter:
    """One queued query: its bound param feed + the rendezvous."""

    __slots__ = ("params", "done", "out", "err", "t0", "group")

    def __init__(self, params):
        self.params = params
        self.done = threading.Event()
        self.out = None             # compacted ColumnBatch on success
        self.err = None             # exception to re-raise on this thread
        self.t0 = time.perf_counter()
        self.group = 0              # occupancy, filled by the leader


class _Group:
    """Transient queue of waiters for one (statement, shapes) group; lives
    only while members wait — the leader pops it when the tick fires."""

    __slots__ = ("pending", "filled")

    def __init__(self):
        self.pending: list[_Waiter] = []
        self.filled = threading.Event()


class BatchDispatcher:
    """One per Database: engine-wide, so queries from DIFFERENT sessions
    (connections) coalesce — that is the whole point."""

    # ranked below store.table_lock(10): the combiner only holds its lock
    # for map bookkeeping — never across device work or store calls
    RANK = 4

    def __init__(self):
        # the lockset witness (debug_guards) asserts the dispatcher maps
        # are only touched under this lock
        from ..analysis.runtime import GuardedLock
        self._mu = GuardedLock("dispatch.combine_mu", rank=self.RANK)
        self._groups: dict = {}          # group_key -> _Group (queued only)
        self._inflight: dict = {}        # group_key -> runs in flight
        # ck_base -> the plan object every batched compile of this statement
        # group traces from (the first leader's; join-cap growth mutates it)
        self._plans: OrderedDict = OrderedDict()
        # (ck_base, padded_group) -> (jitted fn, raw, meta, publishable)
        # — LRU-bounded; ``publishable`` is the unjitted batched callable
        # the AOT publisher exports, None for AOT-loaded pairs
        self._compiled: OrderedDict = OrderedDict()
        # batched-executable keys whose AOT artifact's baked caps
        # overflowed on live data: never re-load them this process
        self._aot_bad: set = set()
        # exact group-size histogram for information_schema.dispatcher
        self.occupancy: dict[int, int] = {}

    # -- introspection (information_schema.dispatcher) ---------------------
    def queue_depth(self) -> int:
        with self._mu:
            return sum(len(g.pending) for g in self._groups.values())

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "queue_depth": sum(len(g.pending)
                                   for g in self._groups.values()),
                "live_groups": len(self._groups),
                "inflight": sum(self._inflight.values()),
                "occupancy": dict(self.occupancy),
                "compiled": len(self._compiled),
            }

    # -- admission ---------------------------------------------------------
    def run(self, run_inline, group_key, ck_base, entry, batches):
        """Execute one query through the dispatcher.

        ``run_inline``: zero-arg closure running the session's own
        ``_run_plan`` (the bypass and fallback path).  Returns the compacted
        result ColumnBatch.  Raises :class:`DispatchOverload` when the
        group's queue is full; :class:`CombineFallback` never escapes
        (handled internally by re-running inline)."""
        from ..expr.params import PARAMS_KEY
        with self._mu:
            g = self._groups.get(group_key)
            if g is None and not self._inflight.get(group_key):
                # idle group: run inline on this thread, zero added latency
                self._inflight[group_key] = 1
                w = None
                leader = False
            else:
                if g is None:
                    g = self._groups[group_key] = _Group()
                if len(g.pending) >= max(1, int(
                        FLAGS.batch_dispatch_queue_max)):
                    metrics.qos_rejections.add(1)
                    raise DispatchOverload(
                        "dispatcher queue full for this statement group "
                        f"({len(g.pending)} waiting)")
                w = _Waiter(batches[PARAMS_KEY])
                g.pending.append(w)
                leader = len(g.pending) == 1
                if len(g.pending) >= max(2, int(
                        FLAGS.batch_dispatch_max_group)):
                    # full group fires now AND rotates out of the registry,
                    # so later arrivals form a fresh group under a new
                    # leader — max_group is a per-tick cap, not a hint
                    g.filled.set()
                    if self._groups.get(group_key) is g:
                        del self._groups[group_key]
        if w is None:
            metrics.dispatch_inline.add(1)
            try:
                return run_inline()
            finally:
                self._release(group_key)
        if leader:
            return self._lead(g, group_key, ck_base, entry, batches,
                              run_inline)
        return self._wait(w, run_inline)

    def _release(self, group_key) -> None:
        with self._mu:
            n = self._inflight.get(group_key, 0) - 1
            if n > 0:
                self._inflight[group_key] = n
            else:
                self._inflight.pop(group_key, None)

    # -- member side -------------------------------------------------------
    def _wait(self, w: _Waiter, run_inline):
        qp = progress.current()
        with trace.span("batch.enqueue") as sp:
            # sliced wait: each slice is a progress beat and a KILL
            # cancellation point (the dispatch queue is a pure read path —
            # abandoning the rendezvous has no side effects; the leader's
            # combined run just carries one unread lane)
            deadline = time.perf_counter() + \
                float(FLAGS.batch_dispatch_wait_s)
            while True:
                remaining = deadline - time.perf_counter()
                ok = w.done.wait(timeout=min(0.05, max(0.0, remaining)))
                wait_ms = (time.perf_counter() - w.t0) * 1e3
                qp.beat(phase="exec.queued", queue_wait_ms=wait_ms)
                if ok or remaining <= 0:
                    break
            sp.set(queue_wait_ms=round(
                (time.perf_counter() - w.t0) * 1e3, 3), group=w.group)
        if not ok or isinstance(w.err, CombineFallback):
            metrics.dispatch_fallbacks.add(1)
            return run_inline()
        if w.err is not None:
            raise w.err
        return w.out

    # -- leader side -------------------------------------------------------
    def _lead(self, g_mine: _Group, group_key, ck_base, entry, batches,
              run_inline):
        # the tick: wait out the latency budget (or a full group) so
        # followers can pile on, then pop the group and combine
        g_mine.filled.wait(timeout=max(0.0, float(
            FLAGS.batch_dispatch_tick_ms)) / 1e3)
        with self._mu:
            if self._groups.get(group_key) is g_mine:
                del self._groups[group_key]
            ws = g_mine.pending
            self._inflight[group_key] = \
                self._inflight.get(group_key, 0) + 1
        try:
            now = time.perf_counter()
            G = len(ws)
            for m in ws:
                m.group = G
                metrics.queue_wait_ms.observe((now - m.t0) * 1e3)
            if G == 1:
                # nobody joined during the tick: plain inline run
                metrics.dispatch_inline.add(1)
                return run_inline()
            from ..chaos.failpoint import FailpointPanic
            try:
                outs = self._combine(ws, ck_base, entry, batches)
            except (Exception, FailpointPanic) as e:  # noqa: BLE001 — the
                #   valve: ANY combiner failure (incl. an injected
                #   FailpointPanic, which has no daemon to crash at the
                #   frontend seam) degrades every member to inline
                #   execution; exactly-once is preserved because no result
                #   was delivered yet.  KeyboardInterrupt/SystemExit flow.
                metrics.count_swallowed("dispatch.combine")
                fb = CombineFallback(f"{type(e).__name__}: {e}")
                for m in ws[1:]:
                    m.err = fb
                    m.done.set()
                metrics.dispatch_fallbacks.add(1)
                return run_inline()
            for m, out in zip(ws[1:], outs[1:]):
                m.out = out
                m.done.set()
            if isinstance(ws[0].err, CombineFallback):
                metrics.dispatch_fallbacks.add(1)   # own-lane overflow
                return run_inline()
            if ws[0].err is not None:
                raise ws[0].err     # this lane's own per-client error
            return outs[0]
        finally:
            self._release(group_key)

    def _combine(self, ws, ck_base, entry, batches):
        """Stack the group's param feeds, run ONE batched executable —
        plan evaluation AND the per-lane egress compact fused into a single
        jitted program (exec/egress.gather_live) — then rebuild per-client
        host batches from one fused transfer.  The leader's thread does all
        of it; under the GIL the combiner IS the serialization point, so
        its critical path must be a fixed handful of Python steps, not a
        per-client chain of eager device ops."""
        import jax

        from ..chaos import failpoint
        from ..expr.params import PARAMS_KEY
        from ..plan.nodes import ScalarSourceNode
        from ..plan.planner import PlanError
        from . import egress as egress_mod
        from .executor import compile_plan

        G = len(ws)
        gpad = max(2, 1 << (G - 1).bit_length())
        feeds = [m.params for m in ws] + [ws[0].params] * (gpad - G)
        # host-side stack: bind() leaves are numpy, so the whole group's
        # feed ships to the device in ONE transfer at the jit call below
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *feeds)
        table_batches = {k: v for k, v in batches.items()
                        if k != PARAMS_KEY}
        with self._mu:
            plan = self._plans.get(ck_base)
            if plan is None:
                self._plans[ck_base] = plan = entry["plan"]
                while len(self._plans) > max(1, int(
                        FLAGS.batch_dispatch_cache)):
                    self._plans.popitem(last=False)
            self.occupancy[G] = self.occupancy.get(G, 0) + 1
        metrics.batched_groups.add(1)
        metrics.group_occupancy.observe(float(G))
        from ..utils import compilecache
        from .executor import AotRawShim, flag_meta_of

        scap = max(1, int(FLAGS.batch_dispatch_scatter_rows))
        # AOT artifact identity for this batched program: the statement
        # group + plan signature (ck_base), the padded group size and
        # scatter budget, the input skeleton (incl. dictionary content)
        # and the topology.  Derived lazily — a warm tick that hits the
        # in-memory pair never pays the fingerprint walk.
        aot_key = None

        def get_aot_key():
            nonlocal aot_key
            if aot_key is None and compilecache.AOT.enabled():
                aot_key = compilecache.aot_key(
                    "batched", entry.get("plan_sig"),
                    (str(ck_base), gpad, scap),
                    compilecache.input_fingerprint((table_batches,
                                                    stacked)))
            return aot_key

        # AOT pairs pin the EXACT store versions they loaded under: jit
        # retraces when a dictionary's content changes (pytree aux), a
        # deserialized program cannot — in-bucket DML must re-derive the
        # artifact key instead of reusing a stale-dictionary executable
        vk = tuple(sorted(entry.get("versions", {}).items()))

        def _fresh_batched():
            # a publish-only clone of the combine program: the background
            # export re-traces it, so it must own its OWN run_local
            # closure and meta list — tracing the live pair's would mutate
            # state a concurrent tick is reading
            raw2 = compile_plan(plan)
            meta2: list = []

            def batched2(tb, sp_, _raw=raw2, _meta=meta2, _cap=scap):
                def one(p):
                    b = dict(tb)
                    b[PARAMS_KEY] = p
                    out, flags = _raw(b)
                    _meta.clear()
                    _meta.append(egress_mod.column_meta(out))
                    return egress_mod.gather_live(out, _cap), flags
                return jax.vmap(one)(sp_)
            return batched2

        t0 = time.perf_counter()
        with trace.span("batch.combine", group=G, padded=gpad) as sp:
            if failpoint.ENABLED:
                if failpoint.hit("dispatch.combine", group=G):
                    # drop: abandon this tick — members fall back inline
                    raise CombineFallback("dispatch.combine dropped")
            for _ in range(int(FLAGS.join_retry_max) + 1):
                ck = (ck_base, gpad)
                with self._mu:
                    pair = self._compiled.get(ck)
                    if pair is not None and pair[3] is not None \
                            and pair[3] != vk:
                        del self._compiled[ck]      # stale AOT pair
                        pair = None
                    elif pair is not None:
                        self._compiled.move_to_end(ck)
                    # membership read under the same lock as the .add in
                    # the fallback path — combiner ticks race session
                    # threads here
                    aot_ok = aot_key not in self._aot_bad
                if pair is None and compilecache.AOT.enabled() \
                        and get_aot_key() is not None and aot_ok:
                    art = compilecache.AOT.load(aot_key)
                    if art is not None and isinstance(
                            (art.extra or {}).get("egress_meta"), tuple):
                        # the vmapped program + its egress column meta
                        # round-trip from the artifact: zero traces
                        pair = (lambda tb, sp_, _art=art: _art.run((tb, sp_)),
                                AotRawShim(art.flag_meta),
                                [art.extra["egress_meta"]], vk)
                        with self._mu:
                            self._compiled[ck] = pair
                if pair is None:
                    raw = compile_plan(plan)
                    meta: list = []          # filled at trace time

                    def batched(tb, sp_, _raw=raw, _meta=meta, _cap=scap):
                        def one(p):
                            b = dict(tb)
                            b[PARAMS_KEY] = p
                            out, flags = _raw(b)
                            _meta.clear()
                            _meta.append(egress_mod.column_meta(out))
                            return egress_mod.gather_live(out, _cap), flags
                        return jax.vmap(one)(sp_)

                    pair = (jax.jit(batched), raw,  # tpulint: disable=RETRACE
                            meta, None)
                    with self._mu:
                        self._compiled[ck] = pair
                        while len(self._compiled) > max(1, int(
                                FLAGS.batch_dispatch_cache)):
                            self._compiled.popitem(last=False)
                fn, raw, meta, _aot_vk = pair
                traces_before = raw.trace_count[0]
                (gdatas, gvalids, ns_dev), flags = fn(table_batches, stacked)
                compiled_now = raw.trace_count[0] > traces_before
                if compiled_now:
                    cms = (time.perf_counter() - t0) * 1e3
                    metrics.compile_ms.observe(cms)
                    sp.set(compiled=True)
                    # device accounting: a batched executable is its own
                    # compile (vmapped over the padded group) — record
                    # under kind="batched" with the group size in the
                    # shape so fleet dashboards see the fork-out
                    if compilecache.EXECUTABLES.enabled():
                        compilecache.EXECUTABLES.record_compile(
                            "batched",
                            str(entry.get("text") or "<unnamed>"),
                            entry.get("plan_sig"), f"group={gpad}", cms,
                            fn, (table_batches, stacked))
                grew = False
                # ONE fused transfer for every lane of every overflow flag
                host_flags = jax.device_get(flags)
                for node, flag in zip(raw.join_order, host_flags):
                    fl = np.asarray(flag)
                    if isinstance(node, ScalarSourceNode) \
                            or getattr(node, "aot_scalar", False):
                        for i in np.nonzero(fl[:G] > 1)[0]:
                            ws[int(i)].err = PlanError(
                                "Subquery returns more than 1 row")
                        continue
                    needed = int(fl.max())
                    if needed > (node.cap or 0):
                        node.cap = max(16, 1 << (needed - 1).bit_length())
                        grew = True
                if not grew:
                    if compiled_now and not isinstance(raw, AotRawShim) \
                            and get_aot_key() is not None:
                        compilecache.AOT.publish_async(
                            aot_key, "batched",
                            str(entry.get("text") or "<unnamed>"),
                            entry.get("plan_sig"),
                            lambda a, _b=_fresh_batched(): _b(a[0], a[1]),
                            (table_batches, stacked),
                            ((gdatas, gvalids, ns_dev), flags),
                            flag_meta_of(raw.join_order),
                            extra={"egress_meta": meta[0]})
                    break
                if isinstance(raw, AotRawShim):
                    # live data outgrew the artifact's baked caps: drop it
                    # for this process and compile fresh
                    with self._mu:
                        self._aot_bad.add(aot_key)
                    metrics.aot_cache_fallbacks.add(1)
                with self._mu:
                    self._compiled.pop(ck, None)   # caps changed: re-trace
            else:
                raise RuntimeError(
                    "join output cap still overflowing after retries")
            metrics.dispatch_tick_ms.observe(
                (time.perf_counter() - t0) * 1e3)
        with trace.span("batch.scatter", group=G):
            # the one egress transfer for the whole group
            hdatas, hvalids, ns = jax.device_get((gdatas, gvalids, ns_dev))
            outs = egress_mod.rebuild_clients(meta[0], hdatas, hvalids,
                                              ns, G)
        # a lane that overflowed the static scatter budget re-runs inline
        # (rare: a groupable point query returning > scatter_rows rows)
        fb = None
        for m, o in zip(ws, outs):
            if o is None and m.err is None:
                if fb is None:
                    fb = CombineFallback("scatter budget overflow")
                    metrics.count_swallowed("dispatch.scatter_overflow")
                m.err = fb
        # scalar-subquery / overflow errors claim their lanes; the rest
        # carry their compacted host batch
        return [None if m.err is not None else o
                for m, o in zip(ws, outs)]


# lockset witness enrollment: debug_guards=log|disallow installs
# per-attribute assertions from the static ownership map (the dispatcher
# is the canonical witnessed class — its maps are mutated by every
# session thread plus the combiner)
from ..analysis.runtime import LOCK_RANKS as _LOCK_RANKS  # noqa: E402
from ..analysis.runtime import register_witness  # noqa: E402

register_witness(BatchDispatcher,
                 "baikaldb_tpu/exec/dispatch.py:BatchDispatcher")
# rank visible at import (docs/LINT.md table is pinned against the
# registry by test_lint.py without constructing a dispatcher)
_LOCK_RANKS.setdefault("dispatch.combine_mu", BatchDispatcher.RANK)
