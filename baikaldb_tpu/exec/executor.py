"""Plan executor: lower the plan IR to one jit-compiled kernel pipeline.

This replaces BOTH of the reference's execution modes: the volcano
open/get_next interpreter (include/exec/exec_node.h:140-145) and the Acero
declaration path (GlobalArrowExecutor::execute,
src/runtime/arrow_io_excutor.cpp:265).  The whole query — scan filters,
projections, group-by, joins, sort — traces into a single XLA program, so
operator boundaries cost nothing: XLA fuses scan+filter+aggregate into a few
HBM passes (the fusion the reference hopes Acero's pipelining approximates).

Static-shape discipline: join/limit caps are compile-time constants; join
overflow is detected via returned flags and retried with doubled caps
(recompile), the analog of the reference re-fetching on region-version change
(fetcher_store.cpp handle_version_old).
"""

from __future__ import annotations

from dataclasses import replace as dreplace
from typing import Callable

import jax
import jax.numpy as jnp

from ..column.batch import Column, ColumnBatch
from ..expr.ast import ColRef, Lit
from ..expr.compile import eval_expr, eval_output, eval_predicate, infer_type
from ..expr.params import PARAMS_KEY, bind_params
from ..ops import join as join_ops
from ..ops.compact import compact, head
from ..ops.hashagg import (AggSpec, MERGE_OP, finalize_partials,
                           group_aggregate_dense, group_aggregate_sorted,
                           partial_specs, scalar_aggregate)
from ..ops.sort import SortKey, sort_batch, top_k
from ..ops.compact import shrink
from ..plan.nodes import (AggNode, DistinctNode, ExchangeNode, FilterNode,
                          JoinNode, LimitNode, MembershipNode, MultiJoinNode,
                          PlanNode, ProjectNode, ScalarSourceNode, ScanNode,
                          ShrinkNode, SortNode, StreamResultNode, UnionNode,
                          ValuesNode, WindowNode)
from ..column.batch import concat_batches
from ..parallel.mesh import AXIS, shard_map
from ..types import LType


class ExecError(RuntimeError):
    pass


from ..utils import metrics  # noqa: E402
from ..utils.flags import FLAGS, define  # noqa: E402

# Pushed-down fragments (exec/fragments.py) merge daemon partials HOST-side
# under parallel.agg.WIRE_MERGE while this executor merges mesh partials
# under ops.hashagg.MERGE_OP — the same semantic in two planes.  Pin them
# at import: a kind whose wire merge drifted from its device merge would
# make pushed results silently diverge from the image path (the
# off-switch's bit-identity guarantee), so fail loudly instead.
from ..parallel.agg import WIRE_MERGE as _WIRE_MERGE  # noqa: E402

_drift = {k for k, op in _WIRE_MERGE.items() if MERGE_OP.get(k) != op}
if _drift:
    raise ExecError(
        f"wire/device partial-merge drift for agg kinds {sorted(_drift)}: "
        "parallel.agg.WIRE_MERGE must match ops.hashagg.MERGE_OP")
del _drift

import threading  # noqa: E402

# set (thread-locally) by utils/compilecache._analyze while it AOT
# re-lowers a cached executable for cost accounting: jax traces on the
# calling thread, and that bookkeeping trace must not count as plan-cache
# churn in trace_count / metrics.xla_retraces
ACCOUNTING_TRACE = threading.local()

define("radix_join_buckets", 0,
       "hash-partition sort-join builds into this many buckets (power of "
       "two; 0 = off): batched per-bucket sorts replace the one global "
       "bitonic sort — the TPU-shaped hash join (ops/radix.py)")
define("radix_join_min_build", 65536,
       "radix-partition joins only engage for builds at least this large")


class AotFlagShim:
    """Stands in for one plan node in the flag order of an AOT-loaded
    executable: the artifact records each overflow flag's settled capacity
    (and whether it is a scalar-subquery count) at publish time, and the
    session's retry loop checks live flags against these.  A shim whose
    cap is exceeded cannot grow (the capacity is baked into the exported
    program) — the session falls back to compile-from-scratch instead."""

    __slots__ = ("cap", "aot_scalar", "kind")

    def __init__(self, cap, scalar: bool, kind: str):
        self.cap = cap
        self.aot_scalar = bool(scalar)
        self.kind = kind


def flag_meta_of(join_order) -> list:
    """The publish-time snapshot of a settled executable's flag order:
    [(cap, is_scalar, node-kind), ...] — everything an AOT run needs to
    interpret the returned overflow flags without the plan objects."""
    out = []
    for node in join_order:
        cap = getattr(node, "cap", None)
        out.append({"cap": None if cap is None else int(cap),
                    "scalar": isinstance(node, ScalarSourceNode),
                    "kind": type(node).__name__})
    return out


class AotRawShim:
    """Quacks like :func:`compile_plan`'s raw closure for the session /
    dispatcher retry loops: ``trace_count`` never moves (an AOT run never
    compiles — warm_compiles stays 0 by construction) and ``join_order``
    carries :class:`AotFlagShim` entries in the artifact's flag order."""

    def __init__(self, flag_meta: list):
        self.join_order = [AotFlagShim(m.get("cap"), m.get("scalar", False),
                                       m.get("kind", "?"))
                           for m in (flag_meta or [])]
        self.trace_order: list = []
        self.trace_count = [0]


class _CapBox:
    """A retryable capacity knob that rides the join-overflow protocol:
    the session retry loop grows ``.cap`` to the reported need and
    re-traces (used for the radix join's per-bucket width and the fused
    exchange's per-input shuffle capacities).  ``kind``/``site`` label the
    knob for shuffle-retry accounting and the mpp.* trace spans."""

    def __init__(self, cap=None, kind: str = "width", site: str = ""):
        self.cap = cap
        self.kind = kind
        self.site = site


def compile_plan(plan: PlanNode, trace: bool = False, mesh=None) -> Callable:
    """-> fn(table_batches: dict) -> (ColumnBatch, overflow_flags[, counts]).

    The returned fn is pure/traceable; wrap in jax.jit by the session.  Join
    caps live on the plan nodes (mutated by the retry loop, forcing re-trace).
    With trace=True the result also carries per-node live-row counts — the
    EXPLAIN ANALYZE feed (reference: TraceNode tree, include/runtime/
    trace_state.h, surfaced via EXPLAIN FORMAT=analyze).

    With ``mesh`` set, the plan must have been through plan/distribute.py:
    the WHOLE query runs inside one shard_map over the mesh's row axis —
    table batches arrive shard-partitioned, ExchangeNodes lower to
    all_gather/all_to_all over ICI, partial aggregates merge via
    psum/pmin/pmax, and the final (replicated) result leaves the program.
    This is the MPP fragment DAG (SURVEY §3.2) as a single XLA program."""

    join_order: list = []
    trace_order: list = []
    n_shards = int(mesh.devices.size) if mesh is not None else 0
    # Python-side-effect trace counter: run_local's body only executes when
    # jax (re)traces — a steady-state cached execution never enters it.  The
    # session's compile telemetry (metrics.xla_retraces / compile_ms) and the
    # bucketing regression tests key off this.
    trace_count = [0]

    def run_local(batches: dict):
        if not getattr(ACCOUNTING_TRACE, "active", False):
            trace_count[0] += 1
            metrics.xla_retraces.add(1)
        overflows: list = []
        counts: list = []
        trace_order.clear()
        ctx = (overflows, counts if trace else None, trace_order, n_shards)
        # hoisted-literal params (plan/paramize.py) ride the batches pytree;
        # Param expr nodes read their slots from this trace-scoped binding
        with bind_params(batches.get(PARAMS_KEY, ())):
            out = _sub(plan, batches, overflows, ctx)
        # nodes are host objects: expose them on the closure (filled at trace
        # time), return only the traced flags
        join_order.clear()
        join_order.extend(n for n, _ in overflows)
        flags = tuple(f for _, f in overflows)
        if n_shards:
            # flags carry NEEDED capacities: the retry must satisfy the
            # hungriest shard, so reduce with pmax
            flags = tuple(jax.lax.pmax(jnp.asarray(f), AXIS) for f in flags)
        if trace:
            return out, flags, tuple(counts)
        return out, flags

    if mesh is None:
        run = run_local
    else:
        from jax.sharding import PartitionSpec as P

        def run(batches: dict):
            # per-leaf in_specs (the pjit per-leaf in_axis_resources shape):
            # table batches shard over the row axis, the hoisted-literal
            # params feed replicates P() — scalar params ride the
            # partitioned batches pytree, so ONE mesh executable serves
            # every literal variant instead of baking each literal into
            # its own shard_map program.  Built per call from the batch
            # keys; jit caches on the pytree structure, so steady state
            # never reconstructs a trace.
            specs = {k: (P() if k == PARAMS_KEY else P(AXIS))
                     for k in batches}
            smapped = shard_map(run_local, mesh=mesh, in_specs=(specs,),
                                out_specs=P(), check_vma=False)
            return smapped(batches)

    run.join_order = join_order
    run.trace_order = trace_order
    run.trace_count = trace_count
    return run


def _presort_order(node, batches: dict, expected_len: int):
    """The host-precomputed sort permutation fed by the session's
    walk_presort, or None when absent / the input's positions are not the
    base table's (access-path gather, shard slice)."""
    pkey = getattr(node, "presort_input", None)
    order = batches.get(pkey) if pkey else None
    if order is not None and len(order) != expected_len:
        return None
    return order


def _eval_traced(node: PlanNode, batches: dict, ctx):
    overflows, counts, trace_order, n_shards = ctx
    out = _eval(node, batches, overflows, ctx)
    trace_order.append(node)
    c = out.live_count()
    if n_shards and getattr(node, "dist", None) == "shard":
        c = jax.lax.psum(c, AXIS)
    counts.append(c)
    return out


def _eval(node: PlanNode, batches: dict, overflows: list, ctx=None) -> ColumnBatch:
    if isinstance(node, ScanNode):
        b = batches[node.table_key]
        names = tuple(f"{node.label}.{c}" for c in node.columns)
        cols = [b.column(c) for c in node.columns]
        # bucket-padded store batches arrive with a live-prefix sel mask;
        # the static promise survives the scan (and dies at the first
        # and_sel), letting compact skip its gather on unfiltered scans
        out = ColumnBatch(names, cols, b.sel, b.num_rows,
                          live_prefix=b.live_prefix)
        if node.pushed_filter is not None:
            out = out.and_sel(eval_predicate(node.pushed_filter, out))
        return out

    if isinstance(node, FilterNode):
        child = _sub(node.child(), batches, overflows, ctx)
        return child.and_sel(eval_predicate(node.pred, child))

    if isinstance(node, ShrinkNode):
        child = _sub(node.child(), batches, overflows, ctx)
        if node.cap is None:
            # first trace: guess a 16x cut; the flag reports the true live
            # count, so one retry lands exactly when the guess is short
            node.cap = max(1024, 1 << (max(1, len(child) // 16)
                                       - 1).bit_length())
        out, needed = shrink(child, node.cap)
        overflows.append((node, needed))
        return out

    if isinstance(node, ProjectNode):
        child = _sub(node.child(), batches, overflows, ctx)
        n = len(child)
        cols = []
        for e in node.exprs:
            c = eval_output(e, child)
            cols.append(_broadcast(c, n))
        return ColumnBatch(tuple(node.names), cols, child.sel, child.num_rows)

    if isinstance(node, JoinNode):
        left = _sub(node.children[0], batches, overflows, ctx)
        right = _sub(node.children[1], batches, overflows, ctx)
        if node.how == "cross":
            if node.cap is None:
                node.cap = max(1, len(left) * len(right))
            out, ovf = join_ops.cross_join(left, right, cap=node.cap)
        elif node.neq is not None and node.how in ("semi", "anti"):
            # EXISTS + one <> residual: range counts, no expansion; with a
            # host-precomputed build permutation, no on-device sort either
            out, ovf = join_ops.semi_join_neq(left, node.left_keys, right,
                                              node.right_keys, node.neq[0],
                                              node.neq[1], how=node.how,
                                              order=_presort_order(
                                                  node, batches, len(right)))
        elif node.strategy == "dense":
            # unique-build PK-FK join: scatter/gather over the dense key
            # domain(s), output keeps the probe's shape (no overflow
            # protocol)
            out, ovf = join_ops.dense_join(left, node.left_keys, right,
                                           node.right_keys,
                                           list(node.dense_lo),
                                           list(node.dense_span),
                                           how=node.how)
        else:
            if node.cap is None:
                # key-FK joins emit at most max(sides) rows; true many-to-many
                # expansion beyond that reports its exact need via the flag
                node.cap = max(1, len(left), len(right))
            nb = int(FLAGS.radix_join_buckets)
            presort = _presort_order(node, batches, len(right))
            float_keys = any(right.column(k).ltype.is_float
                             for k in node.right_keys
                             if k in right.names)
            use_radix = (nb >= 2 and (nb & (nb - 1)) == 0 and
                         presort is None and not float_keys and
                         not getattr(node, "build_sorted", False) and
                         len(right) >= int(FLAGS.radix_join_min_build))
            if use_radix:
                box = getattr(node, "radix_width", None)
                if box is None:
                    box = node.radix_width = _CapBox()
                if box.cap is None:
                    # 4x average occupancy as the first guess; skew reports
                    # the exact need through the flag channel
                    box.cap = max(64, 1 << (4 * len(right) // nb - 1)
                                  .bit_length())
                out, ovf, wneed = join_ops.radix_join(
                    left, node.left_keys, right, node.right_keys,
                    how=node.how, cap=node.cap,
                    wide_keys_ok=getattr(node, "pack32_verified", False),
                    n_buckets=nb, width=box.cap)
                overflows.append((node, ovf))
                overflows.append((box, wneed))
                return out
            out, ovf = join_ops.join(
                left, node.left_keys, right, node.right_keys, how=node.how,
                cap=node.cap,
                wide_keys_ok=getattr(node, "pack32_verified", False),
                build_sorted=getattr(node, "build_sorted", False),
                order=presort)
        overflows.append((node, ovf))
        # label-qualified names are globally unique, no suffixing occurs
        return out

    if isinstance(node, MultiJoinNode):
        probe = _sub(node.children[0], batches, overflows, ctx)
        builds = [_sub(c, batches, overflows, ctx)
                  for c in node.children[1:]]
        n = ctx[3]
        reuse = node.reuse or [False] * len(node.children)
        exch = node.exch_keys or ([list(node.probe_keys)]
                                  + [list(bk) for bk in node.build_keys])
        if n:
            # the fused exchange: every input hash-repartitions ONCE on
            # the segment's key class (one shuffle round for the whole
            # segment); intermediate join results never exist, so never
            # re-shuffle.  Inputs the scheduler proved already partitioned
            # on the class — and replicated rider builds (exch None) —
            # flow through without a collective.
            if node.exch_caps is None:
                node.exch_caps = [
                    None if (reuse[i] or exch[i] is None) else
                    _CapBox(kind="shuffle", site=f"multiway[{i}]")
                    for i in range(len(node.children))]
            inputs = list(zip([probe] + builds, exch))
            shuffled = []
            for (b, keys), box in zip(inputs, node.exch_caps):
                if box is None:         # reused partition / replicated rider
                    shuffled.append(b)
                    continue
                if box.cap is None:
                    box.cap = max(1, 2 * len(b) // n)
                out_b, needed = _repartition_exec(b, list(keys), n, box.cap)
                overflows.append((box, needed))
                shuffled.append(out_b)
            probe, builds = shuffled[0], shuffled[1:]
        if node.cap is None:
            node.cap = max(1, len(probe), *(len(b) for b in builds))
        out, ovf = join_ops.multiway_join(
            probe, node.probe_keys, list(zip(builds, node.build_keys)),
            list(node.hows), cap=node.cap, level_keys=node.level_keys,
            packs=node.packs)
        overflows.append((node, ovf))
        return out

    if isinstance(node, ExchangeNode):
        child = _sub(node.child(), batches, overflows, ctx)
        if node.kind == "gather":
            return _all_gather_batch(child)
        if node.reused:
            # keyed exchange scheduler: the child is already hash-
            # partitioned on this key class — rows flow through, no
            # collective, no overflow flag
            return child
        n = ctx[3]
        keys = node.keys if node.keys is not None else list(child.names)
        if node.cap is None:
            node.cap = max(1, 2 * len(child) // max(1, n))
        out, ovf = _repartition_exec(child, keys, n, node.cap)
        overflows.append((node, ovf))
        return out

    if isinstance(node, AggNode):
        child = _sub(node.child(), batches, overflows, ctx)
        merge = node.merge
        if not node.key_names:
            if merge:
                return _scalar_agg_merged(child, node.specs)
            return scalar_aggregate(child, node.specs)
        shift = getattr(node, "key_shift", {}) or {}
        if node.strategy == "dense":
            work = child
            if shift:
                cols = list(work.columns)
                for kn, mn in shift.items():
                    i = work.names.index(kn)
                    c = cols[i]
                    cols[i] = dreplace(c, data=c.data - jnp.asarray(mn, c.data.dtype))
                work = ColumnBatch(work.names, cols, work.sel, work.num_rows)
            if merge:
                out = _dense_agg_merged(work, node.key_names, node.domains,
                                        node.specs)
            else:
                out = group_aggregate_dense(work, node.key_names, node.domains,
                                            node.specs)
            if shift:
                cols = list(out.columns)
                for kn, mn in shift.items():
                    i = out.names.index(kn)
                    c = cols[i]
                    cols[i] = dreplace(c, data=c.data + jnp.asarray(mn, c.data.dtype))
                out = ColumnBatch(out.names, cols, out.sel, out.num_rows)
            return out
        if node.key_names and getattr(node, "agg_dist", "") == "local" \
                and ctx is not None and ctx[3]:
            # cardinality-adaptive "local" arm (sorted strategy): pre-reduce
            # this shard's rows into partial-aggregate rows, shuffle only
            # the PARTIALS on the key hash, merge co-located partials once
            # (Partial Partial Aggregates; parallel/agg.py has the policy)
            from ..parallel.agg import merge_partial_agg_specs

            n = ctx[3]
            parts, fin = partial_specs(node.specs)
            mg_part = max(1, min(node.max_groups, len(child))
                          if node.max_groups else len(child))
            part = group_aggregate_sorted(child, node.key_names, parts,
                                          mg_part)
            part = ColumnBatch(part.names, part.columns, part.sel, None)
            box = getattr(node, "agg_exch_cap", None)
            if box is None:
                box = node.agg_exch_cap = _CapBox(kind="shuffle", site="agg")
            if box.cap is None:
                box.cap = max(1, 2 * len(part) // n)
            shuf, needed = _repartition_exec(part, node.key_names, n,
                                             box.cap)
            overflows.append((box, needed))
            final = group_aggregate_sorted(shuf, node.key_names,
                                           merge_partial_agg_specs(parts),
                                           max(1, len(shuf)))
            return finalize_partials(final, fin, node.key_names)
        mg = node.max_groups or max(1, len(child))
        return group_aggregate_sorted(child, node.key_names, node.specs, mg,
                                      order=_presort_order(node, batches,
                                                           len(child)))

    if isinstance(node, DistinctNode):
        child = _sub(node.child(), batches, overflows, ctx)
        mg = max(1, len(child))
        return group_aggregate_sorted(child, list(child.names), [], mg)

    if isinstance(node, SortNode):
        child = _sub(node.child(), batches, overflows, ctx)
        keys = [SortKey(k, asc) for k, asc in node.keys]
        if node.limit is not None:
            k = node.limit + node.offset
            if node.dist_topk:
                # per-shard top-k, all_gather the candidates, final top-k —
                # the TopNSorter merge of per-region streams (src/runtime/
                # topn_sorter.cpp) as two kernels + one collective
                local = top_k(child, keys, min(k, len(child)))
                child = _all_gather_batch(local)
            out = top_k(child, keys, k)
            if node.offset:
                out = head(out, node.limit, node.offset)
            return out
        return sort_batch(child, keys)

    if isinstance(node, LimitNode):
        child = _sub(node.child(), batches, overflows, ctx)
        return head(child, node.limit, node.offset)

    if isinstance(node, UnionNode):
        parts = [compact(_sub(c, batches, overflows, ctx)) for c in node.children]
        names = [f.name for f in node.schema.fields]
        parts = [p.rename(names) for p in parts]
        parts = [_harmonize(p, node.schema) for p in parts]
        parts = _align_string_dicts(parts)
        return concat_batches(parts)

    if isinstance(node, MembershipNode):
        child = _sub(node.children[0], batches, overflows, ctx)
        sub = _sub(node.children[1], batches, overflows, ctx)
        sub_name = sub.names[0]
        if len(sub) == 0:
            # empty list: IN -> FALSE, NOT IN -> TRUE even for NULL keys —
            # no comparison ever happens, so the result is non-NULL
            n = len(child)
            data = jnp.broadcast_to(jnp.asarray(node.negate), (n,))
            names = list(child.names) + [node.out_name]
            cols = list(child.columns) + [Column(data, None, LType.BOOL)]
            return ColumnBatch(tuple(names), cols, child.sel, child.num_rows)
        probe = ColumnBatch((node.key_col,), [child.column(node.key_col)],
                            child.sel, None)
        probe2, build2 = join_ops._align_string_keys(
            probe, [node.key_col], sub, [sub_name])
        xc = probe2.column(node.key_col)
        bc = build2.column(sub_name)
        bsel = sub.sel_mask()
        bvalid = bc.valid_mask() & bsel
        sentinel = (jnp.iinfo if bc.data.dtype.kind in "iu"
                    else jnp.finfo)(bc.data.dtype).max
        bkey = jnp.where(bvalid, bc.data, sentinel)
        bsorted = jnp.sort(bkey)
        nlive = jnp.sum(bvalid)
        pos = jnp.searchsorted(bsorted, xc.data)
        hit = (pos < nlive) & \
            (jnp.take(bsorted, jnp.clip(pos, 0, len(sub) - 1), mode="clip")
             == xc.data)
        has_null_in_list = jnp.any(bsel & ~bc.valid_mask())
        found = hit
        if node.negate:
            data = ~found
        else:
            data = found
        # SQL three-valued IN: NULL key -> NULL; a miss with NULLs
        # in the list -> NULL.  A live-empty list (all rows filtered out,
        # nonzero capacity) behaves like the empty fast path above: no
        # comparison happens, so even NULL keys yield a non-NULL result
        live_empty = nlive == 0
        validity = (xc.valid_mask() | live_empty) & (found | ~has_null_in_list)
        names = list(child.names) + [node.out_name]
        cols = list(child.columns) + [Column(data, validity, LType.BOOL)]
        return ColumnBatch(tuple(names), cols, child.sel, child.num_rows)

    if isinstance(node, ScalarSourceNode):
        child = _sub(node.children[0], batches, overflows, ctx)
        sub = compact(_sub(node.children[1], batches, overflows, ctx))
        n = len(child)
        names = list(child.names)
        cols = list(child.columns)
        live = sub.live_count()
        has_row = live > 0
        # MySQL ER_SUBQUERY_NO_1_ROW (1242): the live count rides back with
        # the needed-capacity flags; the session raises when it exceeds 1
        overflows.append((node, jnp.asarray(live, jnp.int32)))
        for i, name in enumerate(node.col_names):
            c = sub.columns[i]
            if len(sub) == 0:
                # zero-capacity source: constant NULL
                v0 = jnp.zeros((), c.data.dtype)
                val0 = jnp.asarray(False)
            else:
                v0 = c.data[0]
                val0 = c.valid_mask()[0] & has_row   # empty subquery -> NULL
            cols.append(Column(jnp.broadcast_to(v0, (n,)),
                               jnp.broadcast_to(val0, (n,)), c.ltype,
                               c.dictionary))
            names.append(name)
        return ColumnBatch(tuple(names), cols, child.sel, child.num_rows)

    if isinstance(node, WindowNode):
        from ..ops.window import window_compute

        child = _sub(node.child(), batches, overflows, ctx)
        keys = [SortKey(k, asc) for k, asc in node.order_keys]
        return window_compute(child, node.partition_names, keys, node.specs)

    if isinstance(node, ValuesNode):
        cols = []
        empty = ColumnBatch((), [], None, None)
        for i, e in enumerate(node.exprs[0]):
            c = eval_output(e, empty)
            cols.append(_broadcast(c, 1))
        return ColumnBatch(tuple(node.names), cols)

    if isinstance(node, StreamResultNode):
        # the chunk-folded aggregate's finalized batch (exec/streaming.py)
        return batches[node.key]

    raise ExecError(f"unknown plan node {type(node).__name__}")


def _sub(node, batches, overflows, ctx):
    if ctx is not None and ctx[1] is not None:
        return _eval_traced(node, batches, ctx)
    return _eval(node, batches, overflows, ctx)


# -- mesh collectives (dist mode; plan/distribute.py inserts the markers) ----

def exchange_summary(plan: PlanNode) -> dict:
    """Exchange accounting for a distributed plan — the numbers the keyed
    exchange scheduler exists to move.  One round = one EXECUTED
    synchronized repartition step: a binary shuffle join's two input
    exchanges are ONE round, a fused MultiJoin's N+1 input exchanges are
    ONE round, a lone repartition (group-by / distinct co-location) or a
    "local" adaptive agg's internal partial shuffle is one each.  Reused
    partitions (scheduler-proved, collective skipped at runtime) count in
    ``reused``, never in ``rounds`` or ``collectives`` — the EXPLAIN
    ANALYZE line and the bench JSON must report what the device actually
    paid, not what the plan tree syntactically contains.  ``collectives``
    counts individual executed repartition all_to_alls (a fused segment's
    probe + each shuffled build; replicated rider builds cost none);
    ``keys`` lists the chosen partition key (short names) per counted
    round, outermost-last."""
    rounds = 0
    reused = 0
    collectives = 0
    keys: list = []
    skip: set = set()
    seen: set = set()

    def short(cols) -> str:
        return "+".join(c.split(".")[-1] for c in (cols or ()))

    def walk(n: PlanNode) -> None:
        nonlocal rounds, reused, collectives
        if id(n) in seen:           # DAG-shared subtrees execute per parent
            return                  # trace, but count once for the metric
        seen.add(id(n))
        if isinstance(n, MultiJoinNode):
            r = n.reuse or [False] * len(n.children)
            exch = n.exch_keys or ([n.probe_keys] + list(n.build_keys))
            execd = sum(1 for i in range(len(n.children))
                        if exch[i] is not None and not r[i])
            reused += sum(1 for i in range(len(n.children))
                          if exch[i] is not None and r[i])
            collectives += execd
            if execd:
                rounds += 1
                keys.append(short(n.probe_keys))
        elif isinstance(n, JoinNode):
            reps = [c for c in n.children
                    if isinstance(c, ExchangeNode) and c.kind == "repartition"]
            if reps:
                reused += sum(c.reused for c in reps)
                execd = sum(1 for c in reps if not c.reused)
                collectives += execd
                if execd:
                    rounds += 1
                    keys.append(short(n.left_keys))
                skip.update(id(c) for c in reps)
        elif isinstance(n, ExchangeNode) and n.kind == "repartition" \
                and id(n) not in skip:
            if n.reused:
                reused += 1
            else:
                rounds += 1
                collectives += 1
                keys.append(short(n.keys) or "*")
        elif isinstance(n, AggNode) and \
                getattr(n, "agg_dist", "") == "local" \
                and n.strategy != "dense":
            rounds += 1
            collectives += 1
            keys.append(short(n.key_names))
        for c in n.children:
            walk(c)

    walk(plan)
    # outermost-last reads as execution order (keys collected top-down)
    keys.reverse()
    return {"rounds": rounds, "reused": reused, "collectives": collectives,
            "keys": keys}


def count_shuffle_rounds(plan: PlanNode) -> int:
    """Executed hash-repartition rounds (see :func:`exchange_summary`)."""
    return exchange_summary(plan)["rounds"]


def progress_totals(plan: PlanNode) -> dict:
    """HOST-side work estimates for the live progress record (obs/
    progress.py): operator count, scan count, and the executed exchange
    rounds a multi-round MPP query will pay — the denominators SHOW
    PROCESSLIST renders "m/n" against.  A plan-tree walk over host
    objects; nothing here touches device state or traced scope."""
    operators = 0
    scans = 0
    seen: set = set()

    def walk(n: PlanNode) -> None:
        nonlocal operators, scans
        if id(n) in seen:
            return
        seen.add(id(n))
        operators += 1
        if isinstance(n, ScanNode):
            scans += 1
        for c in n.children:
            walk(c)

    walk(plan)
    return {"operators": operators, "scans": scans,
            "rounds": exchange_summary(plan)["rounds"]}


def _all_gather_batch(b: ColumnBatch) -> ColumnBatch:
    """Shard-partitioned rows -> replicated full batch (one all_gather)."""
    def ag(x):
        return jax.lax.all_gather(x, AXIS, axis=0, tiled=True)

    cols = [dreplace(c, data=ag(c.data),
                     validity=None if c.validity is None else ag(c.validity))
            for c in b.columns]
    return ColumnBatch(b.names, cols, ag(b.sel_mask()), None)


def _repartition_exec(b: ColumnBatch, keys: list[str], n: int, cap: int):
    """Hash-partition local rows on ``keys`` + all_to_all: equal keys land on
    one shard (the ExchangeSender/Receiver pair as one collective)."""
    from ..parallel.shuffle import repartition_collective

    return repartition_collective(b, keys, n, cap)


def _merge_collective(op: str, x):
    if op == "sum":
        return jax.lax.psum(x, AXIS)
    if op == "min":
        return jax.lax.pmin(x, AXIS)
    if op == "max":
        return jax.lax.pmax(x, AXIS)
    raise ExecError(f"no collective merge for {op}")


def _merge_partial_cols(part: ColumnBatch, parts: list[AggSpec],
                        key_names: list[str]):
    """psum/pmin/pmax-merge the aggregate columns of a local partial table."""
    cols = []
    for name, c in zip(part.names, part.columns):
        if name in key_names:
            cols.append(c)
            continue
        spec = next(s for s in parts if s.out_name == name)
        merged = _merge_collective(MERGE_OP[spec.op], c.data)
        validity = c.validity
        if validity is not None:
            validity = jax.lax.psum(validity.astype(jnp.int32), AXIS) > 0
        cols.append(dreplace(c, data=merged, validity=validity))
    return cols


def _dense_agg_merged(batch: ColumnBatch, key_names: list[str],
                      domains: list[int], specs: list[AggSpec]) -> ColumnBatch:
    """Per-shard dense partial group-by + in-network merge (the partial
    AggNode on every region + MERGE_AGG_NODE on the coordinator,
    src/exec/agg_node.cpp, as psum/pmin/pmax over ICI)."""
    parts, fin = partial_specs(specs)
    part = group_aggregate_dense(batch, key_names, domains, parts)
    cols = _merge_partial_cols(part, parts, key_names)
    present = jax.lax.psum(part.sel_mask().astype(jnp.int32), AXIS) > 0
    merged = ColumnBatch(part.names, cols, present, None)
    return finalize_partials(merged, fin, key_names)


def _scalar_agg_merged(batch: ColumnBatch, specs: list[AggSpec]) -> ColumnBatch:
    parts, fin = partial_specs(specs)
    part = scalar_aggregate(batch, parts)
    cols = _merge_partial_cols(part, parts, [])
    merged = ColumnBatch(part.names, cols, None, None)
    return finalize_partials(merged, fin, [])


def _broadcast(c: Column, n: int) -> Column:
    data = jnp.asarray(c.data)
    if data.ndim == 0:
        data = jnp.broadcast_to(data, (n,))
    v = c.validity
    if v is not None and jnp.ndim(v) == 0:
        v = jnp.broadcast_to(v, (n,))
    return dreplace(c, data=data, validity=v)


def _align_string_dicts(parts: list[ColumnBatch]) -> list[ColumnBatch]:
    """Remap string columns of UNION arms onto shared dictionaries."""
    from ..column.dictionary import NULL_CODE, Dictionary
    import numpy as np

    if len(parts) < 2:
        return parts
    out = [list(p.columns) for p in parts]
    for i, c0 in enumerate(parts[0].columns):
        if c0.ltype is not LType.STRING:
            continue
        dicts = [p.columns[i].dictionary for p in parts]
        if any(d is None for d in dicts):
            raise ExecError("UNION string column lacks a dictionary")
        if all(d._id == dicts[0]._id for d in dicts):
            continue
        values = dicts[0].values
        for d in dicts[1:]:
            values = np.union1d(values, d.values)
        merged = Dictionary(values)
        for pi, p in enumerate(parts):
            c = p.columns[i]
            remap = jnp.asarray(np.searchsorted(values, c.dictionary.values)
                                .astype(np.int32))
            data = jnp.where(c.data >= 0,
                             jnp.take(remap, jnp.clip(c.data, 0, None), mode="clip"),
                             NULL_CODE)
            out[pi][i] = dreplace(c, data=data, dictionary=merged)
    return [ColumnBatch(p.names, cols, p.sel, p.num_rows)
            for p, cols in zip(parts, out)]


def _harmonize(p: ColumnBatch, schema) -> ColumnBatch:
    """Cast union arms to the unified schema's types."""
    from ..expr.compile import cast_column

    cols = []
    for c, f in zip(p.columns, schema.fields):
        if c.ltype != f.ltype:
            if c.ltype is LType.STRING or f.ltype is LType.STRING:
                raise ExecError("UNION of string and non-string columns")
            c = cast_column(c, f.ltype)
        cols.append(c)
    return ColumnBatch(p.names, cols, p.sel, p.num_rows)
