"""Pushed-down fragment dispatch: N store daemons scan, the frontend merges.

The reference's read architecture ships one serialized plan fragment per
region to the store processes and executes it THERE (Region::query over the
pb::Plan, src/store/region.cpp:1680/2671), so the frontend receives only
qualifying rows or aggregate partials and the fleet's scan bandwidth scales
with the store count.  Round 5 built the contract (plan/fragment.py) with a
SERIAL per-region loop on the frontend; this module is the missing dispatch
layer:

- ``plan/distribute.slice_fragments`` keys the fragment to region ownership
  (one FragmentSpec per region, routed-range attached);
- every spec ships CONTENT-ADDRESSED (``frag_key`` — the AOT-artifact
  discipline): the body is pre-published to the stores once per frontend,
  daemons warm-start compiled programs from memory -> disk blob -> peer
  fetch, and ``fragment_warm_compiles`` stays pinned at 0 on re-dispatch;
- specs dispatch CONCURRENTLY (one thread per region — each blocks on its
  daemon's scan+fold, so N daemons deliver N× scan bandwidth);
- a mid-flight split/migration surfaces as StaleRoutingError from the
  range-validated read loop: the WHOLE attempt is discarded, routing
  refreshes, and the fragment re-slices over the new owners
  (``fragment_retargets``).  Partials are merged only from a
  fully-successful attempt, so a retarget can never double-fold a region —
  the exactly-once discipline the ``fragment_chaos`` scenario audits via
  the per-daemon ``scanned`` counts riding each payload.

Anything the stores cannot serve raises PushdownUnsupported and the caller
falls back to the frontend-pulled image path (``fragment_fallbacks``) —
pushed execution is an optimization with a full-fidelity fallback.
"""

from __future__ import annotations

import contextvars
import json
import threading
from collections import deque

from ..chaos import failpoint
from ..obs import trace
from ..plan.distribute import slice_fragments
from ..plan.fragment import frag_wire_key
from ..storage.remote_tier import PushdownUnsupported, StaleRoutingError
from ..utils import metrics
from ..utils.flags import FLAGS, define

define("fragment_pushdown", True,
       "dispatch eligible pushed reads as per-region fragment_execute "
       "RPCs executed by the store daemons in parallel (hash-addressed "
       "bodies, daemon-side cold fold, split/migration re-targeting); "
       "off = the serial per-region exec_fragment loop — bit-identical "
       "results, frontend-paced")
define("fragment_retry_max", 3,
       "dispatch attempts per pushed query: each retry refreshes routing "
       "and re-slices over the new region owners (mid-flight split / "
       "migration); exhausted retries fall back to the pulled image path")

# last dispatches for information_schema.fragments (newest last; the ring
# is the introspection surface, not an accounting truth — counters are)
RECENT_CAP = 64
RECENT: deque = deque(maxlen=RECENT_CAP)
_recent_mu = threading.Lock()


def recent_dispatches() -> list:
    """Snapshot of the recent-dispatch ring, oldest first."""
    with _recent_mu:
        return [dict(r) for r in RECENT]


def _payload_wire_bytes(payload: dict) -> int:
    """The JSON frame size this payload occupied on the wire — the
    subtrahend of bytes-saved (region bytes scanned daemon-side minus what
    actually crossed)."""
    from ..utils.net import _enc

    try:
        return len(json.dumps(_enc(payload)))
    except (TypeError, ValueError):
        return len(str(payload))


def dispatch_fragments(tier, frag: dict) -> tuple[list, dict]:
    """Execute one wire fragment across every region owner concurrently.
    Returns ``(payloads, stats)`` with payloads in region start-key order —
    the SAME merge order as the serial path, so
    ``plan.fragment.merge_push_results`` yields bit-identical results.
    Raises PushdownUnsupported / ReplicationError when the stores cannot
    serve it; the caller falls back to the image path."""
    key = frag_wire_key(frag)
    stats = {"frag_key": key, "table": tier.table_key,
             "mode": frag.get("mode", ""), "dispatched": 0, "local": 0,
             "retargeted": 0, "partial_rows": 0, "scanned": 0,
             "bytes_saved": 0, "status": "ok"}
    try:
        with trace.span("fragment.dispatch", table=tier.table_key,
                        frag=key):
            payloads = _dispatch(tier, frag, key, stats)
    except BaseException as e:      # noqa: BLE001 — recorded, re-raised
        stats["status"] = type(e).__name__
        raise
    finally:
        with _recent_mu:
            RECENT.append(dict(stats))
    trace.event("fragments", **{k: stats[k] for k in
                                ("dispatched", "local", "retargeted",
                                 "partial_rows", "bytes_saved")})
    return payloads, stats


def _dispatch(tier, frag: dict, key: str, stats: dict) -> list:
    if key not in tier._frag_published:
        tier.frag_publish(key, frag)
    attempts = max(1, int(FLAGS.fragment_retry_max))
    last: Exception = PushdownUnsupported(
        f"{tier.table_key}: fragment dispatch exhausted")
    for attempt in range(attempts):
        specs = slice_fragments(frag, tier, key)
        if failpoint.ENABLED:
            if failpoint.hit("fragment.dispatch", table=tier.table_key,
                             attempt=attempt):
                # drop: this attempt is abandoned before any spec leaves;
                # the loop re-dispatches, then the caller falls back
                last = PushdownUnsupported(
                    "fragment.dispatch dropped by failpoint")
                continue
        results: list = [None] * len(specs)
        errors: list = [None] * len(specs)

        def run(i, spec, region):
            try:
                results[i] = tier.fragment_execute_region(
                    region, spec.frag_key, spec.frag)
            except Exception as e:   # noqa: BLE001 — re-raised below
                errors[i] = e

        if len(specs) == 1:
            run(0, *specs[0])
        else:
            # copy_context: the worker threads must see the live query's
            # cancel token (a contextvar) so a KILL cuts their idempotent
            # fragment_execute response waits short instead of riding out
            # the full RPC deadline
            ctx = contextvars.copy_context()
            threads = [threading.Thread(
                target=ctx.copy().run, args=(run, i, spec, region),
                daemon=True,
                name=f"frag-{key[:8]}-r{spec.region_id}")
                for i, (spec, region) in enumerate(specs)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        stale = next((e for e in errors
                      if isinstance(e, StaleRoutingError)), None)
        hard = next((e for e in errors if e is not None
                     and not isinstance(e, StaleRoutingError)), None)
        if hard is not None:
            raise hard
        if stale is not None:
            # a region split/migrated mid-flight: throw the WHOLE attempt
            # away, refresh routing, re-slice over the new owners.  Only a
            # fully-successful attempt is ever merged, so a region scanned
            # by both attempts still folds exactly once
            metrics.fragment_retargets.add(1)
            stats["retargeted"] += 1
            tier.refresh_routing()
            last = stale
            continue
        metrics.fragments_dispatched.add(len(results))
        stats["dispatched"] = len(results)
        saved = 0
        for p in results:
            if p.get("cold"):
                stats["local"] += 1     # cold tier folded in place
            stats["partial_rows"] += len(p.get("rows") or p.get("groups")
                                         or ())
            stats["scanned"] += int(p.get("scanned", 0))
            raw = int(p.get("raw_bytes", 0)) + int(p.get("cold_bytes", 0))
            saved += max(0, raw - _payload_wire_bytes(p))
        metrics.fragment_bytes_saved.add(saved)
        stats["bytes_saved"] = saved
        return results
    raise last
