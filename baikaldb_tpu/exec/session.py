"""Session: the SQL entry point (parse -> plan -> jit -> result).

The analog of the reference's connection state machine driving a query
(src/protocol/state_machine.cpp:1775 _handle_client_query_common_query:
LogicalPlanner::analyze -> PhysicalPlanner::analyze -> execute -> PacketNode),
minus the wire protocol (server tier lands later).  Includes the plan cache
(reference: state_machine.cpp:1984) keyed by SQL text + table versions +
static shapes, so repeated queries skip parse/plan/trace and reuse the
compiled XLA executable.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import numpy as np
import pyarrow as pa

from ..column.batch import ColumnBatch
from ..expr.compile import eval_expr, eval_output, eval_predicate
from ..meta.catalog import Catalog, IndexInfo, parse_type
from ..ops.compact import compact
from ..plan.nodes import (AggNode, ExchangeNode, JoinNode, MultiJoinNode,
                          PlanNode, ScalarSourceNode, plan_signature)
from ..plan.planner import PlanError, Planner
from ..sql.lexer import SqlError
from ..sql.parser import parse_sql
from ..sql.stmt import (AlterTableStmt, CreateDatabaseStmt, CreateTableStmt, DeleteStmt,
                        DescribeStmt, DropDatabaseStmt, DropTableStmt,
                        ExplainStmt, InsertStmt, SelectStmt, ShowStmt,
                        SetStmt, TruncateStmt, TxnStmt, UpdateStmt, UseStmt)
from ..meta.privileges import READ, WRITE, AccessError, PrivilegeManager
from ..sql.stmt import (CreateMatViewStmt, CreateSubscriptionStmt,
                        CreateUserStmt, CreateViewStmt, DeallocateStmt,
                        DropMatViewStmt, DropSubscriptionStmt, DropUserStmt,
                        DropViewStmt, ExecuteStmt, FetchStmt, GrantStmt,
                        HandleStmt,
                        KillStmt, LoadDataStmt, PrepareStmt, RevokeStmt)
from ..plan import paramize
from ..storage.column_store import ROWID as ROWID_COL
from ..storage.column_store import (TableStore, check_cold_readable,
                                    schema_to_arrow)
from ..types import Field, LType, Schema
from ..analysis.runtime import guard_stats, hot_path_guard
from ..obs import progress, trace
from ..obs.flightrec import (FlightRecorder, device_stats, metric_delta,
                             metric_marks)
from ..obs.progress import PROGRESS, QueryKilled
from ..obs.trace import TRACER
from ..obs.watchdog import QueryWatchdog
from ..utils import compilecache, metrics
from ..utils.flags import FLAGS, define

define("cold_fs_dir", "",
       "external cold-storage root (posix AFS stand-in); empty = cold "
       "tier disabled")
define("param_queries", True,
       "auto-parameterize WHERE literals (plan/paramize.py): one plan-cache "
       "entry and one compiled executable serve every literal variant of a "
       "query shape; 0 restores SQL-text-keyed caching with baked literals")
from .dispatch import BatchDispatcher
from . import executor, streaming
from . import fragments as _fragments  # noqa: F401 — registers the
# fragment_pushdown / fragment_retry_max flags at session load (SET and
# the CLI must see them before the first pushed dispatch)
from .executor import (_CapBox, compile_plan, count_shuffle_rounds,
                       exchange_summary)

# join overflow retry budget lives in FLAGS.join_retry_max: retries settle
# at most one operator per re-trace, so a chain of N joins can need N rounds
# in the worst case (each is a recompile)
# INSERT..SELECT at or below this lands in the hot (WAL-durable) row tier;
# above it, the bulk cold path (durable at the next checkpoint)
HOT_INSERT_ROWS = 100_000


# server-level system variable defaults (reference: the session_variables
# map MySQL clients read at connect; SHOW VARIABLES and SELECT @@x share it)
_SERVER_VARS = {
    "version": "8.0.0-baikaldb-tpu",
    "version_comment": "baikaldb_tpu (JAX/XLA)",
    "lower_case_table_names": "0",
    "max_allowed_packet": str(1 << 24),
    "character_set_server": "utf8mb4",
    "character_set_client": "utf8mb4",
    "character_set_results": "utf8mb4",
    "collation_server": "utf8mb4_bin",
    "collation_connection": "utf8mb4_bin",
    "autocommit": "ON",
    "sql_mode": "STRICT_TRANS_TABLES",
    "tx_isolation": "REPEATABLE-READ",
    "transaction_isolation": "REPEATABLE-READ",
    "wait_timeout": "28800",
    "interactive_timeout": "28800",
    "net_write_timeout": "60",
    "time_zone": "SYSTEM",
    "system_time_zone": "UTC",
    "init_connect": "",
    "license": "Apache-2.0",
    "performance_schema": "0",
}

_CONN_IDS = itertools.count(1)


def next_conn_id() -> int:
    """One connection-id space for embedded Sessions AND wire connections:
    KILL <id> and the processlist Id column resolve against the same
    counter no matter which door the client came through."""
    return next(_CONN_IDS)

_ENV_FNS = ("database", "schema", "user", "current_user", "session_user",
            "system_user", "connection_id", "version")


def _opt_on(v) -> bool:
    """Table-option truth: parser option values arrive as strings, so
    BINLOG=0 / BINLOG=false must read as OFF."""
    if v is None:
        return False
    return str(v).strip().lower() not in ("", "0", "false", "off", "no")


def _env_alias(e):
    """MySQL column captions for environment expressions: SELECT @@version
    titles the column '@@version', DATABASE() titles it 'DATABASE()'."""
    from ..expr.ast import Call
    if isinstance(e, Call):
        if e.op == "__sysvar__":
            return "@@" + e.args[0].value
        if e.op == "__uservar__":
            return "@" + e.args[0].value
        if e.op in _ENV_FNS and not e.args:
            return f"{e.op.upper()}()"
    return None


@functools.lru_cache(maxsize=64)
def _show_like_rx(pat: str):
    """Compiled SHOW ... LIKE matcher (MySQL semantics: case-insensitive,
    wildcard/escape translation shared with expression-level LIKE)."""
    import re

    from ..expr.compile import _like_to_regex
    return re.compile(_like_to_regex(pat), re.IGNORECASE)


def _empty_info(name: str):
    return schema_to_arrow(Catalog.INFORMATION_SCHEMA[name]).empty_table()


def _stmt_image(kind: str, s) -> str:
    where = f" WHERE {s.where!r}" if getattr(s, "where", None) is not None else ""
    if kind == "update":
        sets = ", ".join(f"{n}={e!r}" for n, e in s.assignments)
        return f"UPDATE {s.table.name} SET {sets}{where}"
    if kind == "replace":
        return f"REPLACE INTO {s.table.name} ({len(s.rows)} rows)"
    if kind == "upsert":
        sets = ", ".join(f"{c}={v!r}" for c, v in s.on_dup)
        return (f"INSERT INTO {s.table.name} ({len(s.rows)} rows) "
                f"ON DUPLICATE KEY UPDATE {sets}")
    return f"DELETE FROM {s.table.name}{where}"


def _is_vector_component(name: str, vcols: dict) -> bool:
    if not name.startswith("__"):
        return False
    return _component_owner(name, vcols) is not None

def _component_owner(name: str, vcols: dict):
    for v in vcols:
        if name.startswith(f"__{v}_") and name[len(v) + 3:].isdigit():
            return v
    return None


def _parse_vector(v, dim: int):
    if v is None:
        return [None] * dim
    if isinstance(v, str):
        body = v.strip().lstrip("[").rstrip("]").replace(",", " ")
        vals = [float(x) for x in body.split()]
    else:
        vals = [float(x) for x in v]
    if len(vals) != dim:
        raise PlanError(f"vector literal has {len(vals)} components, "
                        f"expected {dim}")
    return vals


def _expand_vector_arrow(t: pa.Table, vcols: dict) -> pa.Table:
    """Split list-typed vector columns into float32 component columns
    (NULL vectors allowed, like the row path)."""
    for name, dim in vcols.items():
        if name not in t.column_names:
            continue
        rows = t.column(name).to_pylist()
        mat = np.zeros((len(rows), dim), np.float32)
        isnull = np.zeros(len(rows), bool)
        for i, v in enumerate(rows):
            if v is None:
                isnull[i] = True
                continue
            if len(v) != dim:
                raise PlanError(f"vector column {name!r} expects dim {dim}")
            mat[i] = v
        t = t.drop_columns([name])
        for i in range(dim):
            t = t.append_column(
                f"__{name}_{i}",
                pa.array(mat[:, i], pa.float32(),
                         mask=isnull if isnull.any() else None))
    return t


def _expand_vector_row(r: dict, vcols: dict) -> dict:
    out = dict(r)
    for name, dim in vcols.items():
        if name in out:
            vals = _parse_vector(out.pop(name), dim)
            for i, x in enumerate(vals):
                out[f"__{name}_{i}"] = x
    return out


def _qualify_free(e):
    """Strip table qualifiers: region batches carry plain column names."""
    from ..expr.ast import AggCall, Call, ColRef

    if isinstance(e, ColRef):
        return ColRef(e.name)
    if isinstance(e, AggCall):
        raise PlanError("aggregates not allowed in UPDATE/DELETE")
    if isinstance(e, Call):
        return Call(e.op, tuple(_qualify_free(a) for a in e.args))
    return e


@dataclass
class Result:
    """Query result (the PacketNode analog: result set or affected-rows OK)."""
    columns: list[str] = field(default_factory=list)
    arrow: Optional[pa.Table] = None
    affected_rows: int = 0
    plan_text: Optional[str] = None

    @property
    def rows(self) -> list[tuple]:
        if self.arrow is None:
            return []
        cols = [self.arrow.column(i).to_pylist() for i in range(self.arrow.num_columns)]
        return [tuple(c[i] for c in cols) for i in range(self.arrow.num_rows)]

    def to_pylist(self) -> list[dict]:
        return [] if self.arrow is None else self.arrow.to_pylist()

    def scalar(self):
        r = self.rows
        return r[0][0] if r else None


class _TableBinlogRetry:
    """One table's CDC retry state: a queue of failed distributed-binlog
    event batches plus the lock that serializes this table's drain/append
    rounds.  Rank 20: acquired INSIDE the store lock (10) by the autocommit
    CDC path and BEFORE the replicated tier's lock (30) when a queued append
    retries through the distributed binlog.  Every instance shares the
    runtime name ``db.binlog_retry_mu`` — one rank covers the per-table
    family, and two tables' locks (same rank) are never nested."""

    __slots__ = ("mu", "q")
    RANK = 20

    def __init__(self):
        from ..analysis.runtime import GuardedLock
        self.mu = GuardedLock("db.binlog_retry_mu", rank=self.RANK)
        self.q: deque = deque()


# instances are lazy (first binlogged table), but the declared rank must be
# visible to the static<->runtime consistency check from import time
from ..analysis.runtime import LOCK_RANKS as _LOCK_RANKS  # noqa: E402

_LOCK_RANKS.setdefault("db.binlog_retry_mu", _TableBinlogRetry.RANK)


class Database:
    """Shared engine state: catalog + table stores (one per server).

    With ``data_dir`` set the engine is durable: every table gets a WAL for
    hot DML (storage/column_store.py row tier), DDL persists the catalog as
    JSON, and ``checkpoint()`` flushes cold Parquet + resets WALs.  A new
    Database over the same directory recovers committed state — the analog
    of baikalStore restart recovery (SURVEY §3.4)."""

    def __init__(self, data_dir: Optional[str] = None, fleet=None,
                 cluster=None, cold_dir: Optional[str] = None,
                 read_replica: str = "leader", read_tag: str = "",
                 read_max_lag: int = 0):
        """``fleet``: a raft.fleet.StoreFleet — when set, every table's hot
        row tier is raft-replicated across the fleet's store nodes (DML
        quorum-commits through region raft groups; a new Database over the
        same fleet recovers committed state from the replicas).  The
        reference's always-on mode: every DML is a raft apply on a Region
        (src/store/region.cpp:1961,2301).

        ``cluster``: a storage.remote_tier.ClusterClient (or "host:port" of
        the meta daemon) — the multi-process variant of ``fleet``: the same
        replication discipline, but regions live in real store daemon
        processes reached over TCP (the three-binary deployment,
        src/protocol/main.cpp + store/main.cpp + meta_server/main.cpp)."""
        self.catalog = Catalog()
        self.fleet = fleet
        if isinstance(cluster, str):
            from ..storage.remote_tier import ClusterClient
            cluster = ClusterClient(cluster)
        self.cluster = cluster
        if data_dir and (fleet is not None or cluster is not None):
            # the replicated tier IS the durability story in fleet/cluster
            # mode; silently skipping the requested WAL would be worse than
            # refusing (the operator asked for local durability)
            raise ValueError("data_dir cannot combine with fleet/cluster "
                             "mode: durability lives in the replicated tier")
        self.stores: dict[str, TableStore] = {}
        # MVCC plane (storage/mvcc.py): one TSO client per Database — in
        # fleet mode it draws batched grants from the meta service's
        # oracle, so every frontend on the fleet shares one clock — plus
        # the snapshot pin registry feeding the GC watermark
        from ..storage.mvcc import MvccRuntime
        self.mvcc = MvccRuntime(
            fleet.meta.tso.gen if fleet is not None else None)
        # fleet telemetry plane (obs/telemetry.py): registered daemon
        # addresses polled into information_schema.cluster_metrics /
        # SHOW STATUS cluster.* rows; cheap until daemons register (no
        # thread, no RPC) — device HBM gauges install into REGISTRY here
        from ..obs.telemetry import Telemetry
        self.telemetry = Telemetry()
        if cluster is not None:
            # three-binary deployment: meta + its registered stores join
            # the scrape set automatically (instances refresh per poll)
            self.telemetry.attach_meta(
                f"{cluster.meta.host}:{cluster.meta.port}")
            # ... and the AOT executable tier replicates through the same
            # deployment: this node publishes its compilations to the
            # store daemons and warm-starts from its peers'
            compilecache.AOT.attach_peer(
                f"{cluster.meta.host}:{cluster.meta.port}")
            # real TCP daemons: scrape in the background (telemetry_poll_s)
            # so cluster_metrics / SHOW STATUS read a warm cache instead of
            # paying a serial fleet RPC round inline per query
            self.telemetry.start()
        # query statistics ring (reference: slow-SQL collection + print_agg_sql,
        # network_server.h:82-107) — feeds information_schema.query_log
        self.query_log = deque(maxlen=1000)
        from ..storage.binlog import Binlog
        self.qos = None          # optional utils.qos.QosManager
        # cross-query batched dispatch (exec/dispatch.py): engine-wide so
        # concurrent SESSIONS coalesce onto one device batch per tick
        self.dispatcher = BatchDispatcher()
        self.privileges = PrivilegeManager()
        from ..meta.ddl import DdlManager
        self.ddl = DdlManager(self)   # online-DDL work queue + worker
        # live connections for SHOW PROCESSLIST (id -> dict), kept by the
        # wire server (reference: show processlist over NetworkServer conns)
        self.processlist: dict[int, dict] = {}
        # always-on flight recorder (obs/flightrec.py): bounded ring of
        # completed-query summaries; slow/killed/failed queries keep a full
        # forensic bundle — SELECT * FROM information_schema.flight_recorder
        self.flightrec = FlightRecorder()
        # wedged-query detector: scans this Database's live QueryProgress
        # records for silent beats (obs/watchdog.py); the thread only runs
        # in cluster mode — embedded single-process tests scan on demand
        self.watchdog = QueryWatchdog(db=self)
        if cluster is not None:
            self.watchdog.start()
        # committed-txn CDC batches whose distributed-binlog append failed:
        # PER-TABLE queues of event batches retried on later flushes instead
        # of silently dropped (bounded; overflow counts in
        # metrics.binlog_events_dropped).  CDC ordering is a per-table
        # contract, so each table gets its own queue+lock: one table's dead
        # binlog region no longer convoys every other table's commits (the
        # old engine-wide db.binlog_retry_mu), and holding the table's lock
        # across the drain-check AND the append closes the release-to-append
        # race the global design had in column_store._write_hot
        self._binlog_retry: dict[str, _TableBinlogRetry] = {}
        self._binlog_retry_reg_mu = threading.Lock()    # registry dict only
        self.data_dir = data_dir
        # external cold-storage FS (AFS stand-in, storage/coldfs): segment
        # bytes live here, manifests replicate through the region groups
        self.cold_dir = cold_dir
        self._cold_fs = None
        # read routing (reference: fetcher_store.cpp:351 choose_opt_instance
        # — leader for writes; follower/learner resource-isolated reads):
        # "follower" serves this frontend's table rebuilds from non-leader
        # replicas under a bounded applied-index staleness check, optionally
        # pinned to instances with a resource tag (the OLAP-isolated reader)
        self.read_replica = read_replica
        self.read_tag = read_tag
        self.read_max_lag = int(read_max_lag)
        from ..cdc import ChangeStreams, MatViews
        if data_dir:
            import os
            os.makedirs(data_dir, exist_ok=True)
            # WAL-backed binlog: CDC events + capturer checkpoints survive
            # kill-9 with the rest of the durable tier (region_binlog analog)
            self.binlog = Binlog(path=os.path.join(data_dir, "binlog.wal"))
            # change-stream + matview registries attach BEFORE recovery:
            # _recover re-arms persisted subscriptions and views against
            # the already-recovered binlog cursors
            self.cdc = ChangeStreams(self)
            self.matviews = MatViews(self)
            self._recover()
        else:
            self.binlog = Binlog()
            self.cdc = ChangeStreams(self)
            self.matviews = MatViews(self)

    def close(self) -> None:
        """Stop this Database's background machinery — today the fleet
        telemetry poller (auto-started in cluster mode), whose scrape RPCs
        would otherwise outlive a discarded Database, paying timeouts
        against dead daemon addresses forever.  Idempotent."""
        self.telemetry.stop()
        self.watchdog.stop()
        self.mvcc.stop_gc()

    def store(self, key: str) -> TableStore:
        return self.stores[key]

    @staticmethod
    def attach_aot_peer(meta_address: str) -> None:
        """Join the fleet AOT executable tier without full cluster mode:
        publish compiled artifacts to / warm-start from the store daemons
        behind this meta service (the cache tier is process-wide, so one
        attach serves every Database in the process)."""
        compilecache.AOT.attach_peer(meta_address)

    _BINLOG_RETRY_MAX = 1024    # queued batches PER TABLE; beyond, oldest drop

    def binlog_retry_queue(self, table_key: str) -> _TableBinlogRetry:
        """This table's retry state (created on first use)."""
        rq = self._binlog_retry.get(table_key)
        if rq is None:
            with self._binlog_retry_reg_mu:
                rq = self._binlog_retry.setdefault(table_key,
                                                   _TableBinlogRetry())
        return rq

    def binlog_retry_pending(self) -> list[str]:
        """Tables with queued retry batches (unlocked snapshot — callers
        take the per-table lock before acting)."""
        return [tk for tk, rq in list(self._binlog_retry.items()) if rq.q]

    def binlog_retry_depth(self, table_key: Optional[str] = None) -> int:
        """Queued batch count, per table or engine-wide (tests/metrics)."""
        if table_key is not None:
            rq = self._binlog_retry.get(table_key)
            return len(rq.q) if rq is not None else 0
        return sum(len(rq.q) for rq in list(self._binlog_retry.values()))

    def discard_binlog_retry(self, table_key: str) -> None:
        """Forget a DROPPED table's retry state: queued batches count as
        dropped (no table, no subscribers to replay to — retrying them
        forever against dist.append would be phantom CDC), and the registry
        entry goes away so the per-commit pending scan stays O(live tables)
        under create/drop churn."""
        with self._binlog_retry_reg_mu:
            rq = self._binlog_retry.pop(table_key, None)
        if rq is not None:
            with rq.mu:
                while rq.q:
                    metrics.binlog_events_dropped.add(len(rq.q.popleft()))

    def drain_binlog_retry(self, dist) -> None:
        """Re-attempt queued distributed-binlog appends, table by table.
        Thread-safe; tables are independent — one table's dead binlog
        region stops only ITS queue, never another table's."""
        for tk in self.binlog_retry_pending():
            rq = self.binlog_retry_queue(tk)
            with rq.mu:
                self._drain_rq_locked(rq, tk, dist)

    def _drain_rq_locked(self, rq: _TableBinlogRetry, table_key: str,
                         dist) -> None:
        """Arrival-order drain of ONE table's queue; the first failure stops
        it (the region is likely still down — later batches of this table
        must not jump the queue).  Caller holds rq.mu."""
        q = rq.q
        for _ in range(len(q)):
            events = q.popleft()
            try:
                dist.append(table_key, events)
            except Exception:   # noqa: BLE001
                q.appendleft(events)
                break

    def _queue_rq_locked(self, rq: _TableBinlogRetry, events: list) -> None:
        """Caller holds rq.mu."""
        rq.q.append(events)
        metrics.binlog_retry_queued.add(len(events))
        while len(rq.q) > self._BINLOG_RETRY_MAX:
            dropped = rq.q.popleft()
            metrics.binlog_events_dropped.add(len(dropped))

    def dist_binlog(self):
        """The cluster's distributed binlog writer (storage/binlog_regions)
        — None off the daemon plane or when binlog_regions is off."""
        if self.cluster is None:
            return None
        from ..storage.binlog_regions import DistributedBinlog

        if not FLAGS.binlog_regions:
            return None
        dl = getattr(self, "_dist_binlog", None)
        if dl is None:
            dl = self._dist_binlog = DistributedBinlog(self.cluster)
        return dl

    def cold_fs(self, required: bool = False):
        """The external cold-storage FS, or None when unconfigured."""
        if self._cold_fs is None:
            root = self.cold_dir or str(FLAGS.cold_fs_dir)
            if root:
                from ..storage.coldfs import ExternalFS

                self._cold_fs = ExternalFS(root)
        if required and self._cold_fs is None:
            raise PlanError("no cold storage configured (set cold_dir or "
                            "the cold_fs_dir flag)")
        return self._cold_fs

    def _new_store(self, info) -> TableStore:
        """A TableStore joined to this Database's MVCC plane (shared TSO
        clock + snapshot pin registry)."""
        st = TableStore(info)
        st.attach_mvcc(self.mvcc)
        return st

    def make_store(self, info) -> TableStore:
        """Create a table's store; durable (WAL-attached) under data_dir,
        raft-replicated when the Database is fleet-bound."""
        key = f"{info.database}.{info.name}"
        if self.fleet is not None:
            from ..storage.replicated import ReplicatedRowTier
            st = self._new_store(info)
            tier = ReplicatedRowTier.get_or_create(
                self.fleet, info.table_id, key, st._row_schema(),
                [ROWID_COL])
            fs = self.cold_fs()
            check_cold_readable(tier, fs, key)
            cold = tier.cold_rows(fs) if fs is not None else None
            hot = None
            if self.read_replica == "follower":
                hot = tier.follower_rows(max_lag=self.read_max_lag,
                                         resource_tag=self.read_tag)
            st.attach_replicated(tier, cold_rows=cold, hot_rows=hot)
            return st
        if self.cluster is not None:
            from ..storage.remote_tier import RemoteRowTier
            st = self._new_store(info)
            tier = RemoteRowTier.get_or_create(
                self.cluster, key, st._row_schema(), [ROWID_COL])
            fs = self.cold_fs()
            # checked eagerly even for a deferred attach: a frontend that
            # cannot read the cold tier must refuse the table at attach,
            # not at first query
            check_cold_readable(tier, fs, key)
            if not info.name.startswith("__") and \
                    _opt_on((info.options or {}).get("binlog")):
                # binlog is opt-in per table, like the reference's
                # link-to-binlog option (CREATE TABLE ... BINLOG=1):
                # unlinked tables keep 1PC write latency.  Hidden backing
                # tables (global-index, rollups) ride their main table's
                # events — a sink there would double-log
                st.binlog_sink = self.dist_binlog()
                # back-reference for the autocommit ordering guard: queued
                # retry batches must drain before a fresh autocommit CDC
                # event for the same table lands (column_store._write_hot)
                st.binlog_db = self
            if str(FLAGS.pushdown_reads) != "off":
                # defer the full-region pull: eligible SELECTs execute as
                # pushed fragments ON the store daemons (the reference's
                # read architecture); the image materializes only when a
                # query actually needs it
                st.attach_replicated_lazy(tier, fs)
                return st
            # one manifest fetch: cold_rows returns [] when no cold exists
            cold = tier.cold_rows(fs) if fs is not None else None
            st.attach_replicated(tier, cold_rows=cold)
            return st
        if not self.data_dir:
            return self._new_store(info)
        import os
        st = self._new_store(info)
        pq_dir = os.path.join(self.data_dir, key)
        if os.path.isdir(pq_dir):
            st.load_parquet(pq_dir)
        st.durable_dir = pq_dir
        st.attach_wal(os.path.join(self.data_dir, key + ".wal"))
        return st

    # -- durability -------------------------------------------------------
    def save_catalog(self):
        if not self.data_dir:
            return
        import json
        import os
        dbs = [d for d in self.catalog.databases()
               if d != "information_schema"]
        out = {"databases": dbs, "tables": []}
        for db in dbs:
            for t in self.catalog.tables(db):
                info = self.catalog.get_table(db, t)
                out["tables"].append({
                    "database": db, "name": t,
                    "fields": [[f.name, f.ltype.value, f.nullable]
                               for f in info.schema.fields],
                    "indexes": [[ix.name, ix.kind, list(ix.columns),
                                 {k: v for k, v in ix.params.items()
                                  if k != "fresh_at"}]   # refresh on restart
                                for ix in info.indexes],
                    "options": dict(info.options or {}),
                })
        vsnap = self.catalog._views      # ONE published dict: a concurrent
        #                                  DROP VIEW swaps the attr, never
        #                                  mutates this snapshot
        out["views"] = [
            {"database": k.split(".", 1)[0], "name": k.split(".", 1)[1], **v}
            for k, v in sorted(vsnap.items())
            if k.split(".", 1)[0] in dbs]
        out["subscriptions"] = self.cdc.to_meta()
        out["matviews"] = self.matviews.to_meta()
        tmp = os.path.join(self.data_dir, "catalog.json.tmp")
        with open(tmp, "w") as f:
            json.dump(out, f)
        os.replace(tmp, os.path.join(self.data_dir, "catalog.json"))

    def _recover(self):
        import json
        import os
        path = os.path.join(self.data_dir, "catalog.json")
        if not os.path.exists(path):
            return
        with open(path) as f:
            saved = json.load(f)
        for db in saved["databases"]:
            if db not in self.catalog.databases():
                self.catalog.create_database(db, if_not_exists=True)
        resume: list[tuple[str, IndexInfo]] = []
        for t in saved["tables"]:
            fields = tuple(Field(n, LType(v), nullable)
                           for n, v, nullable in t["fields"])
            indexes = [IndexInfo(ix[0], ix[1], ix[2],
                                 ix[3] if len(ix) > 3 else {})
                       for ix in t["indexes"]]
            info = self.catalog.create_table(
                t["database"], t["name"], Schema(fields), indexes,
                options=t["options"], if_not_exists=True)
            key = f"{t['database']}.{t['name']}"
            self.stores[key] = self.make_store(info)
            for ix in indexes:
                if ix.params.get("state") == "backfilling":
                    resume.append((key, ix))
        for v in saved.get("views", []):
            self.catalog.create_view(v["database"], v["name"], v["sql"],
                                     v.get("columns"), or_replace=True)
        # durable CDC cursors were recovered with the binlog; these entries
        # re-attach the subscription objects (and their GC holds) to them
        self.cdc.recover(saved.get("subscriptions"))
        self.matviews.recover(saved.get("matviews"))
        # resume interrupted backfills only AFTER every table is loaded:
        # the worker save_catalog()s at publish, and a snapshot taken
        # mid-recovery would persist a catalog missing later tables
        for key, ix in resume:
            self.ddl.submit(key, ix)

    def checkpoint(self):
        """Flush every table's live state to Parquet + reset WALs (the
        hot->cold flush boundary, region_olap.cpp:445)."""
        if not self.data_dir:
            raise RuntimeError("checkpoint requires a data_dir")
        import os
        for key, st in self.stores.items():
            st.checkpoint(os.path.join(self.data_dir, key))
        self.save_catalog()


class Session:
    def __init__(self, db: Optional[Database] = None, database: str = "default",
                 mesh=None, user: str = "root"):
        """``mesh``: a jax.sharding.Mesh with one axis — when set, every
        SELECT plans through plan/distribute.py and executes as a single
        shard_map program over the mesh (scans row-sharded across devices,
        exchanges as ICI collectives — the MPP mode, SURVEY §3.2).
        ``user``: the authenticated account; statements are checked against
        its grants (reference: privilege_manager + per-statement checks)."""
        self.db = db or Database()
        self.current_db = database
        self.user = user
        self.mesh = mesh
        # sharded device batches, keyed (table_key, version); stale versions
        # of a table are dropped on re-shard, so this is bounded by #tables
        self._mesh_batches: dict = {}
        # SQL-text-keyed compiled plans, LRU-bounded (FLAGS.plan_cache_size;
        # a long-lived server must not leak one executable per distinct
        # query text)
        self._plan_cache: OrderedDict = OrderedDict()
        # active SQL transaction: table_key -> storage TxnContext (row-tier
        # locks + buffered WAL writes + zero-copy region pre-images; the
        # reference's Transaction, src/engine/transaction.cpp:98-396)
        self._sql_txn: Optional[dict] = None
        # session variables (@vars + per-session system vars via SET)
        self.session_vars: dict = {}
        # binlog events buffered until COMMIT (discarded on ROLLBACK) so CDC
        # subscribers never see uncommitted changes
        self._txn_binlog: list = []
        # PREPARE name FROM '...' bodies (text, re-parsed per EXECUTE; the
        # auto-parameterized plan cache dedups the compiled executables)
        self._prepared: dict[str, str] = {}
        # explicit MVCC snapshot (SET SNAPSHOT): (pin_id, snap_ts) in the
        # Database's pin registry, or None.  Automatic analytical pins are
        # per-SELECT (scoped inside _select) and never land here.
        self._snapshot: Optional[tuple[int, int]] = None
        # the snapshot ts the CURRENT query runs at (0 = unpinned read) —
        # query_log / EXPLAIN ANALYZE read it; set per-SELECT
        self._snap_ts: int = 0

    def _log_binlog(self, event_type, db_name, table, rows=None, statement="",
                    affected=0):
        if rows and len(rows) > 1000:
            # bulk ingest: statement image only (avoid O(n) python row images)
            statement = statement or f"bulk insert {len(rows)} rows"
            rows = None
        if self._sql_txn is not None:
            self._txn_binlog.append((event_type, db_name, table, rows,
                                     statement, affected))
            return
        self.db.binlog.append(event_type, db_name, table, rows=rows,
                              statement=statement, affected=affected)

    # -- access control ---------------------------------------------------
    def _stmt_dbs(self, s) -> set[str]:
        """Databases a SELECT reads — FROM/joins/CTEs/unions AND expression
        subqueries (WHERE/items/HAVING), so a subquery can't read around the
        grants (coarse db-granular enforcement like the reference's)."""
        from ..expr.ast import Subquery

        out: set[str] = set()

        def walk_expr(e):
            if e is None:
                return
            if isinstance(e, Subquery):
                walk_sel(e.stmt)
                return
            for a in getattr(e, "args", ()):
                walk_expr(a)

        def walk_sel(st):
            refs = ([st.table] if st.table is not None else []) + \
                   [j.table for j in st.joins]
            for r in refs:
                if r.subquery is not None:
                    walk_sel(r.subquery)
                else:
                    out.add(r.database or self.current_db)
            for j in st.joins:
                walk_expr(j.on)
            for it in st.items:
                walk_expr(it.expr)
            walk_expr(st.where)
            walk_expr(st.having)
            for _, sub in st.ctes:
                walk_sel(sub)
            if st.union is not None:
                walk_sel(st.union[1])

        walk_sel(s)
        return out or {self.current_db}

    def _access_check(self, s):
        P = self.db.privileges
        if isinstance(s, (CreateUserStmt, DropUserStmt, GrantStmt,
                          RevokeStmt, HandleStmt)):
            u = P.users.get(self.user)
            if u is None or not u.is_super:
                raise AccessError(f"{type(s).__name__} requires SUPER")
            return
        if isinstance(s, SelectStmt):
            for db in self._stmt_dbs(s):
                P.check(self.user, db, READ)
            return
        if isinstance(s, (InsertStmt, UpdateStmt, DeleteStmt, TruncateStmt,
                          LoadDataStmt)):
            P.check(self.user, s.table.database or self.current_db, WRITE)
            # reads feeding the write are grants too (INSERT..SELECT,
            # subqueries in WHERE/assignments)
            if isinstance(s, InsertStmt) and s.select is not None:
                for db in self._stmt_dbs(s.select):
                    P.check(self.user, db, READ)
            from ..expr.ast import Subquery

            def sub_dbs(e):
                if e is None:
                    return
                if isinstance(e, Subquery):
                    for db in self._stmt_dbs(e.stmt):
                        P.check(self.user, db, READ)
                    return
                for a in getattr(e, "args", ()):
                    sub_dbs(a)

            sub_dbs(getattr(s, "where", None))
            for _, e in getattr(s, "assignments", []) or []:
                sub_dbs(e)
            return
        if isinstance(s, (CreateTableStmt, DropTableStmt, AlterTableStmt,
                          CreateViewStmt, DropViewStmt, CreateMatViewStmt,
                          DropMatViewStmt)):
            P.check(self.user, s.table.database or self.current_db, WRITE)
            return
        if isinstance(s, (CreateSubscriptionStmt, DropSubscriptionStmt)):
            db = (s.table.database if getattr(s, "table", None) is not None
                  else None) or self.current_db
            P.check(self.user, db, READ)
            return
        if isinstance(s, CreateDatabaseStmt):
            P.check(self.user, s.name, WRITE)
            return
        if isinstance(s, DropDatabaseStmt):
            P.check(self.user, s.name, WRITE)
            return
        if isinstance(s, UseStmt):
            P.check(self.user, s.database, READ)
            return
        if isinstance(s, ExplainStmt):
            for db in self._stmt_dbs(s.stmt):
                P.check(self.user, db, READ)
            return
        if isinstance(s, DescribeStmt):
            P.check(self.user, s.table.database or self.current_db, READ)
            return
        if isinstance(s, ShowStmt):
            # SHOW against another db needs a grant THERE, not on current
            db = s.database or (s.table.database if s.table is not None
                                else None) or self.current_db
            P.check(self.user, db, READ)

    # -- public API -------------------------------------------------------
    def connection_id(self) -> int:
        """This session's id in the shared processlist/KILL space, lazily
        assigned from the same counter the wire server draws from."""
        if not hasattr(self, "_conn_id"):
            self._conn_id = next_conn_id()
        return self._conn_id

    def execute(self, sql: str) -> Result:
        metrics.queries_total.add(1)
        t0 = time.perf_counter()
        marks = metric_marks()   # flight-recorder metric baseline
        err: Optional[BaseException] = None
        spans: list = []
        # the progress record opens here (or at the wire server's _query,
        # whichever ran first — nested opens share the outer record); live
        # for the statement's whole life so SHOW PROCESSLIST, the watchdog
        # and KILL from other threads can see it
        with progress.track(sql, conn_id=self.connection_id(),
                            user=self.user, db=self.db,
                            dbname=self.current_db) as qp:
            try:
                # the per-query trace roots here (or at the wire server's
                # _query, whichever ran first); stage spans nest under it and
                # the keep/drop decision (sampling + slow always-keep) lands
                # when this scope closes (obs/trace.py)
                tmark = trace.mark()
                with trace.root("query", sql):
                    try:
                        res = self._execute(sql)
                    finally:
                        # live-buffer snapshot must happen before the root
                        # closes (the ctx dies with it)
                        spans = trace.since(tmark)
            except Exception as e:
                metrics.queries_failed.add(1)
                err = e
                raise
            finally:
                dur_ms = (time.perf_counter() - t0) * 1e3
                metrics.query_latency.observe(dur_ms)
                if dur_ms > FLAGS.slow_query_ms:
                    metrics.slow_queries.add(1)
                self._flight_record(sql, qp, dur_ms, err, marks, spans)
        if res.arrow is not None:
            metrics.rows_returned.add(res.arrow.num_rows)
        if res.affected_rows:
            metrics.dml_rows.add(res.affected_rows)
        return res

    def _flight_record(self, sql: str, qp, dur_ms: float,
                       err: Optional[BaseException], marks: dict,
                       spans: list) -> None:
        """Flight-recorder entry for the statement that just finished: a
        summary always, plus the full forensic bundle (plan, trace spans,
        metric deltas, device stats, exchange summary) when the query was
        slow, killed, or failed — the three cases an operator digs into
        after the fact."""
        try:
            killed = isinstance(err, QueryKilled)
            slow = dur_ms > float(FLAGS.slow_query_ms)
            summary = {
                "query_id": getattr(qp, "query_id", 0),
                "conn_id": getattr(qp, "conn_id", 0),
                "user": self.user, "db": self.current_db,
                "text": sql, "dur_ms": round(dur_ms, 3),
                "status": ("killed" if killed else
                           "error" if err is not None else "ok"),
                "error": "" if err is None else
                         f"{type(err).__name__}: {err}",
                "phase_ms": {k: round(v, 3)
                             for k, v in qp.phase_ms().items()},
                "rows": getattr(qp, "rows_done", 0),
                "batches": getattr(qp, "batches_done", 0),
                "rounds": getattr(qp, "round_no", 0),
            }
            bundle = None
            if killed or err is not None or slow:
                plan = getattr(qp, "plan", None)
                bundle = {
                    "plan": (plan.tree_repr() if hasattr(plan, "tree_repr")
                             else str(plan)) if plan is not None else "",
                    "spans": spans,
                    "metric_delta": metric_delta(marks),
                    "device_stats": device_stats(),
                    "exchange": getattr(qp, "exchange", None),
                }
            self.db.flightrec.record(summary, bundle=bundle)
        except Exception:
            # forensics must never turn a working query into a failed one
            metrics.count_swallowed("session.flight_record")

    def _execute(self, sql: str) -> Result:
        progress.current().beat(phase="parse")
        with trace.span("parse"):
            stmts = parse_sql(sql)
        if self.db.qos is not None:
            # COMMIT/ROLLBACK are exempt: shedding load must never pin open
            # transactions; batches are charged per statement
            billable = sum(1 for s in stmts if not isinstance(s, TxnStmt))
            if billable:
                self.db.qos.admit(sql, cost=float(billable),
                                  user=self.user,
                                  tables=self._qos_tables(stmts))
        if len(stmts) == 1 and isinstance(stmts[0], SelectStmt):
            self._access_check(stmts[0])
            stmt, env = self._resolve_session_exprs(stmts[0])
            # env-substituted literals are session state: never cache those
            return self._select(stmt, cache_key=None if env
                                else (sql, self.current_db))
        res = Result()
        for s in stmts:
            # check immediately before EACH statement: an earlier USE in the
            # same batch changes what an unqualified name resolves to
            self._access_check(s)
            res = self._execute_stmt(s)
        return res

    def query(self, sql: str) -> list[dict]:
        return self.execute(sql).to_pylist()

    def _qos_tables(self, stmts) -> tuple:
        """Base tables a statement batch touches directly (FROM/joins/DML
        target) — the per-table admission dimension.  Deliberately shallow:
        qos gating is a rate limiter, not an access-control wall, so
        subquery tables may ride free."""
        out: list[str] = []
        for s in stmts:
            for t in [getattr(s, "table", None)] + \
                    [j.table for j in getattr(s, "joins", ()) or ()]:
                if t is not None and getattr(t, "subquery", None) is None \
                        and getattr(t, "name", None):
                    out.append(f"{t.database or self.current_db}.{t.name}")
        return tuple(dict.fromkeys(out))

    def _sysvar(self, name: str):
        """@@name lookup: session SETs override server defaults; live flags
        are visible too (they appear in SHOW VARIABLES)."""
        if name in ("tx_isolation", "transaction_isolation"):
            # the two spellings are one variable in MySQL: a SET of either
            # must be visible through both
            for k in ("transaction_isolation", "tx_isolation"):
                if k in self.session_vars:
                    return self.session_vars[k]
            return _SERVER_VARS[name]
        if name in self.session_vars:
            return self.session_vars[name]
        if name in _SERVER_VARS:
            if name == "autocommit":
                return 1 if self.session_vars.get("autocommit",
                                                  "ON") in ("ON", 1) else 0
            return _SERVER_VARS[name]
        flags = FLAGS.snapshot()
        if name in flags:
            return flags[name]
        raise SqlError(f"Unknown system variable '{name}'")

    def _resolve_session_exprs(self, stmt):
        """Substitute connection-environment expressions — @@sysvars, @user
        vars, DATABASE()/USER()/VERSION()/CONNECTION_ID() — with literals
        before planning (reference: these never reach the executor in the
        reference either; the protocol layer answers them).  Returns
        (stmt, changed); changed disables the plan cache for the statement
        since the substituted values are session state."""
        from ..expr.ast import AggCall, Call, Lit, Subquery, WindowCall
        from ..sql.stmt import SelectStmt
        changed = [False]

        def lit(v):
            changed[0] = True
            return Lit(v)

        def walk_e(e):
            if isinstance(e, Call):
                if e.op == "__sysvar__":
                    return lit(self._sysvar(e.args[0].value))
                if e.op == "__uservar__":
                    return lit(self.session_vars.get("@" + e.args[0].value))
                if e.op in ("database", "schema") and not e.args:
                    return lit(self.current_db or None)
                if e.op in ("user", "current_user", "session_user",
                            "system_user") and not e.args:
                    return lit(f"{self.user}@localhost")
                if e.op == "connection_id" and not e.args:
                    return lit(self.connection_id())
                if e.op == "version" and not e.args:
                    return lit(_SERVER_VARS["version"])
                return Call(e.op, tuple(walk_e(a) for a in e.args))
            if isinstance(e, AggCall):
                return AggCall(e.op, tuple(walk_e(a) for a in e.args),
                               e.distinct)
            if isinstance(e, WindowCall):
                return WindowCall(
                    e.op, tuple(walk_e(a) for a in e.args),
                    tuple(walk_e(p) for p in e.partition_by),
                    tuple((walk_e(oe), asc) for oe, asc in e.order_by),
                    e.running, e.frame)
            if isinstance(e, Subquery):
                return Subquery(walk_s(e.stmt))
            return e

        def opt(e):
            return None if e is None else walk_e(e)

        def walk_s(st: SelectStmt) -> SelectStmt:
            from dataclasses import replace
            from ..sql.stmt import OrderItem, SelectItem
            def walk_t(t):
                if t is not None and t.subquery is not None:
                    return replace(t, subquery=walk_s(t.subquery))
                return t

            return replace(
                st,
                items=[SelectItem(opt(it.expr),
                                  it.alias or _env_alias(it.expr),
                                  it.star_table) for it in st.items],
                table=walk_t(st.table),
                where=opt(st.where),
                group_by=[walk_e(g) for g in st.group_by],
                having=opt(st.having),
                order_by=[OrderItem(walk_e(o.expr), o.asc)
                          for o in st.order_by],
                joins=[replace(j, table=walk_t(j.table), on=opt(j.on))
                       for j in st.joins],
                ctes=[(n, walk_s(c)) for n, c in st.ctes],
                union=None if st.union is None
                else (st.union[0], walk_s(st.union[1])))

        from dataclasses import replace as _rep
        from ..sql.stmt import DeleteStmt, InsertStmt, UpdateStmt
        if isinstance(stmt, SelectStmt):
            out = walk_s(stmt)
        elif isinstance(stmt, UpdateStmt):
            out = _rep(stmt, assignments=[(n, walk_e(e))
                                          for n, e in stmt.assignments],
                       where=opt(stmt.where))
        elif isinstance(stmt, DeleteStmt):
            out = _rep(stmt, where=opt(stmt.where))
        elif isinstance(stmt, InsertStmt) and stmt.select is not None:
            out = _rep(stmt, select=walk_s(stmt.select))
        else:
            return (stmt, False)
        return (out, True) if changed[0] else (stmt, False)

    def _set_stmt(self, s: SetStmt) -> Result:
        """SET (reference: setkv_planner.cpp): GLOBAL names update the flag
        registry (and fire its listeners); ``failpoint.<point>`` arms/clears
        the chaos registry (process-global regardless of scope — fault
        injection is a deployment property, not a session one); @vars and
        unknown session names (autocommit, sql_mode, ...) are stored
        per-session — MySQL clients set those on connect and expect silent
        success."""
        from ..utils.flags import FlagError
        for name, value in [(s.name, s.value)] + list(s.more):
            if name.lower() == "snapshot":
                self._set_snapshot(value)
                continue
            if name.lower().startswith("failpoint."):
                from ..chaos import failpoint as _fp
                spec = "" if value is None else str(value)
                if spec.strip().lower() not in ("", "off") and \
                        not bool(FLAGS.chaos_enable):
                    # chaos_enable is the real master switch at the SQL
                    # surface: any connected client can reach SET, and an
                    # armed panic/drop is destructive — clearing is always
                    # allowed, arming needs the deployment to opt in
                    raise SqlError("failpoints are disabled: "
                                   "SET GLOBAL chaos_enable = 1 first")
                try:
                    _fp.set_failpoint(name.lower()[len("failpoint."):],
                                      spec)
                except ValueError as e:
                    raise SqlError(str(e)) from None
                continue
            if s.scope == "global":
                try:
                    FLAGS.set_flag(name, value)
                except FlagError as e:
                    raise SqlError(str(e)) from None
            else:
                self.session_vars[name] = value
        return Result()

    def _set_snapshot(self, value) -> None:
        """SET SNAPSHOT = 'now' | <ts> | 0/''/OFF — pin (or release) this
        session's MVCC read timestamp.  Every subsequent SELECT sees
        exactly the state committed at the pinned instant, regardless of
        concurrent writes; the pin holds the GC watermark until released
        (or it expires past ``snapshot_max_age_s``).  Refusals from the
        ``snapshot.pin`` failpoint surface to the client — an explicit pin
        must not silently degrade to an unpinned read."""
        from ..storage.mvcc import SnapshotRefused
        raw = "" if value is None else str(value).strip()
        if raw.lower() in ("", "0", "off", "none"):
            if self._snapshot is not None:
                self.db.mvcc.snapshots.unpin(self._snapshot[0])
                self._snapshot = None
            return
        if not bool(FLAGS.mvcc):
            raise SqlError("SET SNAPSHOT requires mvcc=1")
        if raw.lower() == "now":
            ts = self.db.mvcc.now_ts()
        else:
            try:
                ts = int(raw)
            except ValueError:
                raise SqlError(
                    f"SET SNAPSHOT expects 'now', a timestamp, or 0/OFF "
                    f"(got {raw!r})") from None
        try:
            with trace.span("snapshot.pin", ts=ts, explicit=True):
                pid = self.db.mvcc.snapshots.pin(
                    ts, query="SET SNAPSHOT", holder=self.user)
        except SnapshotRefused as e:
            raise SqlError(str(e)) from None
        if self._snapshot is not None:
            self.db.mvcc.snapshots.unpin(self._snapshot[0])
        self._snapshot = (pid, ts)

    # -- prepared statements (textual PREPARE/EXECUTE; the wire server's
    # COM_STMT_* path binds ?s into text and rides the same normalizer) ----
    def _prepare_stmt(self, s: PrepareStmt) -> Result:
        stmts = parse_sql(s.sql)
        if len(stmts) != 1:
            raise PlanError("PREPARE body must be a single statement")
        if not isinstance(stmts[0], (SelectStmt, InsertStmt, UpdateStmt,
                                     DeleteStmt)):
            raise PlanError("PREPARE supports SELECT/INSERT/UPDATE/DELETE")
        self._prepared[s.name] = s.sql
        return Result()

    def _execute_prepared(self, s: ExecuteStmt) -> Result:
        sql = self._prepared.get(s.name)
        if sql is None:
            raise PlanError(f"unknown prepared statement {s.name!r}")
        vals = [self.session_vars.get("@" + v) if kind == "var" else v
                for kind, v in s.params]
        stmt = parse_sql(sql)[0]
        need = paramize.count_placeholders(stmt)
        if need != len(vals):
            raise PlanError(f"prepared statement {s.name!r} needs {need} "
                            f"parameters, got {len(vals)}")
        bound = paramize.substitute_placeholders(stmt, vals)
        metrics.prepared_executes.add(1)
        self._access_check(bound)
        if isinstance(bound, SelectStmt):
            bound, env = self._resolve_session_exprs(bound)
            # the text key carries the bound values: distinct values that
            # land in PINNED positions (IN lists, LIMIT) must not collide;
            # hoistable values collapse onto one normalized entry anyway
            key = None if env else \
                (f"{sql} /*execute:{vals!r}*/", self.current_db)
            return self._select(bound, cache_key=key)
        return self._execute_stmt(bound)

    # -- dispatch -----------------------------------------------------------
    def _execute_stmt(self, s) -> Result:
        # DDL implicitly commits any open transaction (MySQL semantics);
        # rolling back across a schema change is not supported
        if isinstance(s, (CreateTableStmt, DropTableStmt, CreateDatabaseStmt,
                          DropDatabaseStmt, TruncateStmt, AlterTableStmt,
                          CreateViewStmt, DropViewStmt, CreateMatViewStmt,
                          DropMatViewStmt)):
            self._commit_txn()
        if isinstance(s, PrepareStmt):
            return self._prepare_stmt(s)
        if isinstance(s, ExecuteStmt):
            return self._execute_prepared(s)
        if isinstance(s, DeallocateStmt):
            if s.name not in self._prepared:
                raise PlanError(f"unknown prepared statement {s.name!r}")
            del self._prepared[s.name]
            return Result()
        if isinstance(s, (SelectStmt, UpdateStmt, DeleteStmt, InsertStmt)):
            # connection-env expressions are legal anywhere MySQL allows
            # an expression — DML included
            s = self._resolve_session_exprs(s)[0]
        if isinstance(s, SelectStmt):
            return self._select(s)
        if isinstance(s, ExplainStmt):
            if s.fmt == "analyze":
                return self._explain_analyze(s.stmt)
            stmt_x = s.stmt
            cand = self._pushdown_candidate(stmt_x)
            if cand is not None:
                txt = self._render_pushdown(*cand)
                return Result(columns=["plan"], plan_text=txt,
                              arrow=pa.table({"plan": txt.split("\n")}))
            rw = self._try_matview(stmt_x, refresh=False)
            if rw is None:
                rw = self._try_rollup(stmt_x, refresh=False)
            if rw is not None:
                stmt_x = rw
            plan = self._plan_select(stmt_x)
            self._annotate_access(plan)
            return Result(columns=["plan"], plan_text=plan.tree_repr(),
                          arrow=pa.table({"plan": plan.tree_repr().split("\n")}))
        if isinstance(s, InsertStmt):
            return self._insert(s)
        if isinstance(s, UpdateStmt):
            return self._update(s)
        if isinstance(s, DeleteStmt):
            return self._delete(s)
        if isinstance(s, CreateTableStmt):
            return self._create_table(s)
        if isinstance(s, CreateViewStmt):
            db = s.table.database or self.current_db
            prior = self.db.catalog.get_view(db, s.table.name)
            try:
                self.db.catalog.create_view(db, s.table.name, s.select_sql,
                                            s.columns, s.or_replace)
            except ValueError as e:
                raise PlanError(str(e)) from None
            # a view shadows nothing but must PLAN against current tables:
            # surface body errors at CREATE, like the reference's validator
            try:
                self._plan_select(parse_sql(
                    f"SELECT * FROM `{db}`.`{s.table.name}`")[0])
            except Exception:
                # a failed OR REPLACE keeps the previous definition (MySQL)
                if prior is not None:
                    self.db.catalog.create_view(db, s.table.name,
                                                prior["sql"],
                                                prior.get("columns"),
                                                or_replace=True)
                else:
                    self.db.catalog.drop_view(db, s.table.name,
                                              if_exists=True)
                raise
            self._plan_cache.clear()
            self.db.save_catalog()
            return Result()
        if isinstance(s, DropViewStmt):
            db = s.table.database or self.current_db
            try:
                self.db.catalog.drop_view(db, s.table.name, s.if_exists)
            except ValueError as e:
                raise PlanError(str(e)) from None
            self._plan_cache.clear()
            self.db.save_catalog()
            return Result()
        if isinstance(s, CreateMatViewStmt):
            db = s.table.database or self.current_db
            try:
                self.db.matviews.create(self, db, s.table.name,
                                        s.select_sql, s.if_not_exists)
            except ValueError as e:
                raise PlanError(str(e)) from None
            self._plan_cache.clear()
            return Result()
        if isinstance(s, DropMatViewStmt):
            db = s.table.database or self.current_db
            self.db.matviews.drop(self, db, s.table.name, s.if_exists)
            self._plan_cache.clear()
            return Result()
        if isinstance(s, CreateSubscriptionStmt):
            table_key = None
            if s.table is not None:
                tdb = s.table.database or self.current_db
                # surface unknown tables at CREATE, not at first FETCH
                self.db.catalog.get_table(tdb, s.table.name)
                table_key = f"{tdb}.{s.table.name}"
            try:
                self.db.cdc.create(s.name, table_key,
                                   if_not_exists=s.if_not_exists)
            except ValueError as e:
                raise PlanError(str(e)) from None
            self.db.save_catalog()
            return Result()
        if isinstance(s, DropSubscriptionStmt):
            try:
                sub = self.db.cdc.subs.get(s.name)
                if sub is not None and sub.internal:
                    raise PlanError(
                        f"subscription {s.name!r} maintains a materialized "
                        "view; drop the view instead")
                self.db.cdc.drop(s.name, s.if_exists)
            except KeyError as e:
                raise PlanError(str(e.args[0])) from None
            self.db.save_catalog()
            return Result()
        if isinstance(s, FetchStmt):
            return self._fetch_stmt(s)
        if isinstance(s, AlterTableStmt):
            return self._alter_table(s)
        if isinstance(s, DropTableStmt):
            from ..index.globalindex import backing_table_name
            from ..index.rollup import rollup_table_name
            db = s.table.database or self.current_db
            rollups, globals_ = [], []
            if self.db.catalog.has_table(db, s.table.name):
                info = self.db.catalog.get_table(db, s.table.name)
                rollups = [ix.name for ix in info.indexes
                           if ix.kind == "rollup"]
                globals_ = [ix.name for ix in info.indexes
                            if ix.kind in ("global", "global_unique")]
            self.db.catalog.drop_table(db, s.table.name, s.if_exists)
            st = self.db.stores.pop(f"{db}.{s.table.name}", None)
            self._drop_durable(f"{db}.{s.table.name}", st)
            self.db.discard_binlog_retry(f"{db}.{s.table.name}")
            # matviews over the dropped base go with it (cascade), like
            # rollups and global indexes below
            self.db.matviews.drop_for_base(self, f"{db}.{s.table.name}")
            for rn in rollups:
                rt = rollup_table_name(s.table.name, rn)
                self.db.catalog.drop_table(db, rt, if_exists=True)
                self._drop_durable(f"{db}.{rt}",
                                   self.db.stores.pop(f"{db}.{rt}", None))
            for gn in globals_:
                gt = backing_table_name(s.table.name, gn)
                self.db.catalog.drop_table(db, gt, if_exists=True)
                self._drop_durable(f"{db}.{gt}",
                                   self.db.stores.pop(f"{db}.{gt}", None))
            self.db.save_catalog()
            return Result()
        if isinstance(s, TruncateStmt):
            store = self._store(s.table)
            store.truncate()
            for _ix, bstore in self._coupled_global(store):
                bstore.truncate()   # global-index entries go with the rows
            self._log_binlog("truncate", s.table.database or self.current_db,
                             s.table.name, statement="truncate")
            return Result()
        if isinstance(s, CreateDatabaseStmt):
            self.db.catalog.create_database(s.name, if_not_exists=s.if_not_exists)
            self.db.save_catalog()
            return Result()
        if isinstance(s, DropDatabaseStmt):
            self.db.catalog.drop_database(s.name, s.if_exists)
            for k in [k for k in self.db.stores if k.startswith(s.name + ".")]:
                self._drop_durable(k, self.db.stores.pop(k))
                self.db.discard_binlog_retry(k)
            self.db.save_catalog()
            return Result()
        if isinstance(s, UseStmt):
            if s.database not in self.db.catalog.databases():
                raise PlanError(f"unknown database {s.database!r}")
            self.current_db = s.database
            return Result()
        if isinstance(s, SetStmt):
            return self._set_stmt(s)
        if isinstance(s, TxnStmt):
            return self._txn_stmt(s)
        if isinstance(s, ShowStmt):
            return self._show(s)
        if isinstance(s, KillStmt):
            return self._kill(s)
        if isinstance(s, CreateUserStmt):
            self.db.privileges.create_user(s.name, s.password, s.if_not_exists)
            return Result()
        if isinstance(s, DropUserStmt):
            self.db.privileges.drop_user(s.name, s.if_exists)
            return Result()
        if isinstance(s, GrantStmt):
            self.db.privileges.grant(s.user, s.level, s.db)
            return Result()
        if isinstance(s, RevokeStmt):
            self.db.privileges.revoke(s.user, s.db)
            return Result()
        if isinstance(s, LoadDataStmt):
            return self._load_data(s)
        if isinstance(s, HandleStmt):
            return self._handle(s)
        if isinstance(s, DescribeStmt):
            db = s.table.database or self.current_db
            if self.db.catalog.get_view(db, s.table.name) is not None:
                # DESCRIBE on a view: plan the view body (no execution) and
                # read the root node's output schema — logical type names
                # match what tables report (MySQL describes views alike)
                stmt = parse_sql(
                    f"SELECT * FROM `{db}`.`{s.table.name}`")[0]
                fields = self._plan_select(stmt).schema.fields
                return Result(
                    columns=["Field", "Type", "Null", "Key"],
                    arrow=pa.table({
                        "Field": [f.name for f in fields],
                        "Type": [f.ltype.value for f in fields],
                        "Null": ["YES" if f.nullable else "NO"
                                 for f in fields],
                        "Key": [""] * len(fields)}))
            info = self.db.catalog.get_table(db, s.table.name)
            pk = info.primary_key()
            pkcols = set(pk.columns) if pk else set()
            vcols = (info.options or {}).get("vector_cols") or {}
            names, types, nulls, keys = [], [], [], []
            for f in info.schema.fields:
                owner = _component_owner(f.name, vcols)
                if owner is not None:
                    if not names or names[-1] != owner:
                        names.append(owner)
                        types.append(f"vector({vcols[owner]})")
                        nulls.append("YES")
                        keys.append("")
                    continue
                names.append(f.name)
                types.append(f.ltype.value)
                nulls.append("YES" if f.nullable else "NO")
                keys.append("PRI" if f.name in pkcols else "")
            return Result(columns=["Field", "Type", "Null", "Key"],
                          arrow=pa.table({"Field": names, "Type": types,
                                          "Null": nulls, "Key": keys}))
        raise SqlError(f"unsupported statement {type(s).__name__}")

    # -- SHOW / admin surface ---------------------------------------------
    def _show_profile(self, s: ShowStmt) -> Result:
        """SHOW PROFILES / SHOW PROFILE [FOR QUERY n] over the kept trace
        store (obs/trace.py) — the per-stage answer to "where did this
        query's time go", reading the SAME span records EXPLAIN ANALYZE
        renders from."""
        # introspection must not pollute the store it reads: never keep
        # the trace of the SHOW statement itself
        trace.discard()
        if s.what == "profiles":
            recs = TRACER.list()
            return Result(
                columns=["Query_ID", "Duration_ms", "Kind", "Query"],
                arrow=pa.table({
                    "Query_ID": pa.array([r["query_id"] for r in recs],
                                         pa.int64()),
                    "Duration_ms": pa.array([r["duration_ms"] for r in recs],
                                            pa.float64()),
                    "Kind": [r["kind"] for r in recs],
                    "Query": [r["text"] for r in recs]}))
        rec = TRACER.get(s.query_id) if s.query_id is not None \
            else TRACER.last()
        if rec is None:
            where = f"query {s.query_id}" if s.query_id is not None \
                else "any query"
            raise PlanError(
                f"no kept trace for {where} (enable tracing: "
                "SET GLOBAL tracing = 1; see SHOW PROFILES)")
        rows = trace.span_tree(rec)
        return Result(
            columns=["Status", "Duration_ms", "Node"],
            arrow=pa.table({
                "Status": ["  " * d + sp["name"] for d, sp in rows],
                "Duration_ms": pa.array([sp["dur_ms"] for _, sp in rows],
                                        pa.float64()),
                "Node": [sp.get("node") or "frontend" for _, sp in rows]}))

    def _show(self, s: ShowStmt) -> Result:
        """SHOW command family (reference: show_helper.cpp's registry)."""
        def like(name: str, pat: str) -> bool:
            # MySQL LIKE for SHOW ... LIKE: case-insensitive; wildcard and
            # \-escape translation shared with expression-level LIKE
            return _show_like_rx(pat).match(name) is not None

        def visible(db):
            # user-facing tables + views: rollup and global-index backing
            # tables are internal
            from ..cdc.views import is_mv_table
            from ..index.globalindex import is_backing_table
            from ..index.rollup import is_rollup_table
            return ([n for n in cat.tables(db) if not is_rollup_table(n)
                     and not is_backing_table(n) and not is_mv_table(n)],
                    list(cat.views(db)))

        cat = self.db.catalog
        if s.what in ("profile", "profiles"):
            return self._show_profile(s)
        if s.what == "databases":
            names = cat.databases()
            return Result(columns=["Database"],
                          arrow=pa.table({"Database": names}))
        if s.what == "tables":
            db = s.database or self.current_db
            tbls, views = visible(db)
            names = sorted(tbls + views)   # MySQL lists views too
            if s.pattern is not None:
                names = [n for n in names if like(n, s.pattern)]
            return Result(columns=[f"Tables_in_{db}"],
                          arrow=pa.table({f"Tables_in_{db}": names}))
        if s.what == "full_tables":
            db = s.database or self.current_db
            tbls, views = visible(db)
            all_names = sorted(tbls + views)
            if s.pattern is not None:
                all_names = [n for n in all_names if like(n, s.pattern)]
            vset = set(views)
            return Result(
                columns=[f"Tables_in_{db}", "Table_type"],
                arrow=pa.table({
                    f"Tables_in_{db}": all_names,
                    "Table_type": ["VIEW" if n in vset else "BASE TABLE"
                                   for n in all_names]}))
        if s.what == "collation":
            # the collations the engine actually implements (reference:
            # show_helper.cpp _show_collation; comparisons support _bin
            # semantics by default and utf8mb4_general_ci via COLLATE)
            rows = [("utf8mb4_bin", "utf8mb4", 46, "Yes"),
                    ("utf8mb4_general_ci", "utf8mb4", 45, ""),
                    ("binary", "binary", 63, "Yes")]
            if s.pattern is not None:
                rows = [r for r in rows if like(r[0], s.pattern)]
            return Result(
                columns=["Collation", "Charset", "Id", "Default",
                         "Compiled", "Sortlen"],
                arrow=pa.table({
                    "Collation": [r[0] for r in rows],
                    "Charset": [r[1] for r in rows],
                    "Id": pa.array([r[2] for r in rows], pa.int64()),
                    "Default": [r[3] for r in rows],
                    "Compiled": ["Yes"] * len(rows),
                    "Sortlen": pa.array([1] * len(rows), pa.int64()),
                }))
        if s.what == "charset":
            rows = [("utf8mb4", "UTF-8 Unicode", "utf8mb4_bin", 4),
                    ("binary", "Binary pseudo charset", "binary", 1)]
            if s.pattern is not None:
                rows = [r for r in rows if like(r[0], s.pattern)]
            return Result(
                columns=["Charset", "Description", "Default collation",
                         "Maxlen"],
                arrow=pa.table({
                    "Charset": [r[0] for r in rows],
                    "Description": [r[1] for r in rows],
                    "Default collation": [r[2] for r in rows],
                    "Maxlen": pa.array([r[3] for r in rows], pa.int64()),
                }))
        if s.what == "engines":
            return Result(
                columns=["Engine", "Support", "Comment", "Transactions",
                         "XA", "Savepoints"],
                arrow=pa.table({
                    "Engine": ["BaikalTPU"],
                    "Support": ["DEFAULT"],
                    "Comment": ["TPU-native columnar HTAP engine (JAX/XLA)"],
                    "Transactions": ["YES"],
                    "XA": ["NO"],
                    "Savepoints": ["YES"]}))
        if s.what == "table_status":
            db = s.database or self.current_db
            tbls, views = visible(db)
            if s.pattern is not None:   # filter names before the per-table store scans
                tbls = [n for n in tbls if like(n, s.pattern)]
                views = [n for n in views if like(n, s.pattern)]
            rows = []
            for n in tbls:
                # don't force-materialize stores for a metadata listing
                # (fleet/cluster tiers, cold segments, WAL attach): a table
                # this frontend hasn't touched reports Rows=NULL (MySQL
                # treats Rows as an estimate; NULL = unknown)
                st = self.db.stores.get(f"{db}.{n}")
                nrows = st.num_rows if st is not None else None
                info = cat.get_table(db, n)
                pspec = (info.options or {}).get("partition")
                rows.append((n, "BaikalTPU", nrows,
                             "partitioned" if pspec else "", ""))
            for n in views:
                rows.append((n, None, None, "", "VIEW"))
            rows.sort(key=lambda r: r[0])
            return Result(
                columns=["Name", "Engine", "Rows", "Collation",
                         "Create_options", "Comment"],
                arrow=pa.table({
                    "Name": [r[0] for r in rows],
                    "Engine": pa.array([r[1] for r in rows], pa.string()),
                    "Rows": pa.array([r[2] for r in rows], pa.int64()),
                    "Collation": pa.array(
                        ["utf8mb4_bin" if r[1] else None for r in rows],
                        pa.string()),
                    "Create_options": [r[3] for r in rows],
                    "Comment": [r[4] for r in rows]}))
        if s.what == "create_table":
            db = s.table.database or self.current_db
            view = cat.get_view(db, s.table.name)
            if view is not None:
                cols = f" ({', '.join(view['columns'])})" \
                    if view["columns"] else ""
                ddl = (f"CREATE VIEW `{s.table.name}`{cols} AS "
                       f"{view['sql']}")
                return Result(columns=["View", "Create View"],
                              arrow=pa.table({"View": [s.table.name],
                                              "Create View": [ddl]}))
            info = cat.get_table(db, s.table.name)
            lines = []
            pk = info.primary_key()
            auto_col = (info.options or {}).get("auto_increment")
            for f in info.schema.fields:
                bits = [f"  `{f.name}` {f.ltype.value.upper()}"]
                if not f.nullable:
                    bits.append("NOT NULL")
                if f.name == auto_col:
                    bits.append("AUTO_INCREMENT")
                lines.append(" ".join(bits))
            if pk:
                lines.append("  PRIMARY KEY (" +
                             ", ".join(f"`{c}`" for c in pk.columns) + ")")
            for ix in info.indexes:
                if ix.kind == "primary":
                    continue
                kw = {"unique": "UNIQUE KEY", "fulltext": "FULLTEXT KEY",
                      "global": "GLOBAL KEY",
                      "global_unique": "GLOBAL UNIQUE KEY"} \
                    .get(ix.kind, "KEY")
                lines.append(f"  {kw} `{ix.name}` (" +
                             ", ".join(f"`{c}`" for c in ix.columns) + ")")
            ddl = f"CREATE TABLE `{s.table.name}` (\n" + ",\n".join(lines) + \
                "\n)"
            pspec = (info.options or {}).get("partition")
            if pspec and pspec["kind"] == "hash":
                ddl += (f"\nPARTITION BY HASH (`{pspec['column']}`) "
                        f"PARTITIONS {pspec['n']}")
            elif pspec and pspec["kind"] == "range":
                parts = ", ".join(
                    f"PARTITION {nm} VALUES LESS THAN "
                    + ("MAXVALUE" if u is None else f"({u!r})")
                    for nm, u in zip(pspec["names"], pspec["uppers"]))
                ddl += (f"\nPARTITION BY RANGE (`{pspec['column']}`) "
                        f"({parts})")
            return Result(columns=["Table", "Create Table"], arrow=pa.table(
                {"Table": [s.table.name], "Create Table": [ddl]}))
        if s.what in ("columns", "full_columns"):
            base = self._execute_stmt(DescribeStmt(s.table)).arrow
            if s.pattern is not None:
                base = base.take(
                    [i for i, f in
                     enumerate(base.column("Field").to_pylist())
                     if like(f, s.pattern)])
            if s.what == "columns":
                return Result(columns=list(base.column_names), arrow=base)
            # the FULL shape MySQL connectors index by name:
            # Field/Type/Collation/Null/Key/Default/Extra/Privileges/Comment
            fields = base.column("Field").to_pylist()
            types = base.column("Type").to_pylist()
            db = s.table.database or self.current_db
            auto_col = None
            if cat.get_view(db, s.table.name) is None:
                info = cat.get_table(db, s.table.name)
                auto_col = (info.options or {}).get("auto_increment")
            return Result(
                columns=["Field", "Type", "Collation", "Null", "Key",
                         "Default", "Extra", "Privileges", "Comment"],
                arrow=pa.table({
                    "Field": fields,
                    "Type": types,
                    "Collation": pa.array(
                        ["utf8mb4_bin" if t == "string" else None
                         for t in types], pa.string()),
                    "Null": base.column("Null"),
                    "Key": base.column("Key"),
                    "Default": pa.array([None] * len(fields), pa.string()),
                    "Extra": ["auto_increment" if f == auto_col else ""
                              for f in fields],
                    "Privileges": ["select,insert,update,references"]
                    * len(fields),
                    "Comment": [""] * len(fields)}))
        if s.what == "index":
            db = s.table.database or self.current_db
            info = cat.get_table(db, s.table.name)
            rows = []
            for ix in info.indexes:
                for seq, c in enumerate(ix.columns, 1):
                    rows.append((s.table.name, ix.name, ix.kind, seq, c))
            return Result(
                columns=["Table", "Key_name", "Index_type", "Seq_in_index",
                         "Column_name"],
                arrow=pa.table({
                    "Table": [r[0] for r in rows],
                    "Key_name": [r[1] for r in rows],
                    "Index_type": [r[2] for r in rows],
                    "Seq_in_index": pa.array([r[3] for r in rows], pa.int64()),
                    "Column_name": [r[4] for r in rows],
                }))
        if s.what in ("variables", "status"):
            if s.what == "variables":
                vals = dict(_SERVER_VARS)
                # per-session overrides (SET name = v)
                vals.update({k: str(v) for k, v in self.session_vars.items()
                             if not k.startswith("@")})
                # live flag table (gflags analog — SHOW VARIABLES is how
                # MySQL clients inspect server config)
                vals.update({k: str(v).lower() if isinstance(v, bool)
                             else str(v)
                             for k, v in FLAGS.snapshot().items()})
            else:
                vals = {
                    "Threads_connected": str(len(self.db.processlist)),
                    "Uptime": "0",
                }
                # flattened engine counters (bvar analog)
                for name, st in metrics.REGISTRY.expose().items():
                    for k, v in st.items():
                        vals[f"{name}.{k}"] = str(v)
                # fleet extension: merged cluster counters/histograms plus
                # per-daemon liveness as cluster.* rows (only when daemons
                # are registered — a standalone frontend adds nothing)
                if self.db.telemetry.has_daemons():
                    vals.update(self.db.telemetry.status_rows())
                # frontend watchdog verdict (obs/watchdog.py): ok/stalled
                # plus episode counters, same rows the health RPC serves
                vals.update(self.db.watchdog.status_rows())
            items = sorted(vals.items())
            if s.pattern is not None:
                items = [(k, v) for k, v in items if like(k, s.pattern)]
            return Result(columns=["Variable_name", "Value"], arrow=pa.table({
                "Variable_name": [k for k, _ in items],
                "Value": [v for _, v in items]}))
        if s.what == "processlist":
            # wire connections (db.processlist, kept by the MySQL server)
            # merged with live progress records (obs/progress.py) — an
            # embedded Session mid-query shows up even with no socket.
            # Snapshot first: connection threads insert/pop concurrently.
            now = time.time()
            merged: dict[int, dict] = {}
            for cid, ent in dict(self.db.processlist).items():
                merged[cid] = {
                    "user": ent.get("user", ""),
                    "host": ent.get("host", ""),
                    "db": ent.get("db", ""),
                    "command": ent.get("command", "Sleep"),
                    "time_s": int(now - ent.get("since", now)),
                    "state": "", "info": ent.get("info", "")}
            for qp in PROGRESS.live(self.db):
                row = merged.setdefault(qp.conn_id, {
                    "user": qp.user, "host": qp.host, "db": qp.dbname})
                row.update(command=qp.command,
                           time_s=int(qp.elapsed_s()),
                           state=qp.state(), info=qp.text)
            rows = sorted(merged.items())
            # MySQL semantics: Info truncates at 100 chars unless FULL
            infos = [r.get("info", "") for _, r in rows]
            if not s.full:
                infos = [i[:100] for i in infos]
            return Result(
                columns=["Id", "User", "Host", "db", "Command", "Time",
                         "State", "Info"],
                arrow=pa.table({
                    "Id": pa.array([i for i, _ in rows], pa.int64()),
                    "User": [r.get("user", "") for _, r in rows],
                    "Host": [r.get("host", "") for _, r in rows],
                    "db": [r.get("db", "") for _, r in rows],
                    "Command": [r.get("command", "Sleep") for _, r in rows],
                    "Time": pa.array([r.get("time_s", 0) for _, r in rows],
                                     pa.int64()),
                    "State": [r.get("state", "") for _, r in rows],
                    "Info": infos,
                }))
        if s.what == "grants":
            user = s.user or self.user
            gs = self.db.privileges.grants_of(user)
            lines = [f"GRANT {lv} ON {'*' if db == '*' else db}.* TO "
                     f"'{user}'" for db, lv in gs]
            return Result(columns=[f"Grants for {user}"],
                          arrow=pa.table({f"Grants for {user}": lines}))
        if s.what == "regions":
            rows = []
            for key, st in sorted(self.db.stores.items()):
                if s.table is not None:
                    db = s.table.database or self.current_db
                    if key != f"{db}.{s.table.name}":
                        continue
                for r in st.regions:
                    rows.append((key, r.region_id, r.num_rows, r.version))
            return Result(
                columns=["Table", "Region_id", "Rows", "Version"],
                arrow=pa.table({
                    "Table": [r[0] for r in rows],
                    "Region_id": pa.array([r[1] for r in rows], pa.int64()),
                    "Rows": pa.array([r[2] for r in rows], pa.int64()),
                    "Version": pa.array([r[3] for r in rows], pa.int64()),
                }))
        raise SqlError(f"unsupported SHOW {s.what!r}")

    def _kill(self, s: KillStmt) -> Result:
        """KILL [QUERY|CONNECTION] <id> (reference: the kill path through
        state_machine.cpp).  QUERY flips the cancel token of the target
        connection's live statements — the victim's own thread raises
        ER_QUERY_INTERRUPTED (1317) at its next progress beat, so no
        cross-thread exception injection and no torn side effects.
        CONNECTION additionally marks the wire connection for teardown
        and severs its socket so even an idle connection dies now."""
        import socket as _socket
        tid = int(s.target_id)
        n = PROGRESS.kill(conn_id=tid, db=self.db,
                          reason=f"kill {s.kind} {tid}")
        known = bool(n) or tid in self.db.processlist \
            or tid == getattr(self, "_conn_id", None)
        if s.kind == "connection":
            ent = self.db.processlist.get(tid)
            if ent is not None:
                ent["kill"] = True
                sock = ent.get("_sock")
                if sock is not None:
                    # wakes a connection blocked in read(); the serve loop
                    # sees the kill marker and tears down cleanly
                    try:
                        sock.shutdown(_socket.SHUT_RDWR)
                    except OSError:
                        pass
        if not known:
            raise SqlError(f"Unknown thread id: {tid}")
        return Result()

    def _load_data(self, s: LoadDataStmt) -> Result:
        """LOAD DATA INFILE: CSV -> bulk columnar ingest (reference:
        load_planner + the importer; here pyarrow's CSV reader feeds
        insert_arrow directly)."""
        from pyarrow import csv as pacsv

        store = self._store(s.table)
        names = store.info.schema.names()
        ropt = pacsv.ReadOptions(column_names=names,
                                 skip_rows=s.ignore_lines)
        popt = pacsv.ParseOptions(delimiter=s.sep)
        copt = pacsv.ConvertOptions(
            column_types={f.name: schema_to_arrow(store.info.schema).field(
                f.name).type for f in store.info.schema.fields},
            null_values=["", "\\N", "NULL"], strings_can_be_null=True)
        table = pacsv.read_csv(s.path, read_options=ropt,
                               parse_options=popt, convert_options=copt)
        self._ingest_arrow(store, table, check_dups=True)
        db_name = s.table.database or self.current_db
        self._log_binlog("insert", db_name, s.table.name,
                         statement=f"LOAD DATA INFILE {s.path!r}",
                         affected=table.num_rows)
        return Result(affected_rows=table.num_rows)

    def _handle(self, s: HandleStmt) -> Result:
        """Operator commands (reference: handle_helper.cpp's map; the subset
        that has a real in-process counterpart)."""
        if s.command == "checkpoint":
            self.db.checkpoint()
            return Result()
        if s.command == "flightrec" and s.args:
            # handle flightrec dump '/path.jsonl' [rec_id] | clear — the
            # JSON-lines export tools/flightrec.py renders offline
            op = s.args[0].lower()
            if op == "dump" and len(s.args) >= 2:
                rid = int(s.args[2]) if len(s.args) > 2 else None
                return Result(affected_rows=self.db.flightrec.dump(
                    s.args[1], rec_id=rid))
            if op == "clear":
                self.db.flightrec.clear()
                return Result()
        if s.command in ("ttl", "ttl_tick"):
            return Result(affected_rows=self.ttl_tick())
        if s.command == "gc":
            for st in self.db.stores.values():
                if st.row_table is not None:
                    st.row_table.gc(st.row_table.snapshot())
            return Result()
        if s.command == "split" and len(s.args) >= 2:
            # handle split <db.table> <region_rows>: force a smaller split
            # threshold and re-split oversized regions.  `db.t` lexes as
            # three tokens, so rejoin everything before the row count.
            key, rows = "".join(s.args[:-1]), int(s.args[-1])
            st = self.db.stores.get(key)
            if st is None:
                raise PlanError(f"unknown table {key!r}")
            st.region_rows = rows
            with st._lock:
                for r in list(st.regions):
                    st._maybe_split(r)
                st._mutations += 1
            return Result()
        if s.command == "ddl" and s.args:
            # handle ddl suspend|resume (reference: DDL suspend/restart
            # operator commands, handle_helper.cpp)
            op = s.args[0]
            if op == "suspend":
                self.db.ddl.suspend()
                return Result()
            if op in ("resume", "restart"):
                self.db.ddl.resume()
                return Result()
            raise SqlError(f"unsupported HANDLE ddl {op!r}")
        if s.command == "add_privilege" and len(s.args) >= 3:
            # handle add_privilege <user> <db|*> <read|write|all>
            self.db.privileges.grant(s.args[0], s.args[2], s.args[1])
            return Result()
        if s.command == "drop_privilege" and len(s.args) >= 2:
            self.db.privileges.revoke(s.args[0], s.args[1])
            return Result()
        if s.command == "set_flag" and len(s.args) >= 2:
            # handle set_flag <name> <value> (reference: modify gflags)
            FLAGS.set_flag(s.args[0], " ".join(s.args[1:]))
            return Result()
        if s.command in ("drop_instance", "migrate") and s.args:
            # mark a store MIGRATE: balancing drains its peers (reference:
            # handle migrate -> cluster_manager migrate handling)
            self._fleet_meta().drop_instance("".join(s.args))
            return Result()
        if s.command == "add_instance" and s.args:
            # handle add_instance <store_addr> [resource_tag]: register a
            # store (e.g. an OLAP-isolated learner host) with the meta.
            # The lexer splits "host:port" into tokens, so the tag is only
            # the trailing arg when it can't be part of an address (no
            # colon, not a bare port number)
            args = [str(a) for a in s.args]
            tag = ""
            if len(args) > 1 and ":" not in args[-1] and \
                    not args[-1].isdigit():
                tag = args[-1]
                args = args[:-1]
            self._fleet_meta().add_instance("".join(args), resource_tag=tag)
            return Result()
        if s.command in ("add_peer", "remove_peer", "trans_leader",
                         "add_learner", "remove_learner") and \
                len(s.args) >= 2:
            # handle add_peer|remove_peer|trans_leader <region_id> <store>:
            # validated, executed, and recorded in meta by the fleet (the
            # raft_control RPC surface); failures RAISE — an operator must
            # never see success for an op that didn't happen
            try:
                self._fleet_required().operator_order(
                    s.command, int(s.args[0]), "".join(s.args[1:]))
            except (ValueError, RuntimeError) as e:
                raise PlanError(str(e)) from None
            return Result(affected_rows=1)
        if s.command == "split_region" and s.args:
            tier, idx = self._find_region(int(s.args[0]))
            tier.split_region(idx)
            return Result()
        if s.command == "merge_region" and s.args:
            tier, idx = self._find_region(int(s.args[0]))
            tier.merge_region(idx)
            return Result()
        if s.command in ("store_heartbeat", "balance_tick"):
            # one control-loop turn: heartbeats in, balance orders executed
            return Result(affected_rows=self._fleet_required().control_tick())
        if s.command in ("cold_flush", "cold_gc", "cold_status") and s.args:
            # handle cold_flush <db.table> [upto_rowid]: hot rows -> one
            # immutable segment per region on the external FS, manifest +
            # eviction raft-committed (region_olap.cpp:445 flush_to_cold);
            # cold_gc merges segments (latest version per rowid, deletes
            # dropped); cold_status reports hot bytes + manifest size
            has_upto = s.command == "cold_flush" and len(s.args) > 1 and \
                str(s.args[-1]).isdigit()
            key = "".join(s.args[:-1] if has_upto else s.args)
            st = self.db.stores.get(key)
            if st is None or st.replicated is None or \
                    not hasattr(st.replicated, "flush_cold"):
                raise PlanError(f"no cold-capable replicated tier for "
                                f"{key!r}")
            fs = self.db.cold_fs(required=True)
            tier = st.replicated
            if s.command == "cold_flush":
                upto = int(s.args[-1]) if has_upto else None
                return Result(affected_rows=tier.flush_cold(fs, upto=upto))
            if s.command == "cold_gc":
                return Result(affected_rows=tier.cold_gc(fs))
            n_regions = len(tier.groups) if hasattr(tier, "groups") \
                else len(tier.regions)
            entries = sum(len(self._cold_manifest_of(tier, i))
                          for i in range(n_regions))
            return Result(columns=["hot_bytes", "cold_segments"], arrow=(
                pa.table({"hot_bytes": [tier.hot_bytes()],
                          "cold_segments": [entries]})))
        if s.command == "compact":
            # raft log compaction across every replicated tier (the
            # space-efficient snapshot scheme)
            fleet = self.db.fleet
            if fleet is not None:
                for tier in fleet.row_tiers.values():
                    tier.compact_all()
                if hasattr(fleet.meta, "compact_all"):
                    fleet.meta.compact_all()
            return Result()
        raise SqlError(f"unsupported HANDLE command {s.command!r}")

    @staticmethod
    def _cold_manifest_of(tier, i):
        if hasattr(tier, "groups"):     # in-process fleet plane
            g = tier.groups[i]
            return g.bus.nodes[g.leader()].cold_manifest
        return tier._region_manifest(tier.regions[i])   # daemon plane

    def _fleet_required(self):
        if self.db.fleet is None:
            raise PlanError("this HANDLE command needs a fleet-bound "
                            "Database (store fleet + meta)")
        return self.db.fleet

    def _fleet_meta(self):
        return self._fleet_required().meta

    def _find_region(self, region_id: int):
        """(tier, index) hosting a replicated region (fleet mode)."""
        fleet = self._fleet_required()
        for tier in fleet.row_tiers.values():
            for i, m in enumerate(tier.metas):
                if m.region_id == region_id:
                    return tier, i
        raise PlanError(f"unknown region {region_id}")

    def _drop_durable(self, key: str, store):
        """Remove a dropped table's WAL + Parquet from data_dir (and its
        replicated tier from a fleet-bound Database)."""
        if self.db.fleet is not None:
            tier = self.db.fleet.row_tiers.pop(key, None)
            if tier is not None:
                tier.release_regions()   # no ghost raft groups in the fleet
        if self.db.cluster is not None:
            tier = self.db.cluster.tiers.pop(key, None)
            if tier is not None:
                tier.release_regions()
        if not self.db.data_dir:
            return
        import os
        import shutil
        if store is not None:
            store.row_table = None      # release the WAL file handle
        wal = os.path.join(self.db.data_dir, key + ".wal")
        if os.path.exists(wal):
            os.remove(wal)
        pq_dir = os.path.join(self.db.data_dir, key)
        if os.path.isdir(pq_dir):
            shutil.rmtree(pq_dir)

    # -- helpers ------------------------------------------------------------
    def _stats_fn(self, table_key: str, col: str):
        """Collected column statistics, or None — the ONE stats-access
        closure behind the planner's selectivity estimates AND the
        distributor's adaptive-agg ndv lookups."""
        st = self.db.stores.get(table_key)
        if st is None:
            return None
        try:
            return st.column_stats(col)
        except Exception:   # noqa: BLE001 — stats are advisory
            return None

    def _planner(self) -> Planner:
        return Planner(self.db.catalog, self.db.stores, self.current_db,
                       self._stats_fn)

    def _plan_select(self, stmt: SelectStmt) -> PlanNode:
        """Logical+physical planning, plus the distribution pass (the
        Separate/MppAnalyzer analog) when this session is mesh-bound."""
        with trace.span("plan.build"):
            return self._plan_select_inner(stmt)

    def _where_selectivity(self, stmt: SelectStmt):
        """Combined selectivity estimate of the WHERE conjuncts that have
        a stats basis (index/stats histograms + MCVs over THIS
        execution's literal values); None when no conjunct resolves.
        Feeds the adaptive-agg local-vs-raw decision and the mesh plan
        cache's selectivity class — a parameterized statement replans per
        CLASS, not per value, so the executable multiplier stays small."""
        if stmt.where is None:
            return None
        from ..expr.ast import Call as ECall, ColRef as EColRef, Lit as ELit
        from ..index.stats import conjunct_selectivity
        from ..plan.eqclasses import conjuncts

        _FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}
        resolve = self._param_resolver(stmt)
        total, basis = 1.0, False
        for cj in conjuncts(stmt.where):
            if not (isinstance(cj, ECall)
                    and cj.op in ("eq", "ne", "lt", "le", "gt", "ge")
                    and len(cj.args) == 2):
                continue
            a, b = cj.args
            op = cj.op
            if isinstance(b, EColRef) and isinstance(a, ELit):
                a, b = b, a
                op = _FLIP.get(op, op)
            if not (isinstance(a, EColRef) and isinstance(b, ELit)):
                continue
            src = resolve(a.table, a.name)
            if src is None:
                continue
            st = self._stats_fn(src[0], a.name.split(".")[-1])
            s = conjunct_selectivity(st, op, b.value)
            if s is not None:
                total *= s
                basis = True
        return total if basis else None

    def _plan_select_inner(self, stmt: SelectStmt) -> PlanNode:
        plan = self._planner().plan_select(stmt)
        self._annotate_ann(stmt, plan)
        if self.mesh is not None:
            from ..plan.distribute import distribute

            def rows_fn(table_key: str) -> int:
                st = self.db.stores.get(table_key)
                return st.num_rows if st is not None else 0

            def ndv_fn(table_key: str, col: str):
                # index/stats distinct-count estimate feeding the
                # cardinality-adaptive aggregation choice
                return (self._stats_fn(table_key, col) or {}).get("ndv")

            from ..parallel import agg as _agg  # noqa: F401 — defines the
            #                                     adaptive_agg_* flags

            # the parameterized path stashes the ORIGINAL statement's
            # bound-value selectivity before planning (stmt here carries
            # Param markers, not values); EXPLAIN and unparameterized
            # plans compute it from their own baked literals
            wsel = getattr(self, "_where_sel_hint", None)
            if wsel is None and bool(FLAGS.adaptive_agg_selectivity):
                wsel = self._where_selectivity(stmt)
            plan = distribute(plan, int(self.mesh.devices.size), rows_fn,
                              ndv_fn=ndv_fn, stats_fn=self._stats_fn,
                              where_selectivity=wsel)
        return plan

    def _annotate_ann(self, stmt: SelectStmt, plan: PlanNode) -> None:
        """When the statement is the ANN shape over a table with an ANN
        index, mark its ScanNode: the batch builder reduces the scan to
        the IVF candidate set (index/annindex) and the unchanged plan
        re-ranks exactly."""
        from ..index import annindex
        from ..plan.nodes import ScanNode

        t = stmt.table
        if t is None or t.subquery is not None or self.mesh is not None:
            return
        dbname = t.database or self.current_db
        try:
            info = self.db.catalog.get_table(dbname, t.name)
        except Exception:       # noqa: BLE001 — planner already validated
            return
        m = annindex.match_ann_query(stmt, info, t.label)
        if m is None:
            return
        ix, col, metric, qvec, k = m
        key = f"{dbname}.{t.name}"
        scans = []

        def walk(n):
            if isinstance(n, ScanNode) and n.table_key == key:
                scans.append(n)
            for c in n.children:
                walk(c)
        walk(plan)
        if len(scans) == 1:
            # the WHERE flag rides along: filters re-apply AFTER candidate
            # reduction, so the batch builder must widen the pre-filter pool
            # (or fall back to brute force) to still fill LIMIT k
            scans[0].ann = (ix.name, col, metric, qvec, int(k),
                            stmt.where is not None)

    def _ann_batch(self, n, store) -> Optional[ColumnBatch]:
        """IVF candidate batch for an ANN-annotated scan: positions from
        the trained index, sliced out of the store snapshot (same row
        source the full scan would read)."""
        from ..index import annindex

        ix_name, col, metric, qvec, k, has_where = n.ann
        filtered = has_where or n.pushed_filter is not None
        dim = (store.info.options or {}).get("vector_cols", {}).get(col)
        if dim is None:
            return None
        cache = getattr(self, "_access_batches", None)
        if cache is None:
            cache = self._access_batches = {}
        ck = (n.table_key, store.version, "ann", col, qvec, k, filtered)
        hit = cache.get(ck)
        if hit is not None:
            b, desc = hit
            n.access_desc = desc
            return b
        res = annindex.manager(self.db).candidates(
            n.table_key, store, col, int(dim), qvec, metric, k,
            filtered=filtered)
        if res is None:
            n.access_desc = "full"
            return None
        positions, nprobe = res
        import pyarrow as _pa
        b = ColumnBatch.from_arrow(
            store.snapshot().take(_pa.array(positions)))
        n.access_desc = (f"ann({ix_name} nprobe={nprobe}, "
                         f"cand={len(positions)})")
        self._evict_access(n.table_key, store.version)
        cache[ck] = (b, n.access_desc)
        metrics.index_scans.add(1)
        return b

    def _store(self, tref) -> TableStore:
        db = tref.database or self.current_db
        if db == "information_schema":
            raise PlanError("information_schema tables are read-only")
        key = f"{db}.{tref.name}"
        if key not in self.db.stores:
            # registers lazily in case catalog was populated externally
            info = self.db.catalog.get_table(db, tref.name)
            self.db.stores[key] = self.db.make_store(info)
        return self.db.stores[key]

    # -- transactions ------------------------------------------------------
    def _txn_stmt(self, s: TxnStmt) -> Result:
        """BEGIN/COMMIT/ROLLBACK (reference: transaction_planner.cpp +
        TransactionNode fan-out).  Each touched table gets a storage
        TxnContext: pessimistic row locks + row-tier write buffer + zero-copy
        region pre-images; COMMIT is one atomic WAL batch per table."""
        if s.kind == "begin":
            # a new BEGIN implicitly commits any previous txn (MySQL behavior)
            self._commit_txn()
            self._sql_txn = {}
            return Result()
        if self._sql_txn is None:
            return Result()      # COMMIT/ROLLBACK outside txn: no-op
        if s.kind == "commit":
            self._commit_txn()
            return Result()
        for tctx in self._sql_txn.values():
            tctx.rollback()
        self._sql_txn = None
        self._txn_binlog.clear()    # rolled back: subscribers never see these
        return Result()

    def _commit_txn(self):
        if self._sql_txn is not None:
            from ..storage.column_store import commit_group
            try:
                # one atomic commit across every table the transaction
                # touched: replicated tables group into a single 2PC
                # spanning all their region groups (global-index writes and
                # cross-table transactions commit or abort together)
                commit_group(list(self._sql_txn.values()))
            except BaseException:
                # the txn did NOT commit: its buffered events must never
                # publish (a later successful commit would otherwise emit
                # them as phantom CDC rows)
                self._txn_binlog.clear()
                raise
            finally:
                # even a failed WAL write must not trap the session in the
                # transaction (the contexts released their leases already)
                self._sql_txn = None
        self._flush_txn_binlog()

    def _flush_txn_binlog(self):
        # an empty commit still flows through: pending retry batches (failed
        # appends of EARLIER commits) piggyback a drain on any commit
        if not self._txn_binlog and not self.db.binlog_retry_pending():
            return
        with trace.span("binlog.flush", events=len(self._txn_binlog)):
            self._flush_txn_binlog_inner()

    def _flush_txn_binlog_inner(self):
        from ..storage.binlog_regions import DistributedBinlog

        per_table: OrderedDict = OrderedDict()
        for ev in self._txn_binlog:
            event_type, db_name, table, rows, statement, affected = ev
            self.db.binlog.append(event_type, db_name, table, rows=rows,
                                  statement=statement, affected=affected)
            if self._table_binlogged(db_name, table):
                per_table.setdefault(f"{db_name}.{table}", []).extend(
                    DistributedBinlog.events_from_statement(
                        event_type, rows, statement, affected))
        # one prewrite/commit round per table, not per statement (the
        # autocommit path instead joins the data's own 2PC in _write_hot).
        # dist_binlog() resolves only when a binlogged event exists: it
        # creates the __binlog__ regions cluster-wide on first use
        dist = self.db.dist_binlog() \
            if per_table or self.db.binlog_retry_pending() else None
        if dist is not None:
            # CDC must not fail the txn the user already committed — but a
            # failed append is COMMITTED data subscribers would silently
            # lose.  Queue it durably in-process and retry on later flushes;
            # only a bounded-queue overflow drops events, and that shows in
            # metrics.binlog_events_dropped.  Per-table locks: each table's
            # drain-then-append is atomic vs concurrent commits/autocommits
            # of THAT table (the stream-order contract), while other tables
            # proceed in parallel — no engine-wide convoy.  Locks are taken
            # one table at a time, never nested.
            db = self.db
            # piggyback: retry other tables' queued batches on any commit
            for tk in db.binlog_retry_pending():
                if tk not in per_table:
                    rq = db.binlog_retry_queue(tk)
                    with rq.mu:
                        db._drain_rq_locked(rq, tk, dist)
            for table_key, events in per_table.items():
                rq = db.binlog_retry_queue(table_key)
                with rq.mu:
                    db._drain_rq_locked(rq, table_key, dist)
                    if rq.q:
                        # an older batch for this table is still queued:
                        # appending now would reorder the table's CDC stream
                        db._queue_rq_locked(rq, events)
                        continue
                    try:
                        dist.append(table_key, events)
                    except Exception:   # noqa: BLE001
                        db._queue_rq_locked(rq, events)
        self._txn_binlog.clear()

    def _table_binlogged(self, db_name: str, table: str) -> bool:
        try:
            info = self.db.catalog.get_table(db_name, table)
        except Exception:       # noqa: BLE001
            return False
        return _opt_on((info.options or {}).get("binlog"))

    def _tctx(self, store: TableStore):
        """The open transaction's per-table context (created on first touch),
        or None in autocommit."""
        if self._sql_txn is None:
            return None
        key = f"{store.info.database}.{store.info.name}"
        if key not in self._sql_txn:
            self._sql_txn[key] = store.begin_txn()
        return self._sql_txn[key]

    def load_arrow(self, table_name: str, table: pa.Table,
                   database: str | None = None) -> int:
        """Bulk ingest (the importer/fast_importer analog, src/tools/importer):
        appends an Arrow table straight into the column store, bypassing SQL
        row parsing (cold path — durable at the next Database.checkpoint)."""
        from ..sql.stmt import TableRef

        store = self._store(TableRef(database, table_name))
        vcols = (store.info.options or {}).get("vector_cols") or {}
        if vcols:
            table = _expand_vector_arrow(table, vcols)
        self._ingest_arrow(store, table)
        return table.num_rows

    # -- DDL --------------------------------------------------------------
    def _create_table(self, s: CreateTableStmt) -> Result:
        db = s.table.database or self.current_db
        fields = []
        vector_cols: dict[str, int] = {}
        for c in s.columns:
            tl = c.type_name.strip().lower()
            if tl.startswith("vector"):
                # VECTOR(d): stored as d hidden FLOAT32 component columns, so
                # distance expressions fuse into the one-jit query program
                # (the faiss sidecar re-designed as columns; reference:
                # vector_index.cpp stores blobs + a faiss index)
                try:
                    dim = int(tl.split("(")[1].rstrip(") "))
                except (IndexError, ValueError):
                    raise PlanError("VECTOR needs a dimension: VECTOR(d)")
                if not 1 <= dim <= 4096:
                    raise PlanError("VECTOR dimension out of range")
                vector_cols[c.name] = dim
                for i in range(dim):
                    fields.append(Field(f"__{c.name}_{i}", LType.FLOAT32,
                                        True))
                continue
            lt = parse_type(c.type_name)
            nullable = c.nullable and c.name not in s.primary_key
            fields.append(Field(c.name, lt, nullable))
        options = dict(s.options)
        if vector_cols:
            options["vector_cols"] = vector_cols
        pspec = options.get("partition")
        if pspec:
            names = {f.name for f in fields}
            if pspec["column"] not in names:
                raise PlanError(f"unknown partition column "
                                f"{pspec['column']!r}")
            if pspec["kind"] == "range":
                if len(set(pspec["names"])) != len(pspec["names"]):
                    raise PlanError("duplicate partition name")
                pf = next(f for f in fields if f.name == pspec["column"])
                try:
                    finite = [TableStore._norm_part_scalar(u, pf)
                              for u in pspec["uppers"] if u is not None]
                    if any(b <= a for a, b in zip(finite, finite[1:])):
                        raise PlanError("partition bounds must be strictly "
                                        "increasing")
                except (TypeError, ValueError) as e:
                    if isinstance(e, PlanError):
                        raise
                    raise PlanError(f"partition bounds do not match column "
                                    f"{pspec['column']!r}: {e}") from None
            elif pspec["kind"] == "hash" and int(pspec["n"]) < 1:
                raise PlanError("PARTITIONS must be at least 1")
        auto_cols = [c for c in s.columns if c.auto_increment]
        if auto_cols:
            if len(auto_cols) > 1:
                raise PlanError("only one AUTO_INCREMENT column allowed")
            if not parse_type(auto_cols[0].type_name).is_integer:
                raise PlanError("AUTO_INCREMENT requires an integer column")
            options["auto_increment"] = auto_cols[0].name
        schema = Schema(tuple(fields))
        indexes = []
        if s.primary_key:
            indexes.append(IndexInfo("PRIMARY", "primary", list(s.primary_key)))
        for kind, name, cols in s.indexes:
            if kind == "ann":
                if len(cols) != 1 or cols[0] not in vector_cols:
                    raise PlanError("ANN INDEX needs exactly one VECTOR "
                                    "column")
                indexes.append(IndexInfo(name or f"ann_{cols[0]}", kind,
                                         cols))
                continue
            indexes.append(IndexInfo(name or f"idx_{'_'.join(cols)}", kind, cols))
        info = self.db.catalog.create_table(db, s.table.name, schema, indexes,
                                            options=options,
                                            if_not_exists=s.if_not_exists)
        key = f"{db}.{s.table.name}"
        if key not in self.db.stores:
            self.db.stores[key] = self.db.make_store(info)
        for ix in info.indexes:
            if ix.kind in ("global", "global_unique"):
                self._create_global_backing(db, info, ix)
        self.db.save_catalog()
        return Result()

    def _create_global_backing(self, db: str, info, ix) -> TableStore:
        """Materialize a global index's hidden backing table: its own
        catalog entry, its own store — and in fleet/cluster mode its own
        replicated row tier with its OWN region groups (reference: index
        data in separate regions, separate.cpp:653)."""
        from ..index import globalindex as gi

        for c in ix.columns:
            if c not in info.schema:
                raise PlanError(f"unknown column {c!r} in global index "
                                f"{ix.name!r}")
        bname = gi.backing_table_name(info.name, ix.name)
        bkey = f"{db}.{bname}"
        if bkey in self.db.stores:
            return self.db.stores[bkey]
        binfo = self.db.catalog.create_table(
            db, bname, gi.backing_schema(info, ix),
            [IndexInfo("PRIMARY", "primary", gi.backing_pk(info, ix))],
            if_not_exists=True)
        store = self.db.stores[bkey] = self.db.make_store(binfo)
        return store

    # -- daemon-plane pushed-down execution (reference: store-side plan
    # fragments, region.cpp:2671 / store.interface.proto:418) --------------
    def _pushdown_candidate(self, stmt: SelectStmt):
        """(push, info, table_key) when this SELECT can execute as a pushed
        fragment on the store daemons, else None.  Shared by execution and
        EXPLAIN so the displayed plan is the plan that runs."""
        from ..plan.fragment import build_push_query

        db = self.db
        if db.cluster is None:
            return None
        mode = str(FLAGS.pushdown_reads)
        if mode == "off" or self._sql_txn is not None:
            return None
        if self._snap_dirty(stmt):
            # pinned snapshot with version churn: store daemons evaluate
            # the physically-latest region image; the versioned read needs
            # the frontend's MVCC state, so the pin routes this query to
            # the resident path (quiet tables keep the pushed path)
            return None
        t = stmt.table
        if t is None:
            return None
        dbname = t.database or self.current_db
        if dbname == "information_schema":
            return None
        if db.catalog.get_view(dbname, t.name) is not None:
            return None
        try:
            info = db.catalog.get_table(dbname, t.name)
        except Exception:       # noqa: BLE001 — unknown table: planner errs
            return None
        if (info.options or {}).get("partition"):
            return None          # partitioned layout: image path prunes
        if any(f.ltype is LType.DECIMAL for f in info.schema.fields):
            # the row tier's DECIMAL encoding is scaled; row-wise eval
            # would disagree with the image path — not pushable
            return None
        key = f"{dbname}.{t.name}"
        store = db.stores.get(key)
        if mode != "always" and store is not None \
                and not store.attach_pending:
            return None          # warm image: compiled JAX path is faster
        if mode != "always":
            from ..index.selector import is_point_statement

            if is_point_statement(stmt):
                # repeated PK point reads: one image pull then
                # microsecond-class local lookups beats a per-query
                # full-region fragment scan (the OLTP path)
                return None
        push = build_push_query(stmt, info)
        if push is None:
            return None
        return push, info, key

    def _render_pushdown(self, push, info, key) -> str:
        """EXPLAIN display of a pushed fragment: what the store daemons
        execute vs what the frontend finishes."""
        from ..expr.roweval import expr_from_wire

        f = push.frag
        lines = [f"PushDown({key} -> store daemons)"]
        if f.get("filter") is not None:
            lines.append(f"  store filter: {expr_from_wire(f['filter'])!r}")
        if push.mode == "rows":
            outs = ", ".join(f"{n}={expr_from_wire(w)!r}"
                             for n, w in f["outputs"])
            lines.append(f"  store project: {outs}")
            if f.get("limit") is not None:
                lines.append(f"  store limit: {f['limit']} per region")
        else:
            if f["keys"]:
                keys = ", ".join(f"{n}={expr_from_wire(w)!r}"
                                 for n, w in f["keys"])
                lines.append(f"  store group by: {keys}")
            aggs = ", ".join(
                "{}={}({})".format(
                    out, kind,
                    repr(expr_from_wire(w)) if w is not None else "*")
                for kind, w, out in f["aggs"])
            lines.append(f"  store partial aggs: {aggs}")
        finish = []
        if push.having is not None:
            finish.append(f"having {push.having!r}")
        if push.order:
            finish.append("order by " + ", ".join(
                f"{e!r} {'asc' if asc else 'desc'}"
                for e, asc in push.order))
        if push.limit is not None:
            finish.append(f"limit {push.limit}"
                          + (f" offset {push.offset}" if push.offset
                             else ""))
        lines.append("  frontend merge: "
                     + ("; ".join(finish) if finish else "concat/combine"))
        lines.append("  items: " + ", ".join(f"{n}={e!r}"
                                             for n, e in push.items))
        return "\n".join(lines)

    def _try_pushdown(self, stmt: SelectStmt) -> Optional[Result]:
        """Execute an eligible SELECT store-side: only qualifying rows /
        aggregate partials cross the wire, and a cold frontend never pulls
        whole regions for it (VERDICT r04 missing #1)."""
        cand = self._pushdown_candidate(stmt)
        if cand is None:
            return None
        push, info, key = cand
        from ..plan.fragment import merge_push_results
        from ..storage.remote_tier import (PushdownUnsupported,
                                           RemoteRowTier, ReplicationError,
                                           StaleRoutingError)

        store = self.db.stores.get(key)
        if store is None:
            store = self.db.stores[key] = self.db.make_store(info)
        tier = store.replicated
        if not isinstance(tier, RemoteRowTier):
            return None
        try:
            if bool(FLAGS.fragment_pushdown):
                # parallel dispatcher: hash-addressed specs, one thread per
                # region owner, split/migration re-targeting
                # (exec/fragments.py).  Same payloads in the same region
                # order as the serial loop -> bit-identical merge
                from .fragments import dispatch_fragments

                payloads, _fstats = dispatch_fragments(tier, push.frag)
            else:
                payloads = tier.exec_fragment(push.frag)
        except (PushdownUnsupported, ReplicationError,
                StaleRoutingError):
            metrics.fragment_fallbacks.add(1)
            return None          # image path retries / surfaces the error
        with trace.span("fragment.merge", table=key,
                        regions=len(payloads)):
            names, rows = merge_push_results(push, payloads)
        return self._host_rows_result(names, rows)

    @staticmethod
    def _host_rows_result(names: list, rows: list) -> Result:
        """Host-computed row tuples -> Result (pushdown merge, egress
        finish).  from_arrays permits duplicate output names (SELECT a, a
        FROM t) so the wire layer sends the names the client asked for."""
        arrays = []
        for i in range(len(names)):
            vals = [r[i] for r in rows]
            try:
                arrays.append(pa.array(vals))
            except (pa.ArrowInvalid, pa.ArrowTypeError):
                arrays.append(pa.array([None if v is None else str(v)
                                        for v in vals]))
        return Result(columns=list(names),
                      arrow=pa.Table.from_arrays(arrays, names=list(names)))

    def _select_egress(self, eg, cache_key) -> Result:
        """Run the egress-rewritten inner statement, then evaluate the
        string skeletons host-side over the final-sized result
        (exec/egress.py)."""
        from . import egress as egress_mod

        inner_stmt, spec = eg
        key = None if cache_key is None else \
            (cache_key[0] + " /*egress*/", cache_key[1])
        inner = self._select(inner_stmt, cache_key=key)
        names, rows = egress_mod.finish(spec, inner)
        return self._host_rows_result(names, rows)

    # -- OLTP point-read fast path (reference: primary-index point SELECT
    # through the row path, region.cpp select_normal) ----------------------
    def _try_point_lookup(self, stmt: SelectStmt) -> Optional[Result]:
        """WHERE fixes the whole primary key by equality and the statement
        is a plain row fetch: serve from the host tier — no device program,
        no compile, microsecond-class latency (the OLTP path)."""
        from ..expr.ast import ColRef
        from ..index.selector import is_point_statement, point_key

        if not is_point_statement(stmt):
            return None
        db = stmt.table.database or self.current_db
        key = f"{db}.{stmt.table.name}"
        store = self.db.stores.get(key)
        if store is None or store._pk_cols is None:
            return None
        pk = point_key(stmt, store._pk_cols)
        if pk is None:
            return None
        if stmt.offset or stmt.limit == 0:
            return None         # row-skipping edge cases: normal path
        # output must be plain columns (or *); expressions fall through to
        # the normal path rather than re-implementing eval host-side
        names = []
        for it in stmt.items:
            if it.expr is None:
                names.extend(f.name for f in store.info.schema.fields)
            elif isinstance(it.expr, ColRef):
                cname = it.expr.name.split(".")[-1]
                if cname not in store.info.schema:
                    return None
                names.append(it.alias or cname)
            else:
                return None
        if len(set(names)) != len(names):
            return None     # duplicate output names: the device path's
            #                 rename-dedup behavior must not change shape
        try:
            row = store.point_lookup(pk)
        except Exception:
            return None         # any host-index hiccup: run the full path
        metrics.point_lookups.add(1)
        sch = schema_to_arrow(store.info.schema)
        cols: dict = {}
        for it, out_name in zip(self._expand_items(stmt.items, store), names):
            cname = it
            cols[out_name] = pa.array(
                [None if row is None else row.get(cname)],
                sch.field(cname).type)
        t = pa.table(cols) if row is not None else \
            pa.table({n: c.slice(0, 0) for n, c in cols.items()})
        return Result(columns=names, arrow=t)

    def _expand_items(self, items, store):
        out = []
        for it in items:
            if it.expr is None:
                out.extend(f.name for f in store.info.schema.fields)
            else:
                out.append(it.expr.name.split(".")[-1])
        return out

    # -- CDC: FETCH + materialized views (cdc/) ----------------------------
    def _fetch_stmt(self, s: FetchStmt) -> Result:
        """FETCH [n] FROM sub: deliver the next ordered event batch, then
        durably ack past it — deliver-then-ack, so a frontend crash after
        the client read the batch never redelivers it, and a crash BEFORE
        the reply redelivers the whole batch (at-least-once across crash,
        exactly-once in steady state; consumers wanting strict
        exactly-once under crashes dedupe on commit_ts)."""
        import json as _json

        try:
            sub = self.db.cdc.get(s.name)
        except KeyError as e:
            raise PlanError(str(e.args[0])) from None
        events = sub.fetch(s.limit)     # may raise CursorLagging (typed)
        names = ["commit_ts", "event_type", "table_name", "rows",
                 "statement", "affected"]
        rows = [(e.commit_ts, e.event_type, f"{e.database}.{e.table}",
                 _json.dumps(e.rows, default=str), e.statement, e.affected)
                for e in events]
        if events:
            sub.ack(events[-1].commit_ts)
        return self._host_rows_result(names, rows)

    def _try_matview(self, stmt: SelectStmt, refresh: bool = True):
        """If a registered materialized view covers this GROUP BY SELECT,
        fold its pending change-stream deltas (matview_auto_maintain),
        flush state into the hidden __mv_* table, and return the
        rewritten statement.  ``refresh=False`` (EXPLAIN) only rewrites.
        The same gates as _try_rollup: never inside a pinned snapshot or
        an open transaction, never while a seed/rescan query runs."""
        from ..index.rollup import try_rewrite

        if not FLAGS.matview_answer:
            return None
        if getattr(self, "_in_mv_refresh", False) or \
                getattr(self, "_in_rollup_refresh", False):
            return None
        if self._snap_ts or self._sql_txn is not None:
            return None
        if stmt.table is None or stmt.joins or stmt.ctes or stmt.union:
            return None
        db = stmt.table.database or self.current_db
        for mv in self.db.matviews.for_base(f"{db}.{stmt.table.name}"):
            rw = try_rewrite(stmt, stmt.table.name, mv.name, mv.keys,
                             mv.measures, mv.database,
                             target_table=mv.hidden)
            if rw is None:
                continue
            if refresh:
                if FLAGS.matview_auto_maintain:
                    mv.maintain(self)
                mv.materialize(self)
                mv.answered += 1
                metrics.view_answered_queries.add(1)
                # zero-duration marker span: EXPLAIN ANALYZE renders it as
                # the `-- view:` line; info-schema reads the same numbers
                with trace.span("view", view=f"{mv.database}.{mv.name}",
                                applied_ts=mv.applied_ts,
                                staleness_ms=mv.staleness_ms(),
                                deltas_folded=mv.deltas_folded,
                                groups=len(mv.state or {})):
                    pass
            return rw
        return None

    # -- rollup index (reference: I_ROLLUP, region_olap.cpp:530-651) -------
    def _try_rollup(self, stmt: SelectStmt, refresh: bool = True):
        """If a rollup covers this SELECT, refresh it (lazily, on base
        version change) and return the rewritten statement.  ``refresh=False``
        (EXPLAIN) only rewrites — plan display must stay side-effect-free."""
        from ..index.rollup import try_rewrite
        if getattr(self, "_in_rollup_refresh", False):
            return None      # the refresh GROUP BY must hit the base table
        if self._snap_ts:
            # pinned snapshot (explicit SET SNAPSHOT / nested scope): the
            # rollup tracks commit-time freshness, not the pin — and its
            # refresh would write AFTER the pin, hiding its own rows from
            # the versioned read.  Scan the base table versioned instead.
            # (The automatic analytical pin defers to the rollup in
            # _snapshot_scope, so this gate only fires for explicit pins.)
            return None
        if self._sql_txn is not None:
            # inside a transaction the rollup can't see this txn's buffered
            # writes (and refresh would write under the user's locks): scan
            # the base table for read-your-writes semantics
            return None
        if stmt.table is None or stmt.joins or stmt.ctes or stmt.union:
            return None
        db = stmt.table.database or self.current_db
        try:
            info = self.db.catalog.get_table(db, stmt.table.name)
        except ValueError:
            return None
        for ix in info.indexes:
            if ix.kind != "rollup":
                continue
            keys = list(ix.columns)
            measures = list(ix.params.get("measures", ()))
            rw = try_rewrite(stmt, stmt.table.name, ix.name, keys, measures,
                             db)
            if rw is None:
                continue
            if refresh:
                self._refresh_rollup(db, info, ix)
            return rw
        return None

    def _refresh_rollup(self, db: str, info, ix) -> None:
        """Rematerialize iff the base version moved (one GROUP BY program)."""
        from ..index.rollup import refresh_sql, rollup_table_name
        base_key = f"{db}.{info.name}"
        base = self.db.stores[base_key]
        if ix.params.get("fresh_at") == base.version:
            return
        rt = rollup_table_name(info.name, ix.name)
        sql = refresh_sql(f"{db}.{info.name}", rt, list(ix.columns),
                          list(ix.params.get("measures", ())))
        self._in_rollup_refresh = True
        try:
            table = self._execute(sql).arrow
        finally:
            self._in_rollup_refresh = False
        store = self.db.stores[f"{db}.{rt}"]
        store.truncate()
        if table is not None and table.num_rows:
            rinfo = self.db.catalog.get_table(db, rt)
            cast = pa.table({f.name: table.column(f.name).cast(
                schema_to_arrow(rinfo.schema).field(f.name).type)
                for f in rinfo.schema.fields})
            store.insert_arrow(cast, self._tctx(store))
        ix.params["fresh_at"] = base.version

    def _alter_index(self, s: AlterTableStmt, db: str, info) -> Result:
        """Online ADD INDEX: the statement returns once the work is queued
        (reference: DDL accepted by meta's DDLManager, ddl_manager.cpp);
        a background worker backfills region by region and PUBLISHES the
        index, at which point the IndexSelector starts choosing it.  DROP
        INDEX is immediate (the artifact is derived state)."""
        if s.action == "drop_index":
            # only secondary-index kinds: rollups own a hidden backing
            # table and must go through DROP ROLLUP (vector columns are
            # schema-bound); dropping them here would orphan state
            kept = [ix for ix in info.indexes
                    if not (ix.name == s.index_name and
                            ix.kind in ("key", "unique", "fulltext", "ann",
                                        "global", "global_unique"))]
            if len(kept) == len(info.indexes):
                raise PlanError(f"unknown index {s.index_name!r}")
            dropped = [ix for ix in info.indexes if ix not in kept]
            info.indexes = kept
            info.version += 1
            # cached plans compiled WITH the index must re-plan
            self._store(s.table)._mutations += 1
            for ix in dropped:
                if ix.kind in ("global", "global_unique"):
                    self._drop_global_backing(db, info, ix)
            self.db.save_catalog()
            return Result()
        if s.index_kind == "ann":
            vcols = (info.options or {}).get("vector_cols") or {}
            if len(s.index_cols) != 1 or s.index_cols[0] not in vcols:
                raise PlanError("ANN INDEX needs exactly one VECTOR column")
        else:
            self._validate_index_cols(s, info)
        prefix = {"fulltext": "ft", "global": "gidx",
                  "global_unique": "gidx", "ann": "ann"}.get(s.index_kind,
                                                            "idx")
        name = s.index_name or f"{prefix}_{'_'.join(s.index_cols)}"
        if any(ix.name == name for ix in info.indexes):
            raise PlanError(f"index {name!r} exists")
        if s.index_kind in ("global", "global_unique"):
            # online ADD GLOBAL INDEX: register backfilling, materialize the
            # backing table (own regions), hand the fill to the DDL worker;
            # the index becomes choosable — and DML starts maintaining it —
            # only at publish
            ix = IndexInfo(name, s.index_kind, list(s.index_cols),
                           {"state": "backfilling"})
            info.indexes.append(ix)
            self._create_global_backing(db, info, ix)
            self.db.save_catalog()
            work = self.db.ddl.submit(f"{db}.{s.table.name}", ix)
            return Result(affected_rows=0, columns=["work_id"],
                          arrow=pa.table({"work_id": [work.work_id]}))
        if s.index_kind == "fulltext":
            # fulltext is dictionary-side (built lazily per dictionary
            # version, index/fulltext.py) — no backfill artifact: declare
            # it public immediately
            info.indexes.append(IndexInfo(name, "fulltext",
                                          list(s.index_cols)))
            info.version += 1
            self.db.save_catalog()
            return Result()
        if s.index_kind == "ann":
            # trained lazily from the current snapshot on first ANN query
            # (index/annindex drift policy) — no backfill artifact
            info.indexes.append(IndexInfo(name, "ann", list(s.index_cols)))
            info.version += 1
            self._store(s.table)._mutations += 1    # cached plans re-plan
            self.db.save_catalog()
            return Result()
        ix = IndexInfo(name, s.index_kind, list(s.index_cols),
                       {"state": "backfilling"})
        info.indexes.append(ix)
        self.db.save_catalog()
        work = self.db.ddl.submit(f"{db}.{s.table.name}", ix)
        return Result(affected_rows=0,
                      columns=["work_id"],
                      arrow=pa.table({"work_id": [work.work_id]}))

    def _alter_partition(self, s: AlterTableStmt, db: str, info) -> Result:
        """ADD PARTITION extends a range-partitioned table's bounds (the
        reference's dynamic-partition management, table_manager.cpp); DROP
        PARTITION removes a partition's ROWS AND its regions — the
        partition-grade bulk delete."""
        spec = (info.options or {}).get("partition")
        if spec is None:
            raise PlanError(f"table {info.name!r} is not partitioned")
        # NOTE: _execute_stmt already implicit-committed any open
        # transaction before dispatching DDL (MySQL semantics), so a later
        # ROLLBACK can never resurrect rows across the partition remap
        store = self._store(s.table)
        if s.action == "add_partition":
            if spec["kind"] != "range":
                raise PlanError("ADD PARTITION applies to RANGE "
                                "partitioning")
            if s.partition_name in spec["names"]:
                raise PlanError(f"partition {s.partition_name!r} exists")
            if spec["uppers"] and spec["uppers"][-1] is None:
                raise PlanError("cannot ADD PARTITION after MAXVALUE")
            f = info.schema.field(spec["column"])
            if s.partition_upper is not None and spec["uppers"]:
                new_u = store._norm_part_scalar(s.partition_upper, f)
                last_u = store._norm_part_scalar(spec["uppers"][-1], f)
                if new_u <= last_u:
                    raise PlanError("new partition bound must exceed the "
                                    "last bound")
            spec["names"].append(s.partition_name)
            spec["uppers"].append(s.partition_upper)
            info.version += 1
            store._mutations += 1
            self.db.save_catalog()
            return Result()
        # drop_partition
        if spec["kind"] != "range":
            raise PlanError("DROP PARTITION applies to RANGE partitioning")
        if s.partition_name not in spec["names"]:
            raise PlanError(f"unknown partition {s.partition_name!r}")
        if len(spec["names"]) == 1:
            raise PlanError("cannot remove all partitions; use DROP TABLE")
        pid = spec["names"].index(s.partition_name)
        with store._lock:
            coupled = self._coupled_global(store)
            import numpy as np

            def mask_fn(t, _store=store, _pid=pid, _spec=spec):
                ids = _store.partition_ids(t)
                return ids == _pid
            if coupled:
                n = self._delete_with_global(store, coupled, mask_fn)
            else:
                n = store.delete_where(mask_fn, self._tctx(store))
            # remap surviving regions' partition tags past the dropped slot
            spec["names"].pop(pid)
            spec["uppers"].pop(pid)
            for r in store.regions:
                if r.part == pid:
                    r.part = -1          # now empty; tag cleared
                elif r.part > pid:
                    r.part -= 1
            info.version += 1
            store._mutations += 1
        self.db.save_catalog()
        return Result(affected_rows=n)

    def _drop_global_backing(self, db: str, info, ix) -> None:
        from ..index import globalindex as gi

        bname = gi.backing_table_name(info.name, ix.name)
        bkey = f"{db}.{bname}"
        self.db.catalog.drop_table(db, bname, if_exists=True)
        self._drop_durable(bkey, self.db.stores.pop(bkey, None))

    def _validate_index_cols(self, s: AlterTableStmt, info) -> None:
        if not s.index_cols:
            raise PlanError("index needs at least one column")
        for c in s.index_cols:
            if c not in info.schema:
                raise PlanError(f"unknown column {c!r}")

    def _alter_rollup(self, s: AlterTableStmt, db: str, info) -> Result:
        from ..index.rollup import rollup_schema, rollup_table_name
        if s.action == "add_rollup":
            if any(ix.name == s.rollup_name for ix in info.indexes):
                raise PlanError(f"index {s.rollup_name!r} exists")
            for c in s.rollup_keys + s.rollup_aggs:
                if c not in info.schema:
                    raise PlanError(f"unknown column {c!r}")
            if not s.rollup_keys:
                raise PlanError("rollup needs at least one key column")
            sch = rollup_schema(info.schema, s.rollup_keys, s.rollup_aggs)
            rt = rollup_table_name(info.name, s.rollup_name)
            rinfo = self.db.catalog.create_table(db, rt, sch, [])
            self.db.stores[f"{db}.{rt}"] = self.db.make_store(rinfo)
            info.indexes.append(IndexInfo(
                s.rollup_name, "rollup", list(s.rollup_keys),
                {"measures": list(s.rollup_aggs), "fresh_at": -1}))
            self.db.save_catalog()
            return Result()
        # drop_rollup
        kept = [ix for ix in info.indexes
                if not (ix.kind == "rollup" and ix.name == s.rollup_name)]
        if len(kept) == len(info.indexes):
            raise PlanError(f"unknown rollup {s.rollup_name!r}")
        info.indexes = kept
        rt = rollup_table_name(info.name, s.rollup_name)
        self.db.catalog.drop_table(db, rt, if_exists=True)
        st = self.db.stores.pop(f"{db}.{rt}", None)
        self._drop_durable(f"{db}.{rt}", st)
        self.db.save_catalog()
        return Result()

    def _alter_table(self, s: AlterTableStmt) -> Result:
        """ALTER TABLE ADD/DROP COLUMN (reference: online column DDL via the
        meta DDLManager; single-node: immediate schema rewrite)."""
        db = s.table.database or self.current_db
        info = self.db.catalog.get_table(db, s.table.name)
        if s.action in ("add_rollup", "drop_rollup"):
            return self._alter_rollup(s, db, info)
        if s.action in ("add_index", "drop_index"):
            return self._alter_index(s, db, info)
        if s.action in ("add_partition", "drop_partition"):
            return self._alter_partition(s, db, info)
        fields = list(info.schema.fields)
        store = self._store(s.table)
        if s.action == "add_column":
            if s.column.name in info.schema:
                raise PlanError(f"column {s.column.name!r} exists")
            if not s.column.nullable and store.num_rows:
                raise PlanError("cannot ADD COLUMN ... NOT NULL to a non-empty "
                                "table (existing rows would hold NULL)")
            fields.append(Field(s.column.name, parse_type(s.column.type_name),
                                s.column.nullable))
        elif s.action == "drop_column":
            if s.column_name not in info.schema:
                raise PlanError(f"unknown column {s.column_name!r}")
            if len(fields) == 1:
                raise PlanError("cannot drop the last column")
            fields = [f for f in fields if f.name != s.column_name]
            # indexes referencing the dropped column go with it
            info.indexes = [ix for ix in info.indexes
                            if s.column_name not in ix.columns]
        else:
            raise PlanError(f"unsupported ALTER action {s.action!r}")
        new_schema = Schema(tuple(fields))
        store.alter_schema(new_schema)   # bumps info.version itself
        self.db.binlog.append("ddl", db, s.table.name,
                              statement=f"ALTER TABLE {s.table.name} {s.action}")
        self.db.save_catalog()
        return Result()

    def ttl_tick(self, now=None) -> int:
        """Purge expired rows of every TTL table (reference: store-side TTL
        timers).  TTL tables declare options TTL=<seconds> and
        TTL_COLUMN=<datetime col> (default create_time)."""
        import datetime

        now = now or datetime.datetime.now()
        purged = 0
        for key, store in list(self.db.stores.items()):
            opts = store.info.options or {}
            if "ttl" not in opts:
                continue
            try:
                col = opts.get("ttl_column", "create_time")
                f = store.info.schema.field(col) if col in store.info.schema else None
                if f is None or not f.ltype.is_temporal:
                    raise ValueError(f"TTL column {col!r} missing or not temporal")
                cutoff = now - datetime.timedelta(seconds=int(opts["ttl"]))
                if f.ltype is LType.DATE:
                    cutoff = cutoff.date()
                n = store.purge_expired(col, cutoff)
            except Exception as exc:
                # one misconfigured table must not block the sweep
                import logging
                logging.getLogger(__name__).warning("TTL skip %s: %s", key, exc)
                continue
            if n:
                db, name = key.split(".", 1)
                self.db.binlog.append("delete", db, name,
                                      statement=f"TTL purge {col} < {cutoff}",
                                      affected=n)
            purged += n
        return purged

    # -- DML --------------------------------------------------------------
    # -- global secondary indexes (reference: separate.cpp:653 lock nodes,
    # select_manager_node.cpp:1081 lookup join) --------------------------
    def _coupled_global(self, store: TableStore) -> list:
        """[(IndexInfo, backing TableStore)] for this table's PUBLIC global
        indexes: DML must maintain the backing tables in the same (2PC)
        transaction as the main table."""
        from ..index import globalindex as gi

        info = store.info
        if gi.is_backing_table(info.name):
            return []
        out = []
        for ix in info.indexes:
            if ix.kind not in ("global", "global_unique") or \
                    ix.params.get("state", "public") != "public":
                continue
            bname = gi.backing_table_name(info.name, ix.name)
            bkey = f"{info.database}.{bname}"
            bstore = self.db.stores.get(bkey)
            if bstore is None:
                binfo = self.db.catalog.get_table(info.database, bname)
                bstore = self.db.stores[bkey] = self.db.make_store(binfo)
            out.append((ix, bstore))
        return out

    def _run_coupled(self, store: TableStore, coupled: list, fn_main,
                     fns_backing: list):
        """Main-table DML + per-index backing maintenance in ONE atomic
        commit: inside an open transaction they ride the session's per-store
        contexts (COMMIT groups them); in autocommit they run under internal
        contexts committed by commit_group — a single primary-first 2PC
        across every touched region group of every table."""
        from ..storage.column_store import commit_group

        if self._sql_txn is not None:
            r = fn_main(self._tctx(store))
            for (ix, bstore), fb in zip(coupled, fns_backing):
                fb(self._tctx(bstore), r)
            return r
        tctxs = [store.begin_txn()]
        try:
            for ix, bstore in coupled:
                tctxs.append(bstore.begin_txn())
            r = fn_main(tctxs[0])
            for (ix, bstore), fb, t in zip(coupled, fns_backing, tctxs[1:]):
                fb(t, r)
        except BaseException:
            for t in tctxs:
                try:
                    t.rollback()
                except Exception:   # best-effort unwind; keep it countable
                    metrics.count_swallowed("session.coupled_rollback")
            raise
        commit_group(tctxs)
        return r

    def _ingest_arrow(self, store: TableStore, table: "pa.Table",
                      check_dups: bool = False) -> None:
        """Bulk ingest honoring global indexes: entry projections land in
        the backing tables in the same atomic commit (the reference's
        importer maintains global indexes through the same DML plane)."""
        with store._lock:   # one critical section vs backfill publish
            coupled = self._coupled_global(store)
            if not coupled:
                store.insert_arrow(table, self._tctx(store),
                                   check_dups=check_dups)
                return
            from ..index import globalindex as gi

            info = store.info
            if any(ix.kind == "global_unique" for ix, _ in coupled):
                # rows materialize only when a unique check will use them
                rows = table.to_pylist()
                for ix, bstore in coupled:
                    gi.check_unique(info, ix, bstore, rows)

            def main(t):
                store.insert_arrow(table, t, check_dups=check_dups)

            fbs = [(lambda t, _r, ix=ix, b=bstore:
                    b.insert_arrow(gi.entry_table(info, ix, table), t))
                   for ix, bstore in coupled]
            self._run_coupled(store, coupled, main, fbs)

    def _insert_with_global(self, store: TableStore, coupled: list,
                            rows: list[dict]) -> None:
        from ..index import globalindex as gi

        info = store.info
        for ix, bstore in coupled:
            gi.check_unique(info, ix, bstore, rows)

        def main(t):
            store.insert_rows(rows, t)

        fbs = [(lambda t, _r, ix=ix, b=bstore:
                b.insert_rows(gi.entry_rows(info, ix, rows), t))
               for ix, bstore in coupled]
        self._run_coupled(store, coupled, main, fbs)

    def _delete_with_global(self, store: TableStore, coupled: list,
                            mask_fn) -> int:
        from ..index import globalindex as gi

        info = store.info
        cols = sorted({f.name for ix, _ in coupled
                       for f in gi.backing_schema(info, ix).fields})

        def main(t):
            return store.delete_where(mask_fn, t, collect_cols=cols)

        def fb(t, r, ix=None, b=None):
            _, old = r
            entries = gi.entry_table(info, ix, old)
            if entries.num_rows:
                b.delete_where(self._entry_delete_mask(entries), t)

        fbs = [(lambda t, r, ix=ix, b=bstore: fb(t, r, ix, b))
               for ix, bstore in coupled]
        return self._run_coupled(store, coupled, main, fbs)[0]

    def _update_with_global(self, store: TableStore, coupled: list,
                            mask_fn, assign_fn,
                            changed_cols: list[str]) -> int:
        from ..index import globalindex as gi

        info = store.info
        pk = info.primary_key()
        pk_cols = list(pk.columns) if pk else []
        # only indexes whose entries can actually change need maintenance
        touched = [(ix, b) for ix, b in coupled
                   if set(changed_cols) & set(list(ix.columns) + pk_cols)]
        if not touched:
            return store.update_where(mask_fn, assign_fn, self._tctx(store),
                                      changed_cols=changed_cols)
        cols = sorted({f.name for ix, _ in touched
                       for f in gi.backing_schema(info, ix).fields})
        # unique check BEFORE any mutation (a failed check mid-statement
        # would leave main updated but index entries stale): a dry run
        # computes the would-be old/new rows; the caller holds store._lock,
        # so the real update below sees the same rows
        _, dry_old, dry_new = store.update_where(
            mask_fn, assign_fn, self._tctx(store),
            changed_cols=changed_cols, collect_cols=cols, dry_run=True)
        exclude = set(zip(*[dry_old.column(c).to_pylist()
                            for c in pk_cols])) \
            if pk_cols and dry_old.num_rows else set()
        for ix, bstore in touched:
            gi.check_unique(info, ix, bstore, dry_new.to_pylist(),
                            exclude_pks=exclude)

        def main(t):
            return store.update_where(mask_fn, assign_fn, t,
                                      changed_cols=changed_cols,
                                      collect_cols=cols)

        def fb(t, r, ix=None, b=None):
            _, old, new = r
            old_e = gi.entry_table(info, ix, old)
            new_e = gi.entry_table(info, ix, new)
            if old_e.num_rows:
                b.delete_where(self._entry_delete_mask(old_e), t)
            if new_e.num_rows:
                b.insert_rows(new_e.to_pylist(), t)

        fbs = [(lambda t, r, ix=ix, b=bstore: fb(t, r, ix, b))
               for ix, bstore in touched]
        return self._run_coupled(store, touched, main, fbs)[0]

    @staticmethod
    def _entry_delete_mask(entries):
        """Backing-table mask fn matching rows whose full entry tuple is in
        ``entries`` (the outgoing index entries of a DELETE/UPDATE)."""
        import numpy as np

        names = entries.column_names
        tuples = set(zip(*[entries.column(c).to_pylist() for c in names])) \
            if entries.num_rows else set()

        def bmask(bt):
            if not bt.num_rows or not tuples:
                return np.zeros(bt.num_rows, dtype=bool)
            vals = zip(*[bt.column(c).to_pylist() for c in names])
            return np.fromiter((v in tuples for v in vals), dtype=bool,
                               count=bt.num_rows)
        return bmask

    def _insert(self, s: InsertStmt) -> Result:
        store = self._store(s.table)
        schema = store.info.schema
        if s.select is not None:
            sub = self._select(s.select)
            t = sub.arrow
            if s.columns:
                t = t.rename_columns(s.columns)
            else:
                t = t.rename_columns(schema.names()[:t.num_columns])
            if s.replace or s.on_dup:
                # REPLACE INTO .. SELECT / INSERT .. SELECT .. ON DUP KEY:
                # same upsert semantics as the VALUES form
                return self._insert_upsert(
                    store, s, t.to_pylist(),
                    s.table.database or self.current_db)
            if t.num_rows <= HOT_INSERT_ROWS:
                # small INSERT..SELECT takes the hot path: PK-checked and
                # WAL-durable like INSERT..VALUES
                with store._lock:   # vs backfill publish
                    coupled = self._coupled_global(store)
                    if coupled:
                        self._insert_with_global(store, coupled,
                                                 t.to_pylist())
                    else:
                        store.insert_rows(t.to_pylist(), self._tctx(store))
            else:
                self._ingest_arrow(store, t, check_dups=True)
            db_name = s.table.database or self.current_db
            if t.num_rows > 1000:
                self._log_binlog("insert", db_name, s.table.name,
                                 statement=f"bulk insert {t.num_rows} rows",
                                 affected=t.num_rows)
            else:
                self._log_binlog("insert", db_name, s.table.name,
                                 rows=t.to_pylist(), affected=t.num_rows)
            return Result(affected_rows=t.num_rows)
        vcols = (store.info.options or {}).get("vector_cols") or {}
        # positional VALUES address user-visible columns (vector columns by
        # their own names, components hidden)
        cols = s.columns or self._user_columns(store)
        if any(len(r) != len(cols) for r in s.rows):
            raise SqlError("VALUES row length does not match column list")
        rows = [dict(zip(cols, r)) for r in s.rows]
        if vcols:
            rows = [_expand_vector_row(r, vcols) for r in rows]
        auto_col = (store.info.options or {}).get("auto_increment")
        if auto_col:
            missing = [i for i, r in enumerate(rows)
                       if r.get(auto_col) is None]
            if missing:
                ids = store.next_auto_incr(auto_col, len(missing))
                for i, v in zip(missing, ids):
                    rows[i][auto_col] = v
            # explicit ids advance the counter inside the store (all ingest
            # paths — VALUES, INSERT..SELECT, LOAD DATA — share that hook)
        db_name = s.table.database or self.current_db
        for r in rows:
            for f in schema.fields:
                if f.name in r and r[f.name] is not None and f.ltype.is_temporal \
                        and isinstance(r[f.name], str):
                    from ..expr.compile import parse_temporal
                    import datetime
                    v = parse_temporal(r[f.name], f.ltype)
                    if f.ltype is LType.DATE:
                        r[f.name] = datetime.date(1970, 1, 1) + datetime.timedelta(days=v)
                    else:
                        r[f.name] = datetime.datetime(1970, 1, 1) + \
                            datetime.timedelta(microseconds=v)
        if s.replace or s.on_dup:
            return self._insert_upsert(store, s, rows, db_name)
        # the coupling decision, unique check, and mutation must be ONE
        # critical section against the backfill worker's publish (which
        # snapshots + flips the index state under this same lock): deciding
        # "no maintenance" outside it could lose an entry forever
        with store._lock:
            coupled = self._coupled_global(store)
            if coupled:
                self._insert_with_global(store, coupled, rows)
            else:
                store.insert_rows(rows, self._tctx(store))
        self._log_binlog("insert", db_name, s.table.name, rows=rows,
                         affected=len(rows))
        return Result(affected_rows=len(rows))

    def _insert_upsert(self, store: TableStore, s, rows: list[dict],
                       db_name: str) -> Result:
        """REPLACE INTO (delete conflicting PKs, insert all — MySQL counts
        2 per replaced row) and INSERT ... ON DUPLICATE KEY UPDATE
        (insert the new, apply assignments to the conflicting — literals
        and VALUES(col) references).  Reference: insert_planner.cpp
        REPLACE / ON DUP KEY handling."""
        import numpy as np

        if store._pk_cols is None:
            raise PlanError("REPLACE / ON DUPLICATE KEY needs a PRIMARY "
                            "KEY")
        cols = {f.name: [r.get(f.name) for r in rows]
                for f in store.arrow_schema}
        incoming = pa.table(cols, schema=store.arrow_schema)
        with store._lock:
            keys = store._encode_pk_table(incoming)
            idx = store._ensure_pk_index()
            # MySQL processes VALUES rows in order: a key may conflict with
            # the TABLE or with an EARLIER row of the same statement — both
            # are "duplicates", and later occurrences win sequentially
            dupset: set = set()
            new_rows: list[dict] = []
            dup_rows: list[tuple] = []
            seen: set = set()
            for k, r in zip(keys, rows):
                if k in idx or k in seen:
                    dup_rows.append((k, r))
                    if k in idx:
                        dupset.add(k)
                else:
                    new_rows.append(r)
                seen.add(k)
            # rows beyond the first occurrence of their key, however the
            # first fared: each counts as a sequential within-batch replace
            batch_extras = len(rows) - len(seen)

            def mask_over(keyset):
                def pk_mask(t: pa.Table):
                    ks = store._encode_pk_table(t)
                    return np.asarray([k in keyset for k in ks], bool)
                return pk_mask

            coupled = self._coupled_global(store)
            affected = 0
            if s.replace:
                if dupset:
                    if coupled:
                        n = self._delete_with_global(store, coupled,
                                                     mask_over(dupset))
                    else:
                        n = store.delete_where(mask_over(dupset),
                                               self._tctx(store))
                    affected += n
                # last occurrence per key wins (sequential REPLACE result)
                effective: dict = {}
                order: list = []
                for k, r in zip(keys, rows):
                    if k not in effective:
                        order.append(k)
                    effective[k] = r
                ins = [effective[k] for k in order]
                if coupled:
                    self._insert_with_global(store, coupled, ins)
                else:
                    store.insert_rows(ins, self._tctx(store))
                affected += len(rows) + batch_extras
            else:
                if new_rows:
                    if coupled:
                        self._insert_with_global(store, coupled, new_rows)
                    else:
                        store.insert_rows(new_rows, self._tctx(store))
                    affected += len(new_rows)
                if dup_rows:
                    pk_mask = mask_over({k for k, _ in dup_rows})
                    mapping = {}
                    for k, r in dup_rows:
                        vals = {}
                        for col, (kind, v) in s.on_dup:
                            if col not in store.info.schema:
                                raise PlanError(f"unknown column {col!r}")
                            vals[col] = r.get(v) if kind == "values" else v
                        mapping[k] = vals
                    assigned = sorted({c for c, _ in s.on_dup})

                    def assign_fn(t: pa.Table, mask):
                        ks = store._encode_pk_table(t)
                        out = t
                        for col in assigned:
                            f = store.arrow_schema.field(col)
                            old = t.column(col).to_pylist()
                            newv = [mapping.get(k, {}).get(col, old[i])
                                    if m else old[i]
                                    for i, (k, m) in enumerate(
                                        zip(ks, np.asarray(mask)))]
                            out = out.set_column(
                                out.column_names.index(col), f,
                                pa.array(newv, f.type))
                        return out

                    if coupled:
                        n = self._update_with_global(store, coupled,
                                                     pk_mask, assign_fn,
                                                     assigned)
                    else:
                        n = store.update_where(pk_mask, assign_fn,
                                               self._tctx(store),
                                               changed_cols=assigned)
                    affected += 2 * n       # MySQL: 2 per updated row
        # statement image only: the applied row state differs from the
        # incoming VALUES for updated rows, so a row-image 'insert' event
        # would diverge CDC subscribers from the source
        self._log_binlog("insert", db_name, s.table.name,
                         affected=affected,
                         statement=_stmt_image(
                             "replace" if s.replace else "upsert", s))
        return Result(affected_rows=affected)

    def _user_columns(self, store: TableStore) -> list[str]:
        """Declared column order with vector components collapsed back to
        their user-visible vector column name."""
        vcols = (store.info.options or {}).get("vector_cols") or {}
        out: list[str] = []
        for n in store.info.schema.names():
            owner = _component_owner(n, vcols)
            if owner is None:
                out.append(n)
            elif not out or out[-1] != owner:
                out.append(owner)
        return out

    def _host_mask(self, store: TableStore, where):
        """Build host mask fn: predicate evaluated by the SAME device compiler
        over each region (one semantics for reads and writes)."""
        from ..expr.ast import ColRef as _CR

        def fn(region_table: pa.Table):
            if where is None:
                return np.ones(region_table.num_rows, dtype=bool)
            b = ColumnBatch.from_arrow(region_table)
            m = eval_predicate(_qualify_free(where), b)
            return np.asarray(m)

        return fn

    def _pk_mask_fn(self, store: TableStore, key: dict):
        """Host mask for a full-PK-equality WHERE: pyarrow compute only —
        no ColumnBatch encode, no device program (the OLTP write path's
        analog of the point-select fast path; reference: primary-index
        point DML through the row path, region.cpp dml_1pc)."""
        import pyarrow.compute as pc

        sch = store.arrow_schema
        # cast literals NOW, so a type-mismatched literal (id = 2.5 on a
        # BIGINT pk) rejects the fast path here — inside the caller's
        # try/except — instead of aborting the statement mid-region-scan
        # (the compiled predicate evaluates such comparisons numerically)
        scalars = {col: pa.scalar(v).cast(sch.field(col).type)
                   for col, v in key.items()}
        for col, v in key.items():
            if scalars[col].as_py() != v:
                raise ValueError("lossy literal cast")    # e.g. 2.5 -> 2

        def fn(region_table: pa.Table):
            m = None
            for col, sc in scalars.items():
                c = pc.equal(region_table.column(col), sc)
                m = c if m is None else pc.and_(m, c)
            return np.asarray(pc.fill_null(m, False))

        return fn

    def _point_write_mask(self, store: TableStore, where):
        """The cheap PK mask when WHERE fixes the whole primary key by
        equality; None otherwise (fall back to the compiled predicate)."""
        from ..index.selector import point_key

        if store._pk_cols is None or where is None:
            return None

        class _W:                    # point_key reads .where only
            pass

        w = _W()
        w.where = where
        try:
            key = point_key(w, store._pk_cols)
            if key is None:
                return None
            return self._pk_mask_fn(store, key)
        except Exception:
            return None              # odd literal/type: compiled path

    def _update(self, s: UpdateStmt) -> Result:
        store = self._store(s.table)
        schema = store.info.schema
        arrow_schema = store.arrow_schema
        assigns = s.assignments
        for name, _ in assigns:
            if name not in schema:
                raise PlanError(f"unknown column {name!r}")

        def assign_fn(region_table: pa.Table, mask: np.ndarray) -> pa.Table:
            # columnar merge (if_else over the WHERE mask) — no per-row
            # Python; this is the write-path hot loop the reference keeps
            # in C++ (UpdateNode row mutation, src/exec/update_node.cpp)
            b = ColumnBatch.from_arrow(region_table)
            out = region_table
            n = region_table.num_rows
            cond = pa.array(np.asarray(mask, bool))
            for name, e in assigns:
                c = eval_output(_qualify_free(e), b)
                data, valid = c.to_numpy()
                f = arrow_schema.field(name)
                if np.ndim(data) == 0:
                    data = np.broadcast_to(data, (n,))
                if c.ltype is LType.STRING and c.dictionary is not None:
                    vals = c.dictionary.decode(np.asarray(data, np.int32))
                else:
                    vals = np.asarray(data)
                if valid is None:
                    nulls = None
                else:
                    v = np.asarray(valid, bool)
                    nulls = ~(np.broadcast_to(v, (n,)) if v.ndim == 0 else v)
                new_arr = pa.array(vals, mask=nulls)
                if new_arr.type != f.type:
                    new_arr = new_arr.cast(f.type)
                idx = out.column_names.index(name)
                merged = pa.compute.if_else(cond, new_arr, out.column(name))
                out = out.set_column(idx, f, merged)
            return out

        mask_fn = self._point_write_mask(store, s.where)
        if mask_fn is not None:
            # point update: evaluate assignments on the ONE matched row,
            # restricted to the columns the assignments actually touch
            # (encoding untouched VARCHARs into device dictionaries is the
            # dominant cost otherwise), then scalar-merge into the region
            from ..expr.ast import ColRef as _CRef

            needed = {store._pk_cols[0]}
            for name, e in assigns:
                needed.add(name)
                stack = [_qualify_free(e)]
                while stack:
                    x = stack.pop()
                    if isinstance(x, _CRef):
                        needed.add(x.name.split(".")[-1])
                    stack.extend(getattr(x, "args", ()) or ())
            full_assign = assign_fn

            def assign_fn(region_table, mask, _full=full_assign):
                cond = pa.array(np.asarray(mask, bool))
                rows = region_table.filter(cond)
                if rows.num_rows != 1:      # PK dup (shouldn't happen):
                    return _full(region_table, np.asarray(mask, bool))
                rows = rows.select([c for c in region_table.column_names
                                    if c in needed])
                try:
                    small = _full(rows, np.ones(1, dtype=bool))
                except Exception:
                    # a 1-row slice can hit shapes the full path never sees
                    # (e.g. empty dictionaries); semantics win over speed
                    return _full(region_table, np.asarray(mask, bool))
                out = region_table
                for name, _ in assigns:
                    f = arrow_schema.field(name)
                    idx = out.column_names.index(name)
                    merged = pa.compute.if_else(cond, small.column(name)[0],
                                                out.column(name))
                    out = out.set_column(idx, f, merged)
                return out
        else:
            mask_fn = self._host_mask(store, s.where)
        changed = [name for name, _ in assigns]
        db_name = s.table.database or self.current_db
        # row-image capture for CDC/matviews: old/new pairs let consumers
        # fold the delta instead of rescanning; only on the non-coupled
        # path (the global-index path dry-runs assign_fn, which would
        # double-capture) and self-verified below against the affected
        # count — any mismatch falls back to the statement image, which
        # consumers treat as "rescan"
        captured: list = []
        with store._lock:   # one critical section vs backfill publish
            coupled = self._coupled_global(store)
            if coupled:
                n = self._update_with_global(store, coupled, mask_fn,
                                             assign_fn, changed)
            else:
                use_assign = assign_fn
                if self.db.cdc.wants_rows(f"{db_name}.{s.table.name}"):
                    def use_assign(t, mask, _inner=assign_fn):
                        cond = pa.array(np.asarray(mask, bool))
                        old = t.filter(cond).to_pylist()
                        out = _inner(t, mask)
                        new = out.filter(cond).to_pylist()
                        captured.extend({"old": o, "new": w}
                                        for o, w in zip(old, new))
                        return out
                n = store.update_where(mask_fn, use_assign,
                                       self._tctx(store),
                                       changed_cols=changed)
        if n:
            rows = captured if len(captured) == n else None
            self._log_binlog("update", db_name, s.table.name, rows=rows,
                             statement=_stmt_image("update", s), affected=n)
        return Result(affected_rows=n)

    def _delete(self, s: DeleteStmt) -> Result:
        store = self._store(s.table)
        mask_fn = self._point_write_mask(store, s.where) or \
            self._host_mask(store, s.where)
        db_name = s.table.database or self.current_db
        # row-image capture (see _update): outgoing rows let CDC consumers
        # retract exactly; count-verified, statement-image fallback
        captured: list = []
        with store._lock:   # one critical section vs backfill publish
            coupled = self._coupled_global(store)
            if coupled:
                n = self._delete_with_global(store, coupled, mask_fn)
            else:
                use_mask = mask_fn
                if self.db.cdc.wants_rows(f"{db_name}.{s.table.name}"):
                    def use_mask(t, _inner=mask_fn):
                        m = np.asarray(_inner(t), bool)
                        if m.any():
                            captured.extend(
                                t.filter(pa.array(m)).to_pylist())
                        return m
                n = store.delete_where(use_mask, self._tctx(store))
        if n:
            rows = captured if len(captured) == n else None
            self._log_binlog("delete", db_name, s.table.name, rows=rows,
                             statement=_stmt_image("delete", s), affected=n)
        return Result(affected_rows=n)

    # -- SELECT ---------------------------------------------------------
    def _select_into_outfile(self, stmt: SelectStmt, cache_key) -> Result:
        """SELECT ... INTO OUTFILE: run the query, stream the rows to a
        file (reference: full_export_node streaming export,
        src/exec/full_export_node.cpp).  MySQL conventions: refuses to
        overwrite (O_EXCL claim, concurrency-safe), \\N for NULL,
        backslash escaping of separators, 1/0 booleans, the row count as
        the result."""
        import copy
        import os
        import tempfile

        path, fsep, lsep = stmt.into_outfile
        try:
            final_fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            raise PlanError(f"OUTFILE {path!r} already exists") from None
        inner = copy.copy(stmt)
        inner.into_outfile = None
        try:
            res = self._select(
                inner, cache_key=None if cache_key is None else
                (cache_key[0] + " /*outfile*/", cache_key[1]))

            def cell(v):
                if v is None:
                    return "\\N"
                if isinstance(v, bool):
                    return "1" if v else "0"
                s = str(v)
                return (s.replace("\\", "\\\\")
                        .replace(fsep, "\\" + fsep)
                        .replace(lsep, "\\" + lsep))

            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(
                os.path.abspath(path)) or ".", suffix=".outfile")
            try:
                with os.fdopen(fd, "w", encoding="utf-8", newline="") as f:
                    for r in res.rows:          # positional: duplicate
                        f.write(fsep.join(       # column names stay intact
                            cell(v) for v in r) + lsep)
                os.replace(tmp, path)
            except BaseException:
                os.unlink(tmp)
                raise
        except BaseException:
            os.close(final_fd)
            os.unlink(path)
            raise
        os.close(final_fd)
        n = res.arrow.num_rows if res.arrow is not None else 0
        return Result(affected_rows=n)

    def _select_group_concat(self, stmt: SelectStmt) -> Result:
        """GROUP_CONCAT is an egress aggregate: device strings are dictionary
        codes, so concatenation happens at the result layer (the reference
        also accumulates GROUP_CONCAT strings row-wise on CPU,
        src/expr/agg_fn_call.cpp — same tier, different engine).  Runs the
        grouped query without the GROUP_CONCAT items plus one detail query
        (keys + inputs), then assembles strings host-side."""
        import copy

        from ..expr.ast import AggCall
        from ..plan.planner import _display_name
        from ..sql.stmt import SelectItem

        from ..expr.ast import Call as _Call
        from ..expr.ast import ColRef as _ColRef
        from ..expr.ast import Lit as _Lit

        gc: dict[int, object] = {}
        for i, item in enumerate(stmt.items):
            e = item.expr
            if isinstance(e, AggCall) and e.op == "group_concat":
                extra = e.args[1:]
                if any(not (isinstance(x, _Call) and x.op == "__sep")
                       for x in extra):
                    raise PlanError("multi-argument GROUP_CONCAT is not "
                                    "supported (use CONCAT inside it)")
                gc[i] = item

        def mentions_gc(e):
            if isinstance(e, AggCall) and e.op == "group_concat":
                return True
            args = getattr(e, "args", ())
            return any(mentions_gc(a) for a in args)

        if stmt.having is not None and mentions_gc(stmt.having):
            raise PlanError("GROUP_CONCAT in HAVING is not supported")
        gc_aliases = {stmt.items[i].alias for i in gc if stmt.items[i].alias}
        for o in stmt.order_by:
            if mentions_gc(o.expr) or (isinstance(o.expr, _ColRef) and
                                       o.expr.table is None and
                                       o.expr.name in gc_aliases):
                raise PlanError("GROUP_CONCAT in ORDER BY is not supported")
        for i, item in enumerate(stmt.items):
            if i not in gc and mentions_gc(item.expr):
                raise PlanError("GROUP_CONCAT nested in an expression is "
                                "not supported")

        # resolve ordinal (GROUP BY 1) and select-alias keys BEFORE copying
        # them into the helper queries (the planner normally does this)
        keys = []
        alias_map = {it.alias: it.expr for it in stmt.items if it.alias}
        for k in stmt.group_by:
            if isinstance(k, _Lit) and isinstance(k.value, int):
                idx = k.value - 1
                if not 0 <= idx < len(stmt.items) or idx in gc:
                    raise PlanError(f"GROUP BY ordinal {k.value} is invalid "
                                    "here")
                keys.append(stmt.items[idx].expr)
            elif isinstance(k, _ColRef) and k.table is None and \
                    k.name in alias_map:
                if mentions_gc(alias_map[k.name]):
                    raise PlanError("GROUP BY a GROUP_CONCAT alias is invalid")
                keys.append(alias_map[k.name])
            else:
                keys.append(k)
        key_aliases = [f"__gck{j}" for j in range(len(keys))]
        base = copy.copy(stmt)
        base.group_by = [copy.copy(k) for k in keys]   # resolved form
        base.items = [it for i, it in enumerate(stmt.items) if i not in gc]
        n_vis = len(base.items)
        base.items = base.items + [SelectItem(copy.copy(k), a)
                                   for k, a in zip(keys, key_aliases)]
        if not base.items:
            base.items = [SelectItem(AggCall("count_star", ()), "__gcn")]
            n_vis = 0
        main = self._select(base)

        detail = copy.copy(stmt)
        detail.group_by = []
        detail.having = None
        detail.order_by = []
        detail.limit = None
        detail.offset = 0
        detail.distinct = False
        ins = [gc[i].expr.args[0] for i in gc]
        detail.items = [SelectItem(copy.copy(k), a)
                        for k, a in zip(keys, key_aliases)] + \
                       [SelectItem(copy.copy(e), f"__gcv{j}")
                        for j, e in enumerate(ins)]
        drows = self._select(detail).to_pylist()
        groups: dict[tuple, list[list]] = {}
        for r in drows:
            k = tuple(r[a] for a in key_aliases)
            slot = groups.setdefault(k, [[] for _ in ins])
            for j in range(len(ins)):
                v = r[f"__gcv{j}"]
                if v is not None:
                    slot[j].append(v)

        mrows = main.to_pylist()
        mcols = list(main.arrow.column_names)
        out_cols: dict[str, list] = {}
        order_names: list[str] = []
        vis_iter = iter(mcols[:n_vis])
        gclist = list(gc.items())
        for i, item in enumerate(stmt.items):
            if i in gc:
                j = next(jj for jj, (idx, _) in enumerate(gclist) if idx == i)
                call = gc[i].expr
                sep = ","
                if len(call.args) > 1:
                    sep = str(call.args[1].args[0].value)   # __sep wrapper
                vals = []
                for r in mrows:
                    k = tuple(r[a] for a in key_aliases)
                    lst = groups.get(k, [[] for _ in ins])[j]
                    if call.distinct:
                        lst = sorted(set(map(str, lst)))
                    else:
                        lst = list(map(str, lst))
                    # MySQL truncates at group_concat_max_len (default 1024)
                    vals.append(sep.join(lst)[:1024] if lst else None)
                name = gc[i].alias or _display_name(call)
                order_names.append(name)
                out_cols[name] = vals
            else:
                name = next(vis_iter)
                order_names.append(name)
                out_cols[name] = [r[name] for r in mrows]
        table = pa.table({n: out_cols[n] for n in order_names})
        return Result(columns=order_names, arrow=table)

    def _select(self, stmt: SelectStmt, cache_key=None) -> Result:
        """MVCC snapshot scope around the planner: resolve the read
        timestamp (explicit SET SNAPSHOT pin, else an automatic pin for
        eligible analytical statements), hold it in ``self._snap_ts`` for
        the whole execution — every batch-staging seam underneath reads
        it — and release an automatic pin when the query finishes."""
        with self._snapshot_pinned(stmt):
            return self._select_impl(stmt, cache_key)

    @contextmanager
    def _snapshot_pinned(self, stmt: SelectStmt):
        """Enter this SELECT's snapshot scope (see _snapshot_scope)."""
        pin = self._snapshot_scope(stmt)
        if pin is None:
            yield
            return
        pid, ts = pin
        prev = self._snap_ts
        self._snap_ts = ts
        try:
            yield
        finally:
            self._snap_ts = prev
            if pid is not None:
                self.db.mvcc.snapshots.unpin(pid)

    def _snapshot_scope(self, stmt: SelectStmt):
        """(pin_id | None, snap_ts) for this SELECT, or None to read
        unpinned.  An explicit session pin (SET SNAPSHOT) always applies
        and is NOT released per-query (pin_id None here).  Otherwise an
        analytical statement (GROUP BY / aggregates) pins a fresh
        timestamp automatically for its own duration, so a long scan sees
        one consistent state under live writes — but only outside SQL
        transactions (the txn's own locks already isolate it) and off the
        mesh path (sharded device batches stage through their own seam;
        documented limitation).  A chaos-refused automatic pin degrades
        to the unpinned read it would have been before MVCC."""
        if not bool(FLAGS.mvcc):
            return None
        if self._snap_ts:
            return None     # nested SELECT (subquery): inherit the scope
        if self._snapshot is not None:
            return (None, self._snapshot[1])
        if self._sql_txn is not None or self.mesh is not None:
            return None
        from ..expr.ast import AggCall
        analytical = bool(stmt.group_by) or any(
            isinstance(it.expr, AggCall) for it in stmt.items)
        if not analytical:
            return None
        if self._try_matview(stmt, refresh=False) is not None:
            # a materialized view will answer this aggregate from folded
            # state; pinning first would hide the maintenance writes
            return None
        if self._try_rollup(stmt, refresh=False) is not None:
            # a rollup covers this aggregate: the version-gated refresh
            # already materializes ONE consistent cut of the base table,
            # and pinning first would hide the refresh's own writes
            return None
        if self._pushdown_candidate(stmt) is not None:
            # served by daemon-plane fragments over their own region
            # images; snapshot_ts does not travel with fragments yet
            # (ROADMAP), so a pin only adds TSO/registry round-trips
            return None
        from ..storage.mvcc import SnapshotRefused
        ts = self.db.mvcc.now_ts()
        try:
            with trace.span("snapshot.pin", ts=ts, explicit=False):
                pid = self.db.mvcc.snapshots.pin(
                    ts, query="auto", holder=self.user)
        except SnapshotRefused:
            metrics.count_swallowed("snapshot.autopin")
            return None
        return (pid, ts)

    def _snap_dirty(self, stmt) -> bool:
        """Does the pinned snapshot actually diverge from the live image
        of this statement's table?  Quiet tables keep their fast paths
        (egress, point lookup, pushdown): those read the current image,
        which IS the snapshot state when nothing committed past the pin."""
        if not self._snap_ts:
            return False
        t = getattr(stmt, "table", None)
        if t is None or getattr(stmt, "joins", None):
            return True     # multi-table: stage per-table versioned batches
        dbname = t.database or self.current_db
        store = self.db.stores.get(f"{dbname}.{t.name}")
        if store is None:
            return False    # view / info-schema / unstaged: nothing to pin
        return store.mvcc_needs_versioned(self._snap_ts)

    def _select_impl(self, stmt: SelectStmt, cache_key=None) -> Result:
        """Plan cache (reference: state_machine.cpp:1984): one logical plan
        per SQL text, one compiled executable per (table versions, shapes)."""
        from ..expr.ast import AggCall

        if stmt.into_outfile is not None:
            return self._select_into_outfile(stmt, cache_key)
        pushed = self._try_pushdown(stmt)
        if pushed is not None:
            return pushed
        from . import egress as egress_mod
        # pinned snapshot over a table with version churn: egress streaming
        # and rowstore point lookups read the physically-latest image
        # directly — route them through the versioned batch staging.  A
        # quiet table's live image IS the snapshot state, so its fast
        # paths stay engaged (bit-identical by construction).
        snap_dirty = self._snap_dirty(stmt)
        eg = None if snap_dirty else egress_mod.extract(stmt, self)
        if eg is not None:
            return self._select_egress(eg, cache_key)
        point = None if snap_dirty else self._try_point_lookup(stmt)
        if point is not None:
            return point
        rewritten = self._try_matview(stmt)
        if rewritten is not None:
            # answered from incrementally maintained view state: re-enter
            # with the hidden-table statement (cdc/views.py)
            stmt = rewritten
            cache_key = None if cache_key is None else \
                (cache_key[0] + " /*mv*/", cache_key[1])
        else:
            rewritten = self._try_rollup(stmt)
            if rewritten is not None:
                # re-enter with the rollup statement; versions in the cache
                # key come from the rollup store, which refresh just bumped
                stmt = rewritten
                cache_key = None if cache_key is None else \
                    (cache_key[0] + " /*rollup*/", cache_key[1])

        def _has_gc(e):
            if e is None:
                return False
            if isinstance(e, AggCall) and e.op == "group_concat":
                return True
            return any(_has_gc(a) for a in getattr(e, "args", ()))

        if any(_has_gc(it.expr) for it in stmt.items) or _has_gc(stmt.having) \
                or any(_has_gc(o.expr) for o in stmt.order_by):
            return self._select_group_concat(stmt)
        # auto-parameterization (plan/paramize.py): hoist WHERE literals
        # into a runtime params vector and key the plan cache on the
        # canonical statement structure — WHERE id = 42 and WHERE id = 43
        # share one entry AND one compiled executable.  Mesh programs
        # participate too: the executor's per-leaf in_specs replicate the
        # params feed (P()) while batches shard P(AXIS), so one shard_map
        # executable serves every literal variant — without this, the big
        # MPP programs (fused multiway exchange) would fork per WHERE value.
        norm = None
        lookup_key = cache_key
        stmt_run = stmt
        if cache_key is not None and bool(FLAGS.param_queries):
            try:
                with trace.span("plan.paramize"):
                    n = paramize.normalize(stmt, self._param_resolver(stmt))
            except Exception:   # noqa: BLE001 — normalization is an
                #                 optimization; a bug must not fail the query
                metrics.count_swallowed("session.paramize")
                n = None
            if n is not None and n.slots:
                norm = n
                lookup_key = ("//params", self.current_db, n.key)
                stmt_run = n.stmt
                metrics.params_hoisted.add(len(n.slots))
                if self.mesh is not None and stmt.group_by:
                    # selectivity-aware parameterized plans (scoped to the
                    # adaptive-agg decision): the bound values' combined
                    # WHERE selectivity joins the cache key as a coarse
                    # CLASS, so a highly selective literal replans (and can
                    # flip local->raw) while same-regime literals share one
                    # plan + executable.  Class 0 / no-basis keep the
                    # unsuffixed key, and only GROUP BY statements key at
                    # all (the class exists to flip the keyed-agg
                    # local/raw decision; forking scalar-agg executables
                    # per class would repay nothing) — the common case
                    # pays nothing.
                    from ..index.stats import selectivity_class
                    from ..parallel import agg as _agg  # noqa: F401 —
                    #   defines the adaptive_agg_* flags

                    wsel = self._where_selectivity(stmt) \
                        if bool(FLAGS.adaptive_agg_selectivity) else None
                    self._where_sel_hint = wsel
                    cls = selectivity_class(wsel)
                    if cls > 0:
                        lookup_key = lookup_key + (f"selcls{cls}",)
        if norm is None:
            return self._select_cached(stmt, cache_key, cache_key, None)
        from ..expr.compile import ExprError
        from ..expr.params import ParamError
        self._param_counted = False
        try:
            return self._select_cached(stmt_run, cache_key, lookup_key, norm)
        except (paramize.BindError, ExprError, ParamError, PlanError):
            # conservative valve: anything the parameterized path cannot
            # express replans with baked literals (a genuine user error
            # re-raises identically from the baked run)
            self._plan_cache.pop(lookup_key, None)
            # hold the one-count-per-SELECT invariant: the baked re-run
            # only counts if the param attempt died before its counter
            self._qlog_outcome = "fallback"   # query_log: WHY it was slow
            try:
                res = self._select_cached(stmt, cache_key, cache_key, None,
                                          count=not self._param_counted)
            finally:
                self._qlog_outcome = None
            # counted only when the baked run SUCCEEDED: a genuine user
            # error (unknown column, bad subquery) re-raised above and is
            # not a param-machinery fallback — the metric stays an alarm
            # for the parameterized path itself
            metrics.plan_cache_param_fallbacks.add(1)
            return res
        finally:
            self._where_sel_hint = None

    def _select_cached(self, stmt: SelectStmt, text_key, lookup_key,
                       norm, count: bool = True) -> Result:
        qp = progress.current()
        qp.beat(phase="plan")
        entry = self._plan_cache.get(lookup_key) if lookup_key else None
        replanned = False
        if entry is not None:
            self._plan_cache.move_to_end(lookup_key)
            # stats-derived plan choices (dense group-by domains, key shifts)
            # go stale when data changes: replan on any version bump
            stale = any(self.db.stores.get(tk) is None or
                        self.db.stores[tk].version != v
                        for tk, v in entry["versions"].items())
            # view redefinitions (possibly by ANOTHER session) change plans
            # without touching any table store version
            if entry.get("view_gen") != self.db.catalog.view_gen:
                entry = None
            elif stale:
                # version gates the PLAN only, the capacity bucket gates the
                # executable: replan (stats may have moved), and when the
                # fresh plan is structurally identical keep the old entry —
                # its settled join caps AND its compiled executables, which
                # stay valid because bucketed shapes survive the DML.  Only
                # a genuinely different plan drops the executables.
                plan = self._plan_select(stmt)
                sig = plan_signature(plan)
                if sig != entry.get("plan_sig"):
                    entry["plan"] = plan
                    entry["plan_sig"] = sig
                    entry["compiled"] = {}
                    entry.pop("exchange_summary", None)  # re-count: the
                    # fresh plan may shuffle differently
                    # the plan AND every executable were just rebuilt: in
                    # cost terms this is a miss, and the hit/miss split is
                    # how recompile churn shows on dashboards
                    replanned = True
        hit = entry is not None and not replanned
        hit_text = entry.get("text") if entry is not None else None
        if entry is None:
            plan = self._plan_select(stmt)
            entry = {"plan": plan, "plan_sig": plan_signature(plan),
                     "compiled": {}, "versions": {},
                     "view_gen": self.db.catalog.view_gen,
                     "text": text_key[0] if text_key else None}
            cap = int(FLAGS.plan_cache_size)
            if lookup_key and cap > 0:
                self._plan_cache[lookup_key] = entry
                while len(self._plan_cache) > cap:
                    self._plan_cache.popitem(last=False)
        # accounting invariant (tests/test_param_cache.py): each SELECT
        # counts exactly one of {hit, param_hit, miss} — counted AFTER the
        # fallible planning so a param-path fallback can re-count iff this
        # attempt never did.  A hit that still re-traces downstream
        # (capacity-bucket crossing) is a plan-level HIT — the trace shows
        # in xla_retraces/compile_ms, never as a plan-cache miss
        if hit and norm is not None and text_key is not None \
                and hit_text != text_key[0]:
            outcome = "param_hit"
        elif hit:
            outcome = "hit"
        else:
            outcome = "miss"
        if count:
            if outcome == "param_hit":
                metrics.plan_cache_param_hits.add(1)
            elif outcome == "hit":
                metrics.plan_cache_hits.add(1)
            else:
                metrics.plan_cache_misses.add(1)
            self._param_counted = True
        # the query_log row reports the param-machinery fallback, not the
        # baked re-run's own hit/miss — that's the "why was it slow" signal
        qlog_outcome = getattr(self, "_qlog_outcome", None) or outcome
        trace.event("plan.cache", outcome=qlog_outcome)
        plan = entry["plan"]
        # forensic-dump reference + progress denominators (host plan walk,
        # cached on the entry): SHOW PROCESSLIST renders "batch m/n" /
        # "round m/n" against these before the first scan even stages
        totals = entry.get("progress_totals")
        if totals is None:
            totals = entry["progress_totals"] = \
                executor.progress_totals(plan)
        qp.beat(phase="exec.batches", plan=plan,
                batches_total=totals["scans"],
                rounds_total=totals["rounds"] if self.mesh is not None
                else 0)
        # host-side access paths (index gather, zonemap/partition pruning)
        # see this execution's literal values even though the compiled plan
        # does not: _access_path_batch substitutes them into pushed filters
        self._param_subst = {s.index: s for s in norm.slots} \
            if norm is not None else None
        try:
            with trace.span("exec.batches"):
                batches, shape_key, full_scan = self._collect_batches(plan)
        finally:
            self._param_subst = None
        entry["versions"] = {p[0]: p[1] for p in shape_key}
        if norm is not None:
            from ..expr.params import PARAMS_KEY
            with trace.span("plan.bind"):
                batches[PARAMS_KEY] = paramize.bind(norm.slots, batches)
        t0 = time.perf_counter()
        qp.beat(phase="exec.run")
        result = self._maybe_batched_run(entry, batches, shape_key, norm,
                                         lookup_key, full_scan)
        qp.beat(phase="egress.arrow")
        with trace.span("egress.arrow"):
            table = result.to_arrow()
        dur_ms = (time.perf_counter() - t0) * 1e3
        # close the egress wall-clock bucket so the query_log row carries
        # every phase (the beats ride the same seams as the trace spans —
        # SHOW PROFILE over the trace shows the same splits)
        qp.beat(phase="finish", rows_done=table.num_rows)
        if text_key is not None:
            # slow-query rows explain WHY: plan-cache outcome + the
            # capacity buckets the scan batches compiled against
            buckets = ";".join(f"{p[0]}={p[2]}"
                               for p in sorted(shape_key))
            self.db.query_log.append((text_key[0], dur_ms, table.num_rows,
                                      qlog_outcome, buckets, qp.phase_ms(),
                                      self._snap_ts))
        return Result(columns=list(table.column_names), arrow=table)

    def _param_resolver(self, stmt: SelectStmt):
        """(table_label, column) -> (table_key, LType) against the live
        catalog, for paramize's string-literal binder analysis.  Only plain
        base tables resolve; derived tables/views/ambiguous names return
        None, pinning their comparands."""
        tables: dict = {}
        for r in [stmt.table] + [j.table for j in stmt.joins]:
            if r is None or r.subquery is not None:
                continue
            db = r.database or self.current_db
            try:
                info = self.db.catalog.get_table(db, r.name)
            except (ValueError, KeyError):      # view/unknown name: pin
                continue
            tables[r.label] = (f"{db}.{r.name}", info.schema)

        def resolve(tlabel, col):
            cname = col.split(".")[-1]
            if tlabel is not None:
                ent = tables.get(tlabel)
                if ent is not None and cname in ent[1]:
                    return (ent[0], ent[1].field(cname).ltype)
                return None
            hits = [(tk, sch.field(cname).ltype)
                    for tk, sch in tables.values() if cname in sch]
            return hits[0] if len(hits) == 1 else None
        return resolve

    def _explain_analyze(self, stmt: SelectStmt) -> Result:
        """EXPLAIN ANALYZE: run the query once, report per-operator live-row
        counts + compile/run wall time (reference: EXPLAIN FORMAT='analyze'
        over the TraceNode tree, trace_state.h).

        One timing truth: every measurement records as spans/events in the
        query's trace (forced — EXPLAIN ANALYZE always traces, sampler or
        no), and the ``--`` telemetry lines below render FROM those span
        records.  SHOW PROFILE over the same trace shows the same numbers;
        there is no second timing path."""
        with trace.root("explain_analyze", force=True):
            m = trace.mark()
            with self._snapshot_pinned(stmt):
                self._explain_analyze_measure(stmt)
            spans = trace.since(m)
        lines = self._render_analyze(spans)
        txt = "\n".join(lines)
        return Result(columns=["plan"], plan_text=txt,
                      arrow=pa.table({"plan": lines}))

    def _explain_analyze_measure(self, stmt: SelectStmt) -> None:
        """Run + instrument; all output lands in the active trace."""
        # materialized-view answering applies here exactly as in _select
        # (the zero-duration `view` span renders the `-- view:` line)
        rw = self._try_matview(stmt)
        if rw is not None:
            stmt = rw
        cand = self._pushdown_candidate(stmt)
        if cand is not None:
            # pushed-fragment execution: the dispatcher's `fragments`
            # event (dispatched/local/retargeted/partial_rows/bytes_saved)
            # IS the measurement — render the store/frontend plan split
            # and skip the image-path instrumentation, which would measure
            # a plan that does not run
            pushed = self._try_pushdown(stmt)
            if pushed is not None:
                for line in self._render_pushdown(*cand).splitlines():
                    trace.event("op", label=line)
                return
            # dispatch fell back: measure the image path below
        plan = self._plan_select(stmt)
        batches, shape_key, full_scan = self._collect_batches(plan)
        # settle join caps first (the overflow-retry loop), so traced counts
        # describe the plan that actually runs, not a truncated first attempt
        entry = {"plan": plan, "compiled": {}, "versions": {}}
        self._run_plan(entry, batches, shape_key)
        if streaming.stream_source(batches) is not None:
            # chunk-folded execution: there is no single jitted program to
            # re-run under the counting tracer (the scan input is a host
            # chunk iterator) — ops render uncounted; the measured fold
            # telemetry landed in the run's `stream` event instead
            by_node: dict = {}
        else:
            raw = compile_plan(plan, trace=True,
                               mesh=self.mesh if batches else None)
            fn = jax.jit(raw)
            with trace.span("exec.first"):
                with hot_path_guard():
                    out, flags, counts = fn(batches)
                jax.block_until_ready(jax.tree.leaves(counts))
            with trace.span("exec.steady"):
                with hot_path_guard():
                    out, flags, counts = fn(batches)
                jax.block_until_ready(jax.tree.leaves(counts))
            # materialize every per-node counter in one explicit transfer —
            # int(c) per operator is a device round-trip each
            # (tpulint HOSTSYNC)
            by_node = {id(n): int(c) for n, c in
                       zip(raw.trace_order, jax.device_get(counts))}

        def render(node: PlanNode, indent: int):
            rows = by_node.get(id(node))
            attrs = {} if rows is None else {"rows": rows}
            trace.event("op", label="  " * indent + node._label(), **attrs)
            for c in node.children:
                render(c, indent + 1)

        render(plan, 0)
        # capacity buckets + compile telemetry: which shapes this query
        # compiled against, and the engine-wide retrace/compile counters
        # (steady state = xla_retraces stops moving between identical runs)
        scans = [(p[0], p[2], batches[p[0]]) for p in sorted(shape_key)
                 if isinstance(batches.get(p[0]), ColumnBatch)]
        # one fused transfer for all live counts (not an int() per table)
        lives = jax.device_get([b.live_count() for _, _, b in scans])
        for (tk, cap, _b), live in zip(scans, lives):
            # only full-table scans carry pow2 capacity buckets; an index/
            # ANN access-path batch's shape is just its candidate count
            # (and DOES retrace per version) — label it honestly
            kind = "capacity" if tk in full_scan else "gathered"
            trace.event("batch", table=tk, kind=kind, capacity=int(cap),
                        live=int(live))
        cstats = metrics.compile_ms.stats()
        trace.event("xla", retraces_total=metrics.xla_retraces.value,
                    compiles=cstats["count"],
                    compile_avg_ms=cstats["avg_ms"])
        # AOT persistent executable cache: whether this node can serve the
        # plan without compiling after a restart, and the engine-wide
        # hit/miss/fallback state of the tier
        dstats = metrics.aot_cache_deser_ms.stats()
        trace.event("aot", enabled=int(compilecache.AOT.enabled()),
                    hits_total=metrics.aot_cache_hits.value,
                    misses_total=metrics.aot_cache_misses.value,
                    fallbacks_total=metrics.aot_cache_fallbacks.value,
                    publishes_total=metrics.aot_cache_publishes.value,
                    deser_avg_ms=dstats["avg_ms"])
        # device-resource accounting for THIS plan's executable (same rows
        # as information_schema.executables): what the program costs the
        # accelerator, not just how long the host waited
        if compilecache.EXECUTABLES.enabled():
            dev = compilecache.EXECUTABLES.find(
                plan_sig=entry.get("plan_sig"))
            if dev is not None:
                trace.event("device", compile_ms=dev["last_compile_ms"],
                            flops=dev["flops"],
                            bytes_accessed=dev["bytes_accessed"],
                            peak_hbm_bytes=dev["peak_hbm_bytes"],
                            source=dev["mem_source"])
        # literal auto-parameterization: how many literals the normalizer
        # hoists into runtime params vs pins into the cache key for this
        # statement (plan/paramize.py; pinned = shape/trace-time feeders)
        try:
            nz = paramize.normalize(stmt, self._param_resolver(stmt)) \
                if bool(FLAGS.param_queries) else None
        except Exception:   # noqa: BLE001 — display stays best-effort
            metrics.count_swallowed("session.explain_paramize")
            nz = None
        hoisted = nz.hoisted if nz is not None else 0
        pinned = nz.pinned if nz is not None else paramize._count_lits(stmt)
        trace.event("params", hoisted=hoisted, pinned=pinned,
                    param_hits_total=metrics.plan_cache_param_hits.value)
        gs = guard_stats()
        trace.event("guards", mode=gs["mode"],
                    transfer_trips=gs["transfer_trips"],
                    lock_trips=gs["lock_trips"],
                    owner_trips=gs["owner_trips"])
        # cross-query batched dispatch: whether this statement's shape is
        # served by the combiner under concurrency, plus engine-wide tick
        # telemetry (EXPLAIN ANALYZE itself always runs inline)
        from . import dispatch as _dispatch
        occ = metrics.group_occupancy.stats()
        trace.event("dispatch", enabled=_dispatch.enabled(),
                    groups_total=metrics.batched_groups.value,
                    avg_occupancy=occ["avg_ms"],
                    queue_p50_ms=metrics.queue_wait_ms.stats()["p50_ms"])
        # MPP exchange v2: shuffle rounds this plan pays, join chains fused
        # into a multiway exchange, and the adaptive-agg strategy decision
        # (local pre-reduce vs raw-row shuffle) per AggNode
        mj = [0]
        aggs: list[str] = []
        seen_x: set = set()

        def walk_x(n):
            if id(n) in seen_x:
                return
            seen_x.add(id(n))
            if isinstance(n, MultiJoinNode):
                mj[0] += 1
            if isinstance(n, AggNode) and getattr(n, "agg_dist", ""):
                aggs.append(n.agg_dist)
            for c in n.children:
                walk_x(c)

        walk_x(plan)
        xsum = (exchange_summary(plan) if self.mesh is not None
                else {"rounds": 0, "reused": 0, "collectives": 0,
                      "keys": []})
        trace.event("exchange",
                    rounds=xsum["rounds"], reused=xsum["reused"],
                    collectives=xsum["collectives"],
                    keys="[" + ",".join(xsum["keys"]) + "]",
                    multiway=mj[0], agg=",".join(aggs) or "-",
                    retries_total=metrics.shuffle_overflow_retries.value,
                    saved_total=metrics.shuffle_rounds_saved.value)

    @staticmethod
    def _render_analyze(spans: list[dict]) -> list[str]:
        """EXPLAIN ANALYZE display, rendered exclusively from the span
        records (the same ones SHOW PROFILE / trace_spans read)."""
        def find(name):
            return [s for s in spans if s["name"] == name]

        lines: list[str] = []
        for s in find("op"):
            a = s["attrs"]
            suffix = f"  rows={a['rows']}" if "rows" in a else ""
            lines.append(a["label"] + suffix)
        first = find("exec.first")
        steady = find("exec.steady")
        if first and steady:
            lines.append(f"-- run: {steady[-1]['dur_ms']:.2f} ms "
                         f"(first incl. compile: "
                         f"{first[-1]['dur_ms']:.2f} ms)")
        for s in find("batch"):
            a = s["attrs"]
            lines.append(f"-- batch: {a['table']} {a['kind']}="
                         f"{a['capacity']} live={a['live']}")
        for s in find("view"):
            a = s["attrs"]
            lines.append(f"-- view: {a['view']} "
                         f"applied_ts={a['applied_ts']} "
                         f"staleness_ms={a['staleness_ms']} "
                         f"deltas_folded={a['deltas_folded']} "
                         f"groups={a['groups']}")
        snaps = find("snapshot")
        if snaps:
            # one line per query: the pinned ts is shared; versions sum
            a0 = snaps[0]["attrs"]
            vs = sum(s["attrs"].get("versions_scanned", 0) for s in snaps)
            lines.append(f"-- snapshot: ts={a0['ts']} "
                         f"versions_scanned={vs} "
                         f"gc_watermark={a0['gc_watermark']}")
        for s in find("xla"):
            a = s["attrs"]
            lines.append(f"-- xla: retraces_total={a['retraces_total']} "
                         f"compiles={a['compiles']} "
                         f"compile_avg_ms={a['compile_avg_ms']}")
        for s in find("aot"):
            a = s["attrs"]
            lines.append(f"-- aot: enabled={a['enabled']} "
                         f"hits_total={a['hits_total']} "
                         f"misses_total={a['misses_total']} "
                         f"fallbacks_total={a['fallbacks_total']} "
                         f"publishes_total={a['publishes_total']} "
                         f"deser_avg_ms={a['deser_avg_ms']}")
        for s in find("device"):
            a = s["attrs"]
            lines.append(f"-- device: compile_ms={a['compile_ms']} "
                         f"flops={a['flops']:.0f} "
                         f"bytes={a['bytes_accessed']:.0f} "
                         f"peak_hbm={a['peak_hbm_bytes']:.0f} "
                         f"mem_source={a['source']}")
        for s in find("params"):
            a = s["attrs"]
            lines.append(f"-- params: hoisted={a['hoisted']} "
                         f"pinned={a['pinned']} "
                         f"param_hits_total={a['param_hits_total']}")
        for s in find("guards"):
            a = s["attrs"]
            lines.append(f"-- guards: mode={a['mode']} "
                         f"transfer_trips={a['transfer_trips']} "
                         f"lock_trips={a['lock_trips']} "
                         f"owner_trips={a.get('owner_trips', 0)}")
        for s in find("dispatch"):
            a = s["attrs"]
            lines.append(f"-- dispatch: enabled={int(a['enabled'])} "
                         f"groups_total={a['groups_total']} "
                         f"avg_occupancy={a['avg_occupancy']} "
                         f"queue_p50_ms={a['queue_p50_ms']}")
        for s in find("exchange"):
            a = s["attrs"]
            lines.append(f"-- exchange: rounds={a['rounds']} "
                         f"reused={a.get('reused', 0)} "
                         f"collectives={a.get('collectives', 0)} "
                         f"keys={a.get('keys', '[]')} "
                         f"multiway={a['multiway']} agg={a['agg']} "
                         f"shuffle_retries_total={a['retries_total']}")
        for s in find("stream"):
            a = s["attrs"]
            lines.append(f"-- stream: chunks={a['chunks']}/"
                         f"{a['chunks_total']} skipped={a['skipped']} "
                         f"bytes_h2d={a['bytes_h2d']} "
                         f"prefetch_wait_ms={a['prefetch_wait_ms']} "
                         f"stage_ms={a['stage_ms']} "
                         f"restarts={a['restarts']}")
        for s in find("fragments"):
            a = s["attrs"]
            lines.append(f"-- fragments: dispatched={a['dispatched']} "
                         f"local={a['local']} "
                         f"retargeted={a['retargeted']} "
                         f"partial_rows={a['partial_rows']} "
                         f"bytes_saved={a['bytes_saved']}")
        lines.append(f"-- trace: spans={len(spans)} "
                     "(SHOW PROFILE shows the same span records)")
        return lines

    def _snapshot_batch(self, table_key: str, store) -> \
            Optional[ColumnBatch]:
        """Versioned device batch at the pinned ``self._snap_ts``: the
        live image concatenated with the history versions alive at the
        snapshot, with the MVCC visibility predicate
        (storage/mvcc.visibility_mask) ANDed into the batch's sel mask —
        the versioned read stays INSIDE the jitted plan as a sel-mask, no
        host-side row filtering.  None when the resident image already
        equals the snapshot (quiet table): the caller reuses the cached
        unversioned batch, so the pin is free AND bit-identical there."""
        import jax.numpy as jnp

        from ..column.batch import bucket_capacity, pad_batch
        from ..storage.mvcc import visibility_mask

        snap = self._snap_ts
        with trace.span("mvcc.visibility", table=table_key, ts=snap):
            sv = store.snapshot_versions(snap)
            wm = self.db.mvcc.snapshots.watermark(
                self.db.mvcc.tso.last_ts())
            if sv is None:
                trace.event("snapshot", ts=snap, table=table_key,
                            versions_scanned=0, gc_watermark=wm)
                return None
            tbl, cts, dts, nver = sv
            b = ColumnBatch.from_arrow(tbl)
            mask = visibility_mask(jnp.asarray(cts), jnp.asarray(dts),
                                   jnp.int64(snap))
            b = b.and_sel(mask)
            if bool(FLAGS.batch_bucketing):
                b = pad_batch(b, bucket_capacity(
                    len(b), int(FLAGS.batch_bucket_min)))
            trace.event("snapshot", ts=snap, table=table_key,
                        versions_scanned=nver, gc_watermark=wm)
            return b

    def _collect_batches(self, plan: PlanNode):
        from ..plan.nodes import ScanNode

        batches: dict[str, ColumnBatch] = {}
        key_parts = []
        scan_count: dict[str, int] = {}
        # tables whose batch IS the store's full device image (not an
        # index-gathered subset): the only inputs host presort permutations
        # may apply to.  Tracked explicitly — with capacity bucketing the
        # padded batch length no longer equals store.num_rows, so the old
        # length comparison can't identify a full scan
        full_scan: set = set()

        def count(n: PlanNode):
            if isinstance(n, ScanNode):
                scan_count[n.table_key] = scan_count.get(n.table_key, 0) + 1
            for c in n.children:
                count(c)
        count(plan)

        # progress beats per scan staged (host-side, batch boundary — also
        # a cancellation point, so KILL lands between table loads)
        qp = progress.current()
        nscanned = [0, 0]                       # batches staged, rows seen
        qp.beat(batches_total=len(scan_count))

        def scan_beat(table_key: str, b) -> None:
            nscanned[0] += 1
            nscanned[1] += len(b)
            qp.beat(operator=f"scan {table_key}", batches_done=nscanned[0],
                    rows_done=nscanned[1])

        def walk_plan(n: PlanNode):
            if isinstance(n, ScanNode) and n.table_key not in batches:
                db, name = n.table_key.split(".", 1)
                if db == "information_schema":
                    b = ColumnBatch.from_arrow(self._info_schema_table(name))
                    if self.mesh is not None:
                        from ..parallel.mesh import shard_batch
                        b = shard_batch(b, self.mesh)
                    batches[n.table_key] = b
                    key_parts.append((n.table_key, -1, len(b)))
                    scan_beat(n.table_key, b)
                    for c in n.children:
                        walk_plan(c)
                    return
                store = self.db.stores.get(n.table_key)
                if store is None:
                    info = self.db.catalog.get_table(db, name)
                    store = self.db.stores[n.table_key] = self.db.make_store(info)
                b = None
                snapped = False
                # pinned snapshot: a table with version churn past the pin
                # stages the versioned image (replacing index-gathered
                # subsets and streamed chunk sources, which read the
                # physically-latest image); a QUIET table declines here
                # (b stays None) and keeps every fast path below — its
                # live image is the snapshot state, bit-identical
                if self._snap_ts and self.mesh is None:
                    b = self._snapshot_batch(n.table_key, store)
                    snapped = b is not None
                if b is None and \
                        self.mesh is None and scan_count[n.table_key] == 1:
                    if n.ann is not None:
                        b = self._ann_batch(n, store)
                    if b is None:
                        b = self._access_path_batch(n, db, name, store)
                if b is None:
                    if self.mesh is not None:
                        b = self._sharded_batch(n.table_key, store)
                    else:
                        # out-of-core: an eligible scan->filter->aggregate
                        # plan over a big-enough table stages a ChunkSource
                        # (chunk ids post zone-map pruning) instead of the
                        # whole table; _run_plan folds it chunk by chunk.
                        # NOT a full_scan member: presort permutations and
                        # the batched dispatcher need resident positions
                        b = self._maybe_stream_source(plan, n, store)
                        if b is None:
                            b = store.device_table_batch()
                            full_scan.add(n.table_key)
                batches[n.table_key] = b
                # snapped batches append a constant marker, NOT the ts:
                # executables are shape-keyed, and two pins at different
                # timestamps with the same shapes must share one compile
                key_parts.append(
                    (n.table_key, store.version,
                     len(batches[n.table_key])) if not snapped else
                    (n.table_key, store.version,
                     len(batches[n.table_key]), "snap"))
                scan_beat(n.table_key, b)
            for c in n.children:
                walk_plan(c)

        walk_plan(plan)

        captured = {p[0]: p[1] for p in key_parts}

        def walk_presort(n: PlanNode):
            spec = getattr(n, "presort", None)
            if spec is not None and self.mesh is None:
                n.presort_input = None
                kind, table_key, cols = spec
                store = self.db.stores.get(table_key)
                base = batches.get(table_key)
                # only when the scan input IS the full base table (an
                # index-gathered or sharded batch has different positions)
                # AND the store still sits at the version the batch was
                # captured at — a permutation computed over newer data
                # applied to an older batch would be silently unsorted
                if store is not None and base is not None and \
                        table_key in full_scan and \
                        store.version == captured.get(table_key):
                    pkey = f"__presort__{kind}|{table_key}|{','.join(cols)}"
                    if pkey not in batches:
                        import jax.numpy as jnp
                        fn = store.sort_permutation if kind == "join" \
                            else store.agg_sort_permutation
                        perm = fn(tuple(cols))
                        if store.version != captured.get(table_key):
                            perm = None     # raced a write mid-build
                        if perm is not None:
                            batches[pkey] = jnp.asarray(perm)
                    if pkey in batches:
                        n.presort_input = pkey
            for c in n.children:
                walk_presort(c)
        walk_presort(plan)
        return batches, tuple(sorted(key_parts)), full_scan

    def _access_path_batch(self, n, db: str, name: str, store):
        """IndexSelector-driven scan input (index/selector.py): a secondary
        equality gathers just the matching rows; zone maps drop whole
        regions.  Returns None for a full scan (the default batch).  The
        device program's own filter still runs — these are conservative row
        supersets, so correctness never depends on the index choice."""
        from ..index.selector import analyze_conjuncts, choose_access

        if n.pushed_filter is None:
            return None
        pf = n.pushed_filter
        subst = getattr(self, "_param_subst", None)
        if subst:
            # parameterized plan: the filter carries Param markers; the
            # access-path analysis is host-side and per-execution, so it
            # gets this execution's literal values substituted back in
            pf = paramize.substitute_params(pf, subst)
        try:
            info = self.db.catalog.get_table(db, name)
            pred = analyze_conjuncts(pf)
            access = choose_access(info, store, pred, db=self.db)
        except Exception:
            return None
        cache = getattr(self, "_access_batches", None)
        if cache is None:
            cache = self._access_batches = {}
        if access[0] == "global":
            from ..index.globalindex import backing_table_name
            _, ix_name, col, value = access
            n.access_desc = f"global_index({ix_name}:{col})"
            ck = (n.table_key, store.version, "gidx", ix_name, col, value)
            b = cache.get(ck)
            if b is None:
                bkey = f"{db}.{backing_table_name(name, ix_name)}"
                bstore = self.db.stores[bkey]
                # index-region scan -> pk values -> main-table lookup join
                # (select_manager_node.cpp:1081)
                entries = bstore.secondary_scan(col, value)
                b = ColumnBatch.from_arrow(store.lookup_by_pks(entries))
                self._evict_access(n.table_key, store.version)
                cache[ck] = b
            metrics.index_scans.add(1)
            return b
        if access[0] == "secondary":
            _, ix_name, col, value = access
            n.access_desc = f"index({ix_name}:{col})"
            ck = (n.table_key, store.version, "sec", col, value)
            b = cache.get(ck)
            if b is None:
                b = ColumnBatch.from_arrow(store.secondary_scan(col, value))
                self._evict_access(n.table_key, store.version)
                cache[ck] = b
            metrics.index_scans.add(1)
            return b
        if access[0] == "partition":
            _, parts, ptotal = access
            keep, rtotal = store.prune_parts(parts)
            if len(keep) == rtotal:
                n.access_desc = "full"
                return None         # tags unknown: nothing actually drops
            n.access_desc = (f"partition({ptotal - len(parts)}/{ptotal} "
                             f"partitions pruned)")
            ck = (n.table_key, store.version, "part", tuple(sorted(keep)))
            b = cache.get(ck)
            if b is None:
                b = ColumnBatch.from_arrow(store.regions_table(keep))
                self._evict_access(n.table_key, store.version)
                cache[ck] = b
            metrics.regions_pruned.add(rtotal - len(keep))
            return b
        if access[0] == "zonemap":
            keep, total = store.prune_regions(access[1])
            if len(keep) == total:
                n.access_desc = "full"
                return None
            n.access_desc = f"zonemap({total - len(keep)}/{total} " \
                            f"regions pruned)"
            ck = (n.table_key, store.version, "zone", tuple(keep))
            b = cache.get(ck)
            if b is None:
                b = ColumnBatch.from_arrow(store.regions_table(keep))
                self._evict_access(n.table_key, store.version)
                cache[ck] = b
            metrics.regions_pruned.add(total - len(keep))
            return b
        n.access_desc = "full"
        return None

    _ACCESS_CACHE_MAX = 16

    def _maybe_stream_source(self, plan, n, store):
        """A ChunkSource for this scan when the plan is chunk-foldable
        (exec/streaming.py) and the table clears the size gate; None keeps
        the resident path.  Host-side and per-execution, like the access
        paths — the chunk-level zone maps see this execution's literals."""
        from ..index.selector import analyze_conjuncts
        from ..storage.streamchunks import ChunkSource, chunk_set

        if not bool(FLAGS.streaming_scan) or self._sql_txn is not None:
            return None
        if store.num_rows < int(FLAGS.streaming_min_rows):
            return None
        if streaming.eligible(plan, n) is None:
            return None
        try:
            cs = chunk_set(store, n.table_key, self.db.cold_fs())
        except Exception:       # noqa: BLE001 — staging is best-effort
            metrics.count_swallowed("session.stream_stage")
            return None
        ranges = {}
        if n.pushed_filter is not None:
            pf = n.pushed_filter
            subst = getattr(self, "_param_subst", None)
            if subst:
                pf = paramize.substitute_params(pf, subst)
            try:
                ranges = analyze_conjuncts(pf).ranges
            except Exception:   # noqa: BLE001 — prune is conservative
                metrics.count_swallowed("session.stream_prune")
                ranges = {}
        keep = cs.pruned(ranges)
        n.access_desc = (f"stream({len(keep)}/{cs.n_chunks} chunks, "
                         f"{cs.capacity} rows each)")
        return ChunkSource(cs, keep)

    def _evict_access(self, table_key: str, version: int):
        """Drop access-path batches of older versions of this table, and
        cap the cache (distinct predicate literals each pin device arrays —
        unbounded growth would OOM a long-lived session)."""
        self._access_batches = {
            k: v for k, v in self._access_batches.items()
            if not (k[0] == table_key and k[1] != version)}
        while len(self._access_batches) >= self._ACCESS_CACHE_MAX:
            self._access_batches.pop(next(iter(self._access_batches)))

    def _annotate_access(self, plan: PlanNode):
        """EXPLAIN display: run IndexSelector per scan without building
        batches, so the shown choice flips with the predicates."""
        from ..index.selector import analyze_conjuncts, choose_access
        from ..plan.nodes import ScanNode

        def walk(n):
            if isinstance(n, ScanNode) and getattr(n, "ann", None):
                n.access_desc = (f"ann({n.ann[0]} "
                                 f"nprobe={int(FLAGS.ann_nprobe)})")
                return
            if isinstance(n, ScanNode) and "." in n.table_key:
                db, name = n.table_key.split(".", 1)
                store = self.db.stores.get(n.table_key)
                if store is not None and db != "information_schema":
                    try:
                        info = self.db.catalog.get_table(db, name)
                        pred = analyze_conjuncts(n.pushed_filter)
                        access = choose_access(info, store, pred, db=self.db)
                        if access[0] == "secondary":
                            n.access_desc = f"index({access[1]}:{access[2]})"
                        elif access[0] == "global":
                            n.access_desc = \
                                f"global_index({access[1]}:{access[2]})"
                        elif access[0] == "partition":
                            n.access_desc = (
                                f"partition({access[2] - len(access[1])}"
                                f"/{access[2]} partitions pruned)")
                        elif access[0] == "zonemap":
                            keep, total = store.prune_regions(access[1])
                            n.access_desc = (
                                "full" if len(keep) == total else
                                f"zonemap({total - len(keep)}/{total} "
                                f"regions pruned)")
                        else:
                            n.access_desc = "full"
                    except Exception:
                        # EXPLAIN display stays best-effort; the real scan
                        # path reports its own errors
                        metrics.count_swallowed("session.annotate_access")
            for c in n.children:
                walk(c)
        walk(plan)

    def _sharded_batch(self, table_key: str, store: TableStore) -> ColumnBatch:
        """Row-shard a table across the mesh (cached per table version) —
        the region-to-store placement analog: each mesh device holds one
        horizontal slice, padded to SPMD-equal length."""
        from ..parallel.mesh import shard_batch

        # bucket config joins the key: flipping batch_bucketing (or the
        # bucket floor) mid-session must re-shard, not serve a cached batch
        # of the other shape discipline
        ck = (table_key, store.version, bool(FLAGS.batch_bucketing),
              int(FLAGS.batch_bucket_min))
        b = self._mesh_batches.get(ck)
        if b is None:
            # drop stale versions of this table before caching the new one
            self._mesh_batches = {k: v for k, v in self._mesh_batches.items()
                                  if k[0] != table_key}
            b = shard_batch(store.device_table_batch(), self.mesh)
            self._mesh_batches[ck] = b
        return b

    def _info_schema_table(self, name: str) -> pa.Table:
        cat = self.db.catalog
        if name == "tables":
            rows = []
            for db in cat.databases():
                for t in cat.tables(db):
                    info = cat.get_table(db, t)
                    st = self.db.stores.get(f"{db}.{t}")
                    rows.append((db, t, st.num_rows if st else 0, info.version))
            return pa.table({
                "table_schema": [r[0] for r in rows],
                "table_name": [r[1] for r in rows],
                "table_rows": pa.array([r[2] for r in rows], pa.int64()),
                "version": pa.array([r[3] for r in rows], pa.int64()),
            }) if rows else _empty_info("tables")
        if name == "columns":
            rows = []
            for db in cat.databases():
                for t in cat.tables(db):
                    info = cat.get_table(db, t)
                    for f in info.schema.fields:
                        rows.append((db, t, f.name, f.ltype.value,
                                     "YES" if f.nullable else "NO"))
            return pa.table({
                "table_schema": [r[0] for r in rows],
                "table_name": [r[1] for r in rows],
                "column_name": [r[2] for r in rows],
                "data_type": [r[3] for r in rows],
                "is_nullable": [r[4] for r in rows],
            }) if rows else _empty_info("columns")
        if name == "views":
            vsnap = cat._views        # one atomic snapshot: a concurrent
            #                           DROP VIEW swaps the attr, never
            #                           mutates this dict
            rows = [(k.split(".", 1)[0], k.split(".", 1)[1], v["sql"])
                    for k, v in sorted(vsnap.items())]
            return pa.table({
                "table_schema": [r[0] for r in rows],
                "table_name": [r[1] for r in rows],
                "view_definition": [r[2] for r in rows],
            }) if rows else _empty_info("views")
        if name == "subscriptions":
            rows = self.db.cdc.describe()
            return pa.table({
                "name": [r["name"] for r in rows],
                "table_key": [r["table_key"] for r in rows],
                "internal": ["YES" if r["internal"] else "NO"
                             for r in rows],
                "acked_ts": pa.array([r["acked_ts"] for r in rows],
                                     pa.int64()),
                "cursor_lag_ms": pa.array(
                    [r["cursor_lag_ms"] for r in rows], pa.int64()),
                "events_delivered": pa.array(
                    [r["events_delivered"] for r in rows], pa.int64()),
            }) if rows else _empty_info("subscriptions")
        if name == "materialized_views":
            rows = self.db.matviews.describe()
            return pa.table({
                "table_schema": [r["database"] for r in rows],
                "view_name": [r["name"] for r in rows],
                "base_table": [r["base_table"] for r in rows],
                "definition": [r["definition"] for r in rows],
                "applied_ts": pa.array([r["applied_ts"] for r in rows],
                                       pa.int64()),
                "staleness_ms": pa.array(
                    [r["staleness_ms"] for r in rows], pa.int64()),
                "cursor_lag_ms": pa.array(
                    [r["cursor_lag_ms"] for r in rows], pa.int64()),
                "deltas_folded": pa.array(
                    [r["deltas_folded"] for r in rows], pa.int64()),
                "rescans": pa.array([r["rescans"] for r in rows],
                                    pa.int64()),
                "answered_queries": pa.array(
                    [r["answered_queries"] for r in rows], pa.int64()),
                "groups": pa.array([r["groups"] for r in rows],
                                   pa.int64()),
            }) if rows else _empty_info("materialized_views")
        if name == "partitions":
            rows = []
            for db in cat.databases():
                if db == "information_schema":
                    continue
                for t in cat.tables(db):
                    info = cat.get_table(db, t)
                    spec = (info.options or {}).get("partition")
                    if not spec:
                        continue
                    st = self.db.stores.get(f"{db}.{t}")
                    counts: dict[int, int] = {}
                    # snapshot names/uppers/counts under the store lock:
                    # ALTER ... PARTITION pops those lists in place under
                    # the same lock, and an unlocked read between the two
                    # pops would mispair bounds with names
                    import contextlib

                    with (st._lock if st is not None
                          else contextlib.nullcontext()):
                        names = list(spec.get("names", ()))
                        uppers = list(spec.get("uppers", ()))
                        if st is not None:
                            for r in st.regions:
                                counts[r.part] = counts.get(r.part, 0) \
                                    + r.num_rows
                    if spec["kind"] == "hash":
                        for i in range(int(spec["n"])):
                            rows.append((db, t, f"p{i}", "HASH",
                                         spec["column"], "",
                                         counts.get(i, 0)))
                    else:
                        for i, (nm, up) in enumerate(zip(names, uppers)):
                            rows.append((db, t, nm, "RANGE",
                                         spec["column"],
                                         "MAXVALUE" if up is None
                                         else str(up), counts.get(i, 0)))
            return pa.table({
                "table_schema": [r[0] for r in rows],
                "table_name": [r[1] for r in rows],
                "partition_name": [r[2] for r in rows],
                "partition_method": [r[3] for r in rows],
                "partition_expression": [r[4] for r in rows],
                "partition_description": [r[5] for r in rows],
                "table_rows": pa.array([r[6] for r in rows], pa.int64()),
            }) if rows else _empty_info("partitions")
        if name == "cold_segments":
            rows = []
            for key, st in list(self.db.stores.items()):  # DDL-safe snap
                tier = st.replicated
                if tier is None or not hasattr(tier, "cold_rows"):
                    continue
                db, _, tname = key.partition(".")
                if hasattr(tier, "groups"):
                    # aligned (meta, group) pairs under the tier lock: a
                    # concurrent split inserts into both lists
                    with tier._mu:
                        sources = [(m.region_id, g)
                                   for m, g in zip(tier.metas, tier.groups)]
                else:
                    sources = [(r.region_id, r)
                               for r in list(tier.regions)]
                for rid, src in sources:
                    try:       # a leaderless/unreachable region skips, it
                        #        must not fail the whole listing
                        if hasattr(tier, "groups"):
                            manifest = src.bus.nodes[
                                src.leader()].cold_manifest
                        else:
                            manifest = tier._region_manifest(src)
                    except Exception:
                        metrics.count_swallowed("session.cold_manifest")
                        continue
                    for seq, f, w in manifest:
                        rows.append((db, tname, rid, seq, f, w))
            return pa.table({
                "table_schema": [r[0] for r in rows],
                "table_name": [r[1] for r in rows],
                "region_id": pa.array([r[2] for r in rows], pa.int64()),
                "seq": pa.array([r[3] for r in rows], pa.int64()),
                "file": [r[4] for r in rows],
                "watermark": pa.array([r[5] for r in rows], pa.int64()),
            }) if rows else _empty_info("cold_segments")
        if name == "query_log":
            log = list(self.db.query_log)

            def ph(e, key):
                # per-phase wall-clock split (progress beats ride the same
                # seams as the trace spans — one timing truth with SHOW
                # PROFILE); pre-upgrade 5-tuples read as 0
                d = e[5] if len(e) > 5 else {}
                return round(float(d.get(key, 0.0)), 3)
            return pa.table({
                "query": [e[0] for e in log],
                "duration_ms": pa.array([e[1] for e in log], pa.float64()),
                "result_rows": pa.array([e[2] for e in log], pa.int64()),
                # why a slow row was slow: plan-cache outcome
                # (hit/param_hit/miss/fallback) + the capacity buckets the
                # scan batches compiled against
                "cache": [e[3] for e in log],
                "capacity_bucket": [e[4] for e in log],
                "parse_ms": pa.array([ph(e, "parse") for e in log],
                                     pa.float64()),
                "plan_ms": pa.array([ph(e, "plan") for e in log],
                                    pa.float64()),
                "exec_ms": pa.array([ph(e, "exec") for e in log],
                                    pa.float64()),
                "egress_ms": pa.array([ph(e, "egress") for e in log],
                                      pa.float64()),
                # MVCC read timestamp the query ran at (0 = unpinned);
                # pre-MVCC 6-tuples read as 0
                "snapshot_ts": pa.array(
                    [int(e[6]) if len(e) > 6 else 0 for e in log],
                    pa.int64()),
            }) if log else _empty_info("query_log")
        if name == "snapshots":
            rows = self.db.mvcc.snapshots.describe()
            return pa.table({
                "snapshot_ts": pa.array([r["snapshot_ts"] for r in rows],
                                        pa.int64()),
                "age_ms": pa.array([r["age_ms"] for r in rows],
                                   pa.int64()),
                "query": [r["query"] for r in rows],
                "holder": [r["holder"] for r in rows],
            }) if rows else _empty_info("snapshots")
        if name == "processlist":
            rows = [qp.row() for qp in PROGRESS.live(self.db)]
            rows.sort(key=lambda r: r["query_id"])
            return pa.table({
                "id": pa.array([r["id"] for r in rows], pa.int64()),
                "user": [r["user"] for r in rows],
                "host": [r["host"] for r in rows],
                "db": [r["db"] for r in rows],
                "command": [r["command"] for r in rows],
                "time_s": pa.array([r["time_s"] for r in rows], pa.int64()),
                "state": [r["state"] for r in rows],
                "info": [r["info"] for r in rows],
                "query_id": pa.array([r["query_id"] for r in rows],
                                     pa.int64()),
                "phase": [r["phase"] for r in rows],
                "operator": [r["operator"] for r in rows],
                "batches_done": pa.array([r["batches_done"] for r in rows],
                                         pa.int64()),
                "batches_total": pa.array([r["batches_total"] for r in rows],
                                          pa.int64()),
                "rows_done": pa.array([r["rows_done"] for r in rows],
                                      pa.int64()),
                "rows_est": pa.array([r["rows_est"] for r in rows],
                                     pa.int64()),
                "round": pa.array([r["round"] for r in rows], pa.int64()),
                "rounds_total": pa.array([r["rounds_total"] for r in rows],
                                         pa.int64()),
                "chunk_no": pa.array([r["chunk_no"] for r in rows],
                                     pa.int64()),
                "chunks_total": pa.array([r["chunks_total"] for r in rows],
                                         pa.int64()),
                "queue_wait_ms": pa.array([r["queue_wait_ms"] for r in rows],
                                          pa.float64()),
                "elapsed_ms": pa.array([r["elapsed_ms"] for r in rows],
                                       pa.float64()),
            }) if rows else _empty_info("processlist")
        if name == "flight_recorder":
            import json as _json
            rows = self.db.flightrec.rows()
            return pa.table({
                "rec_id": pa.array([r["rec_id"] for r in rows], pa.int64()),
                "ts": pa.array([r["ts"] for r in rows], pa.float64()),
                "query_id": pa.array([r.get("query_id", 0) for r in rows],
                                     pa.int64()),
                "conn_id": pa.array([r.get("conn_id", 0) for r in rows],
                                    pa.int64()),
                "user": [r.get("user", "") for r in rows],
                "db": [r.get("db", "") for r in rows],
                "query": [r.get("text", "") for r in rows],
                "duration_ms": pa.array([r.get("dur_ms", 0.0) for r in rows],
                                        pa.float64()),
                "status": [r.get("status", "") for r in rows],
                "error": [r.get("error", "") for r in rows],
                "phase_ms": [_json.dumps(r.get("phase_ms") or {},
                                         default=str) for r in rows],
                "rows": pa.array([r.get("rows", 0) for r in rows],
                                 pa.int64()),
                "has_bundle": pa.array([bool(r.get("bundle"))
                                        for r in rows], pa.bool_()),
            }) if rows else _empty_info("flight_recorder")
        if name == "trace_spans":
            import json as _json
            rows = []
            for rec in TRACER.list():
                for sp in rec["spans"]:
                    rows.append((rec["query_id"], rec["trace_id"],
                                 sp["span_id"], sp["parent_id"], sp["name"],
                                 sp.get("node") or "frontend",
                                 float(sp["ts_us"]), float(sp["dur_ms"]),
                                 _json.dumps(sp["attrs"], default=str)
                                 if sp["attrs"] else ""))
            return pa.table({
                "query_id": pa.array([r[0] for r in rows], pa.int64()),
                "trace_id": [r[1] for r in rows],
                "span_id": [r[2] for r in rows],
                "parent_id": [r[3] for r in rows],
                "name": [r[4] for r in rows],
                "node": [r[5] for r in rows],
                "start_us": pa.array([r[6] for r in rows], pa.float64()),
                "duration_ms": pa.array([r[7] for r in rows], pa.float64()),
                "attrs": [r[8] for r in rows],
            }) if rows else _empty_info("trace_spans")
        if name == "dispatcher":
            # live state of the cross-query batched dispatcher: queue
            # depth + in-flight, tick latency, the exact group-occupancy
            # histogram, and per-bucket qos token levels
            rows = []
            dp = getattr(self.db, "dispatcher", None)
            if dp is not None:
                snap = dp.snapshot()
                rows += [("queue", "depth", float(snap["queue_depth"]), ""),
                         ("queue", "live_groups",
                          float(snap["live_groups"]), ""),
                         ("queue", "inflight", float(snap["inflight"]), ""),
                         ("executables", "cached",
                          float(snap["compiled"]), "")]
                for size in sorted(snap["occupancy"]):
                    rows.append(("occupancy", str(size),
                                 float(snap["occupancy"][size]),
                                 "groups combined at this size"))
            tick = metrics.dispatch_tick_ms.stats()
            wait = metrics.queue_wait_ms.stats()
            rows += [("tick", k, float(tick[k]), "") for k in
                     ("count", "avg_ms", "p50_ms", "p99_ms", "max_ms")]
            rows += [("queue_wait", k, float(wait[k]), "") for k in
                     ("count", "avg_ms", "p50_ms", "p99_ms")]
            for c in ("batched_groups", "dispatch_inline",
                      "dispatch_fallbacks", "qos_rejections"):
                rows.append(("counter", c,
                             float(metrics.REGISTRY.counter(c).value), ""))
            if self.db.qos is not None:
                for kind, key, tokens, detail in self.db.qos.state():
                    rows.append((kind, key, float(tokens), detail))
            return pa.table({
                "kind": [r[0] for r in rows],
                "name": [r[1] for r in rows],
                "value": pa.array([r[2] for r in rows], pa.float64()),
                "detail": [r[3] for r in rows],
            }) if rows else _empty_info("dispatcher")
        if name == "column_stats":
            rows = []
            for db in cat.databases():
                if db == "information_schema":
                    continue
                for t in cat.tables(db):
                    st = self.db.stores.get(f"{db}.{t}")
                    if st is None:
                        continue
                    info = cat.get_table(db, t)
                    for f in info.schema.fields:
                        try:
                            s = st.column_stats(f.name) or {}
                        except Exception:   # noqa: BLE001 — stats advisory
                            metrics.count_swallowed("session.column_stats")
                            continue
                        rows.append((db, t, f.name, int(s.get("ndv") or 0),
                                     s.get("ndv_method") or "",
                                     int(s.get("nulls") or 0),
                                     int(s.get("n") or 0),
                                     len(s.get("mcv") or ()),
                                     max(0, len(s.get("hist") or ()) - 1)))
            return pa.table({
                "table_schema": [r[0] for r in rows],
                "table_name": [r[1] for r in rows],
                "column_name": [r[2] for r in rows],
                "ndv": pa.array([r[3] for r in rows], pa.int64()),
                "ndv_method": [r[4] for r in rows],
                "nulls": pa.array([r[5] for r in rows], pa.int64()),
                "row_count": pa.array([r[6] for r in rows], pa.int64()),
                "mcv_count": pa.array([r[7] for r in rows], pa.int64()),
                "hist_buckets": pa.array([r[8] for r in rows], pa.int64()),
            }) if rows else _empty_info("column_stats")
        if name == "fragments":
            from .fragments import recent_dispatches
            recs = recent_dispatches()
            return pa.table({
                "frag_key": [r["frag_key"] for r in recs],
                "table_name": [r["table"] for r in recs],
                "mode": [r["mode"] for r in recs],
                "dispatched": pa.array([r["dispatched"] for r in recs],
                                       pa.int64()),
                "local": pa.array([r["local"] for r in recs], pa.int64()),
                "retargeted": pa.array([r["retargeted"] for r in recs],
                                       pa.int64()),
                "partial_rows": pa.array([r["partial_rows"] for r in recs],
                                         pa.int64()),
                "scanned": pa.array([r["scanned"] for r in recs],
                                    pa.int64()),
                "bytes_saved": pa.array([r["bytes_saved"] for r in recs],
                                        pa.int64()),
                "status": [r["status"] for r in recs],
            }) if recs else _empty_info("fragments")
        if name == "failpoints":
            from ..chaos import failpoint as _fp
            rows = _fp.describe()
            return pa.table({
                "name": [r[0] for r in rows],
                "spec": [r[2] for r in rows],
                "hits": pa.array([r[3] for r in rows], pa.int64()),
                "trips": pa.array([r[4] for r in rows], pa.int64()),
                "site": [r[1] for r in rows],
            }) if rows else _empty_info("failpoints")
        if name == "metrics":
            rows = [(mname, k, float(v))
                    for mname, st in metrics.REGISTRY.expose().items()
                    for k, v in st.items() if v is not None]
            return pa.table({
                "name": [r[0] for r in rows],
                "field": [r[1] for r in rows],
                "value": pa.array([r[2] for r in rows], pa.float64()),
            }) if rows else _empty_info("metrics")
        if name == "cluster_metrics":
            # the fleet telemetry plane: this frontend's registry plus
            # every registered daemon's last rpc_metrics snapshot, merged
            # under daemon='fleet' (counters sum, histograms bucket-wise);
            # a daemon whose scrape failed keeps its last rows, stale=1
            rows = self.db.telemetry.cluster_rows()
            return pa.table({
                "daemon": [r[0] for r in rows],
                "metric": [r[1] for r in rows],
                "labels": [r[2] for r in rows],
                "field": [r[3] for r in rows],
                "value": pa.array([r[4] for r in rows], pa.float64()),
                "stale": pa.array([int(r[5]) for r in rows], pa.int64()),
                "age_ms": pa.array([round(float(r[6]), 3) for r in rows],
                                   pa.float64()),
            }) if rows else _empty_info("cluster_metrics")
        if name == "executables":
            # device-resource accounting: what each cached executable costs
            # the accelerator (cost/memory analysis fills lazily here)
            ex = compilecache.EXECUTABLES.rows()
            return pa.table({
                "statement": [r["statement"] for r in ex],
                "kind": [r["kind"] for r in ex],
                "plan_sig": [r["plan_sig"] for r in ex],
                "shape": [r["shape"] for r in ex],
                "compiles": pa.array([r["compiles"] for r in ex],
                                     pa.int64()),
                "compile_ms_total": pa.array(
                    [r["compile_ms_total"] for r in ex], pa.float64()),
                "last_compile_ms": pa.array(
                    [r["last_compile_ms"] for r in ex], pa.float64()),
                "flops": pa.array([r["flops"] for r in ex], pa.float64()),
                "bytes_accessed": pa.array(
                    [r["bytes_accessed"] for r in ex], pa.float64()),
                "peak_hbm_bytes": pa.array(
                    [r["peak_hbm_bytes"] for r in ex], pa.float64()),
                "argument_bytes": pa.array(
                    [r["argument_bytes"] for r in ex], pa.float64()),
                "output_bytes": pa.array(
                    [r["output_bytes"] for r in ex], pa.float64()),
                "mem_source": [r["mem_source"] for r in ex],
            }) if ex else _empty_info("executables")
        if name == "aot_cache":
            # the persistent executable tier: what survives a restart
            # (disk artifacts) and what this process did with it
            # (hits / sources / deserialization cost)
            rows = compilecache.AOT.rows()
            return pa.table({
                "key": [r["key"] for r in rows],
                "kind": [r["kind"] for r in rows],
                "statement": [r["statement"] for r in rows],
                "plan_sig": [r["plan_sig"] for r in rows],
                "size_bytes": pa.array([r["size_bytes"] for r in rows],
                                       pa.int64()),
                "jax_version": [r["jax_version"] for r in rows],
                "created_at": [r["created_at"] for r in rows],
                "source": [r["source"] for r in rows],
                "hits": pa.array([r["hits"] for r in rows], pa.int64()),
                "deser_ms": pa.array([r["deser_ms"] for r in rows],
                                     pa.float64()),
                "status": [r["status"] for r in rows],
            }) if rows else _empty_info("aot_cache")
        if name == "flags":
            rows = FLAGS.describe()
            return pa.table({
                "name": [r[0] for r in rows],
                "value": [str(r[1]) for r in rows],
                "default_value": [str(r[2]) for r in rows],
                "help": [r[3] for r in rows],
            }) if rows else _empty_info("flags")
        if name == "regions":
            fleet = self.db.fleet
            if fleet is None:
                return _empty_info("regions")
            # table_id -> table name via the registered row tiers; regions
            # whose tier is gone (or was never materialized through a tier)
            # fall back to the numeric id
            names = {t.table_id: t.table_key
                     for t in fleet.row_tiers.values()}
            rms = sorted(fleet.meta.regions.values(),
                         key=lambda r: r.region_id)
            return pa.table({
                "region_id": pa.array([r.region_id for r in rms],
                                      pa.int64()),
                "table_name": [names.get(r.table_id, str(r.table_id))
                               for r in rms],
                "start_key": [r.start_key for r in rms],
                "end_key": [r.end_key for r in rms],
                "peers": [",".join(r.peers) for r in rms],
                "learners": [",".join(r.learners) for r in rms],
                "leader": [r.leader for r in rms],
                "state": [r.state for r in rms],
                "version": pa.array([r.version for r in rms], pa.int64()),
                "num_rows": pa.array([r.num_rows for r in rms], pa.int64()),
                "apply_lag": pa.array([r.apply_lag for r in rms],
                                      pa.int64()),
                "proposal_queue": pa.array([r.proposal_queue for r in rms],
                                           pa.int64()),
                "write_rate": pa.array([r.write_rate for r in rms],
                                       pa.int64()),
            }) if rms else _empty_info("regions")
        if name == "ddl_work":
            ws = list(self.db.ddl.works.values())
            return pa.table({
                "work_id": [w.work_id for w in ws],
                "table_name": [w.table_key for w in ws],
                "index_name": [w.index_name for w in ws],
                "kind": [w.kind for w in ws],
                "state": [w.state for w in ws],
                "regions_done": [w.regions_done for w in ws],
                "regions_total": [w.regions_total for w in ws],
                "error": [w.error for w in ws],
            }) if ws else _empty_info("ddl_work")
        raise PlanError(f"unknown information_schema table {name!r}")

    def _maybe_batched_run(self, entry: dict, batches: dict, shape_key,
                           norm, lookup_key, full_scan) -> ColumnBatch:
        """Route through the cross-query batched dispatcher when this query
        is groupable; otherwise (and for every bypass/fallback) run the
        session's own inline ``_run_plan``."""
        from . import dispatch

        def inline():
            return self._run_plan(entry, batches, shape_key)

        if norm is None or self.mesh is not None \
                or self._sql_txn is not None or not dispatch.enabled():
            return inline()
        # groupability: every scan input must be the table's full device
        # image at a real version — index/ANN-gathered batches are
        # literal-dependent (two members' same-shaped inputs would hold
        # DIFFERENT rows), information_schema (version -1) renders fresh
        # per call, and host presort permutations are per-plan-object state
        for tk, v, *_rest in shape_key:
            if v < 0 or tk not in full_scan:
                return inline()
        if any(k.startswith("__presort__") for k in batches):
            return inline()
        # members coalesce on (statement structure + pinned values, scan
        # shapes at exact versions, plan signature): they differ only in
        # their bound param feeds.  The compile key drops versions so DML
        # inside one capacity bucket reuses the batched executable, but
        # keeps the plan signature — a stats-driven replan must compile
        # its own batched variant, never execute a structurally different
        # stored plan.
        group_key = (lookup_key, shape_key, entry["plan_sig"])
        ck_base = (lookup_key, entry["plan_sig"],
                   tuple((p[0],) + tuple(p[2:]) for p in shape_key),
                   int(FLAGS.radix_join_buckets),
                   int(FLAGS.radix_join_min_build))
        try:
            return self.db.dispatcher.run(inline, group_key, ck_base,
                                          entry, batches)
        except dispatch.CombineFallback:    # belt: never escapes normally
            metrics.dispatch_fallbacks.add(1)
            return inline()

    def _run_plan(self, entry: dict, batches: dict, shape_key) -> ColumnBatch:
        plan = entry["plan"]
        if streaming.stream_source(batches) is not None:
            # out-of-core path: the scan staged a ChunkSource, so this
            # execution is a chunk fold driven from the host
            # (exec/streaming.py), not one jitted program over resident
            # batches — none of the executable caching below applies
            out = streaming.run_streamed(self, entry, batches,
                                         progress.current())
            with trace.span("egress.compact"):
                return self._egress_compact(out)
        # a plan with no scans has no sharded state (distribute leaves it
        # fully replicated) — run it as a plain single-device program
        mesh = self.mesh if batches else None
        # executables key on per-table (table_key, capacity bucket) — NOT
        # the store version: a version bump whose row count stays inside the
        # capacity bucket reuses the executable outright (version gates plan
        # staleness in _select; shape gates compilation here).  Trace-time
        # execution flags join the key: flipping SET GLOBAL
        # radix_join_buckets must re-trace, not silently reuse an executable
        # compiled under the other strategy
        versions_key = tuple((p[0], p[1]) for p in shape_key)
        # snapped batches keep their "snap" marker in the compile key: the
        # versioned staging can change the batch's pytree structure vs the
        # cached resident image at the same capacity
        shape_key = (tuple((p[0],) + tuple(p[2:]) for p in shape_key),
                     int(FLAGS.radix_join_buckets),
                     int(FLAGS.radix_join_min_build))

        # AOT persistent tier (utils/compilecache.AOT): the artifact key
        # adds the input pytree skeleton (incl. dictionary content) + jax
        # version + topology to the shape key, so a hit is exactly "the
        # program this compile would produce".  Derived LAZILY — only on a
        # shape-cache miss or at publish time — so the steady-state hot
        # path never pays the fingerprint walk.
        aot_key = None

        # progress: planned shuffle rounds are the mesh query's round
        # denominator (cached on the entry — one plan walk per entry life);
        # the summary also feeds the flight-recorder bundle
        qp = progress.current()
        if mesh is not None:
            summary = entry.get("exchange_summary")
            if summary is None:
                summary = entry["exchange_summary"] = exchange_summary(plan)
            qp.beat(rounds_total=int(summary["rounds"]), round_no=0,
                    exchange=summary)

        def get_aot_key():
            nonlocal aot_key
            if aot_key is None and compilecache.AOT.enabled():
                sig = entry.get("plan_sig")
                if sig is None:
                    sig = entry["plan_sig"] = plan_signature(plan)
                aot_key = compilecache.aot_key(
                    "plan", sig, shape_key,
                    compilecache.input_fingerprint(batches), mesh)
            return aot_key

        compiled_here = False
        for _ in range(int(FLAGS.join_retry_max) + 1):
            # overflow-retry boundary: between device programs, no side
            # effects yet — a KILL lands here instead of paying another
            # trace+compile+run of the whole plan
            qp.checkpoint()
            pair = entry["compiled"].get(shape_key)
            if pair is not None and len(pair) == 3 \
                    and pair[2] != versions_key:
                # an AOT pair is pinned to the EXACT store versions it
                # loaded under: unlike jit (which keys on pytree aux and
                # silently retraces when a dictionary's content changes),
                # a deserialized program cannot notice that its baked
                # string dictionaries went stale.  Any DML — even inside
                # the capacity bucket — re-derives the artifact key; an
                # unchanged input skeleton re-hits the same artifact, a
                # changed dictionary is a clean miss
                entry["compiled"].pop(shape_key, None)
                pair = None
            if pair is None and compilecache.AOT.enabled() \
                    and shape_key not in entry.get("aot_bad", ()) \
                    and get_aot_key() is not None:
                art = compilecache.AOT.load(aot_key, mesh=mesh)
                if art is not None:
                    # no trace, no compile: the deserialized program runs
                    # with its settled caps baked in; the shim feeds the
                    # overflow loop below from the artifact's flag meta
                    pair = (art.run,
                            executor.AotRawShim(art.flag_meta),
                            versions_key)
                    entry["compiled"][shape_key] = pair
            if pair is None:
                raw = compile_plan(plan, mesh=mesh)
                # not a per-iteration wrapper: built only on a shape-cache
                # miss and cached in entry["compiled"] keyed by shape_key.
                # The final compact stays EAGER (outside the jit): its
                # partition scatter is expensive to compile, and the eager
                # op cache pays that once per capacity shape process-wide
                # instead of once per cached executable
                pair = (jax.jit(raw), raw)  # tpulint: disable=RETRACE
                comp = entry["compiled"]
                # distinct shapes (bucket crossings, access-path batches)
                # each pin an executable; without a cap one hot query would
                # pin every executable it ever compiled
                while len(comp) >= max(1, int(FLAGS.plan_cache_shapes)):
                    comp.pop(next(iter(comp)))
                comp[shape_key] = pair
            fn, raw = pair[0], pair[1]
            traces_before = raw.trace_count[0]
            t0 = time.perf_counter()
            # debug_guards: no implicit device->host transfer may hide in
            # the compiled path; the explicit flag egress happens below,
            # OUTSIDE the guard scope.  The span wraps the dispatch from
            # the HOST side — spans inside the traced fn would bake into
            # the program (tpulint SPANINJIT)
            with trace.span("exec.run") as sp:
                with hot_path_guard():
                    out, flags = fn(batches)
                if raw.trace_count[0] > traces_before:
                    # this execution paid a trace+compile (first run /
                    # bucket crossing / overflow retry): record it so
                    # first-run vs steady-state shows up in SHOW metrics
                    # and the trace vs execute split shows in the span
                    cms = (time.perf_counter() - t0) * 1e3
                    metrics.compile_ms.observe(cms)
                    sp.set(compiled=True)
                    compiled_here = True
                    # device-resource accounting (compile seam): the cost/
                    # memory analysis itself is LAZY — only the identity,
                    # wall-ms, and the arg shape skeleton record here
                    if compilecache.EXECUTABLES.enabled():
                        sig = entry.get("plan_sig")
                        if sig is None:
                            sig = entry["plan_sig"] = plan_signature(plan)
                        compilecache.EXECUTABLES.record_compile(
                            "plan", entry.get("text") or "<unnamed>", sig,
                            ";".join(f"{p[0]}={p[1]}"
                                     for p in shape_key[0]),
                            cms, fn, (batches,))
            grew = False
            # ONE explicit transfer for every overflow flag: int(flag) per
            # join would block on a device round-trip once per node
            # (tpulint HOSTSYNC)
            host_flags = jax.device_get(flags)
            if mesh is not None:
                # the one device program carried every planned collective:
                # all rounds are behind us once the flags landed on host
                qp.beat(round_no=int(qp.rounds_total)
                        if qp.query_id else 0)
            for node, flag in zip(raw.join_order, host_flags):
                needed = int(flag)
                if isinstance(node, ScalarSourceNode) \
                        or getattr(node, "aot_scalar", False):
                    if needed > 1:
                        raise PlanError("Subquery returns more than 1 row")
                    continue
                if needed > (node.cap or 0):
                    # flags carry the exact required capacity (join output
                    # cardinality / max shuffle-bucket size): jump straight
                    # there (padded to a power of two so repeated runs with
                    # slightly different data reuse the compiled executable)
                    node.cap = max(16, 1 << (needed - 1).bit_length())
                    grew = True
                    if mesh is not None and (
                            isinstance(node, ExchangeNode)
                            or (isinstance(node, _CapBox)
                                and node.kind == "shuffle")):
                        # a skewed key blew past the per-destination
                        # shuffle capacity — the exchange backpressure
                        # analog, worth its own counter
                        metrics.shuffle_overflow_retries.add(1)
            if grew and isinstance(raw, executor.AotRawShim):
                # live data outgrew the artifact's baked capacities: an
                # exported program cannot re-trace, so this shape compiles
                # from scratch (and never re-loads the undersized artifact
                # in this entry's lifetime)
                entry.setdefault("aot_bad", set()).add(shape_key)
                metrics.aot_cache_fallbacks.add(1)
                entry["compiled"].pop(shape_key, None)
                continue
            if not grew:
                if compiled_here and not isinstance(raw, executor.AotRawShim) \
                        and get_aot_key() is not None:
                    # settled executable: hand it to the background
                    # publisher (export + verify + disk + peer); the query
                    # path never waits on it.  The publisher re-traces on
                    # its own thread, so it gets a FRESH compile_plan
                    # closure — tracing the live `raw` would mutate the
                    # join_order/trace_order lists a concurrent execution
                    # of this entry is reading
                    compilecache.AOT.publish_async(
                        aot_key, "plan",
                        str(entry.get("text") or "<unnamed>"),
                        entry.get("plan_sig"),
                        compile_plan(plan, mesh=mesh), batches,
                        (out, flags),
                        executor.flag_meta_of(raw.join_order), mesh=mesh)
                if mesh is not None:
                    self._mpp_telemetry(plan, entry, raw.join_order,
                                        host_flags)
                with trace.span("egress.compact"):
                    return self._egress_compact(out)
            entry["compiled"].pop(shape_key, None)  # caps changed: re-trace
        raise RuntimeError("join output cap still overflowing after retries")

    def _mpp_telemetry(self, plan, entry: dict, join_order,
                       host_flags) -> None:
        """Per-execution exchange observability for mesh plans: the
        shuffle_rounds counter plus mpp.repartition / mpp.join / mpp.agg
        spans with occupancy/overflow/strategy attrs.  Pure host work on
        the already-fetched flag values — no extra device sync."""
        summary = entry.get("exchange_summary")
        if summary is None:
            summary = entry["exchange_summary"] = exchange_summary(plan)
        metrics.shuffle_rounds.add(summary["rounds"])
        if summary["reused"]:
            # keyed exchange scheduler: collectives this execution did NOT
            # pay because an input was already partitioned on the key class
            metrics.shuffle_rounds_saved.add(summary["reused"])
        if not trace.active():
            # tracing off: the counter above is the whole cost — no plan
            # walk, no per-node span churn on the hot path
            return
        for node, flag in zip(join_order, host_flags):
            needed = int(flag)
            if isinstance(node, ExchangeNode) and node.kind == "repartition":
                with trace.span("mpp.repartition",
                                keys=",".join(node.keys or ()),
                                cap=int(node.cap or 0), occupancy=needed):
                    pass
            elif isinstance(node, _CapBox) and node.kind == "shuffle":
                with trace.span("mpp.repartition", site=node.site,
                                cap=int(node.cap or 0), occupancy=needed):
                    pass
            elif isinstance(node, MultiJoinNode):
                with trace.span("mpp.join", strategy="multiway",
                                builds=len(node.children) - 1, rows=needed,
                                cap=int(node.cap or 0)):
                    pass
            elif isinstance(node, JoinNode) and any(
                    isinstance(c, ExchangeNode) and c.kind == "repartition"
                    for c in node.children):
                with trace.span("mpp.join", strategy="chained", rows=needed,
                                cap=int(node.cap or 0)):
                    pass

        seen: set = set()

        def walk(n):
            if id(n) in seen:
                return
            seen.add(id(n))
            if isinstance(n, AggNode) and getattr(n, "agg_dist", ""):
                with trace.span("mpp.agg", strategy=n.agg_dist,
                                agg_kind=n.strategy):
                    pass
            for c in n.children:
                walk(c)

        walk(plan)

    def _egress_compact(self, batch: ColumnBatch) -> ColumnBatch:
        """Densify the finished result for egress, O(live) not O(capacity).

        The generic compact permutes every lane of the batch — for a
        selective point read that is a full-capacity scatter+gather to
        surface a handful of rows, and it dominated steady-state latency.
        Egress is the sanctioned sync point, so fetch the (scalar) live
        count first and gather just the live indices into a pow2-padded
        batch: the eager nonzero/gather kernels cache per (capacity, cap)
        pair, and num_rows trims the padding at to_arrow time."""
        import jax.numpy as jnp

        if batch.sel is None or batch.live_prefix or len(batch) == 0:
            return compact(batch)
        sel = batch.sel_mask()
        cs = jnp.cumsum(sel.astype(jnp.int32))
        n = int(jax.device_get(cs[-1]))         # egress: one scalar fetch
        cap = min(len(batch), max(16, 1 << max(0, n - 1).bit_length()))
        # index of the k-th live row = first i with cumsum[i] >= k; a
        # vectorized binary search, not jnp.nonzero (whose CPU lowering is
        # an order of magnitude slower at this capacity)
        idx = jnp.searchsorted(cs, jnp.arange(1, cap + 1, dtype=jnp.int32))
        out = batch.gather(jnp.clip(idx, 0, len(batch) - 1))
        out.num_rows = jnp.asarray(n, jnp.int32)
        out.sel = jnp.arange(cap) < n
        return out
